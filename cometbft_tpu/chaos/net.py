"""ChaosNet: an N-node in-process network under a seeded fault plane.

Builds full Nodes (node/node.py) over MemoryTransport with a LinkTable
installed as the transport's link hook, runs a declarative fault
schedule through the Nemesis, and checks the BFT invariants
(chaos/invariants.py) continuously and at end-of-run. Nodes get real
home directories (sqlite stores + consensus WAL) so in-process
crash/restart recovers through the same WAL-replay + ABCI
handshake-replay path a real power cut exercises.

Entry point: ``run_schedule`` (awaitable) -> ChaosReport. On any
violation the report carries the seed, the executed fault trace and
the per-link decision counts — everything needed to replay the run.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import types as T
from ..config.config import test_config
from ..node.inprocess import make_genesis
from ..node.node import Node
from ..p2p import MemoryTransport, NodeInfo, NodeKey
from ..store.block_store import _hkey
from ..trace import global_tracer, write_chrome, write_jsonl
from ..trace import rebase as timeline_rebase
from ..utils.log import get_logger
from ..utils.tasks import spawn
from .invariants import (
    AgreementChecker,
    InvariantViolation,
    WALReplayChecker,
    liveness_violation,
)
from .links import LinkTable
from .nemesis import Nemesis
from .schedule import FaultSchedule

_log = get_logger("chaos")

POLL_S = 0.05


@dataclass
class ChaosNode:
    idx: int
    name: str
    node_key: NodeKey
    privval: object
    home: str
    node: Optional[Node] = None  # None while crashed
    # one tracer per incarnation (restarts build a fresh ring); kept
    # here so a crashed node's timeline survives for the dump
    tracers: List[object] = field(default_factory=list)
    # likewise one loop watchdog per incarnation: its flight records
    # (loop-stall snapshots) outlive the crash for the report
    watchdogs: List[object] = field(default_factory=list)
    # bounded-shutdown breaches (obs/shutdown.py flight records)
    # across every incarnation's stop/kill
    shutdown_stalls: List[dict] = field(default_factory=list)
    # per-node Config mutations applied on the NEXT build (restart
    # variants: adaptive-sync catchup re-enables blocksync)
    build_overrides: Dict[str, object] = field(default_factory=dict)

    @property
    def node_id(self) -> str:
        return self.node_key.node_id

    @property
    def running(self) -> bool:
        return self.node is not None


@dataclass
class ChaosReport:
    seed: int
    schedule_json: str
    trace: List[dict] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    final_heights: Dict[str, int] = field(default_factory=dict)
    link_decisions: Dict[str, Dict[str, int]] = field(default_factory=dict)
    wal_checks: int = 0
    trace_files: List[str] = field(default_factory=list)
    # runtime health plane (obs/, docs/OBS.md)
    stall_records: List[dict] = field(default_factory=list)
    budget_verdicts: List[dict] = field(default_factory=list)
    profile_file: str = ""
    # scenario-factory planes (docs/CHAOS.md "Scenario factory")
    workload: Dict[str, object] = field(default_factory=dict)
    shutdown_stalls: List[dict] = field(default_factory=list)
    # structural fingerprint: proposer address (hex, short) per
    # committed height on the most advanced node — the same-seed
    # determinism surface (heights/proposers reproduce; wall-clock
    # latencies do not). ``rounds`` records each height's commit
    # round: proposer rotation is a pure function of (valset, height,
    # round HISTORY), and round counts are the one wall-clock-coupled
    # input (a round times out when its proposer is mid-crash/restart
    # on a contended box) — so same-seed comparisons assert proposers
    # over the prefix where the round histories still agree.
    proposers: Dict[int, str] = field(default_factory=dict)
    rounds: Dict[int, int] = field(default_factory=dict)
    # self-healing connectivity plane (docs/CHAOS.md): dials that
    # failed into the reconnect plane + injected conn kills
    dial_failures: int = 0
    conns_killed: int = 0
    # light-client serving storm against a live node (ISSUE 13;
    # --light-storm N): session/latency/cache stats, or empty when
    # the leg did not run
    light_storm: Dict[str, object] = field(default_factory=dict)
    # websocket subscriber storm against a live node's fan-out plane
    # (ISSUE 15; --subscriber-storm N): delivery/encode/shed stats
    subscriber_storm: Dict[str, object] = field(default_factory=dict)
    # serving-fleet leg (ISSUE 19; run_schedule(fleet=N) or a
    # scheduled replica_kill): per-replica status, failover/shed
    # counters and the lag-shed isolation probe verdict
    fleet: Dict[str, object] = field(default_factory=dict)
    # runtime concurrency sanitizer (analysis/runtime.py): every
    # finding the per-process sanitizer recorded during the run.
    # Un-injected findings also land in ``violations`` (the matrix
    # hunts races for free); findings from a scheduled
    # lock_inversion are EXPECTED and stay here only.
    sanitizer_findings: List[dict] = field(default_factory=list)
    # committee-scaling probe (analysis/scaling.py): every site the
    # scheduled scaling_probe fault measured, with fitted exponent
    # vs budget. Un-injected breaches also land in ``violations``;
    # a planted (``chaos.``-prefixed) quadratic site breaching is
    # EXPECTED and stays here only.
    scaling_results: List[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def budget_ok(self) -> bool:
        """Span budgets hold (vacuously true when not evaluated).
        Separate from ``ok``: a budget breach is a perf regression
        gate, not a BFT invariant violation."""
        return all(v["ok"] for v in self.budget_verdicts)

    def format(self) -> str:
        lines = [
            f"chaos run seed={self.seed}: "
            + ("OK" if self.ok else "INVARIANT VIOLATIONS"),
            f"final heights: {self.final_heights}",
            f"wal replay checks: {self.wal_checks}",
            "fault trace:",
        ]
        for t in self.trace:
            lines.append(f"  {t}")
        if self.link_decisions:
            lines.append("link decisions (P=partition-drop L=loss "
                         "2=dup R=reorder .=pass):")
            for link, counts in self.link_decisions.items():
                lines.append(f"  {link}: {counts}")
        for v in self.violations:
            lines.append(f"VIOLATION: {v}")
        for f in self.sanitizer_findings:
            lines.append(
                f"sanitizer[{f.get('kind')}]: {f.get('message')}"
            )
        for r in self.scaling_results:
            lines.append(
                f"scaling[{r.get('site')}]: exponent "
                f"{r.get('exponent')} vs budget {r.get('budget')} "
                + ("OK" if r.get("ok") else "OVER")
                + (" (injected)" if r.get("injected") else "")
            )
        if self.workload:
            lines.append(f"workload: {self.workload}")
        if self.light_storm:
            ls = self.light_storm
            lines.append(
                f"light serving storm: {ls.get('sessions')} sessions "
                f"against {ls.get('target_node')} (top height "
                f"{ls.get('top_height')}), request p50 "
                f"{ls.get('p50_ms')}ms p99 {ls.get('p99_ms')}ms, "
                f"cache {ls.get('plane', {}).get('cache', {})}"
            )
        if self.subscriber_storm:
            ss = self.subscriber_storm
            lines.append(
                f"subscriber storm: {ss.get('subscribers')} websocket "
                f"subscribers on {ss.get('target_node')} — "
                f"{ss.get('delivered')} frames from "
                f"{ss.get('encodes')} serializations, "
                f"{ss.get('dropped')} shed, parity "
                + ("OK" if ss.get("parity_ok") else "BROKEN")
            )
        if self.fleet:
            fl = self.fleet
            lp = fl.get("lag_probe") or {}
            lines.append(
                f"serving fleet: {len(fl.get('replicas', []))} "
                f"replicas, {fl.get('sessions')} sessions, "
                f"killed {fl.get('killed')}, "
                f"{fl.get('failovers')} failovers / "
                f"{fl.get('sessions_resumed')} resumed, sheds "
                f"{fl.get('sheds')}, "
                f"{fl.get('delivered_frames')} frames"
                + (
                    f"; lag probe on {lp.get('victim')}: degraded="
                    f"{lp.get('degraded')} recovered="
                    f"{lp.get('recovered')}"
                    if lp
                    else ""
                )
            )
        if self.dial_failures or self.conns_killed:
            lines.append(
                "connectivity plane: "
                f"{self.dial_failures} failed dials handed to "
                f"reconnect, {self.conns_killed} conns killed by "
                "injection"
            )
        if self.shutdown_stalls:
            lines.append(
                "bounded-shutdown breaches flight-recorded: "
                f"{len(self.shutdown_stalls)}"
            )
            for r in self.shutdown_stalls[:8]:
                lines.append(
                    f"  {r.get('node')}: stage {r.get('stage')} "
                    f"exceeded {r.get('waited_s')}s"
                )
        if self.stall_records:
            lines.append(
                f"loop stalls flight-recorded: {len(self.stall_records)}"
            )
            for r in self.stall_records[:8]:
                top = " <- ".join(r.get("loop_stack", [])[:3])
                lines.append(
                    f"  {r.get('node')}: {r.get('stalled_s')}s at {top}"
                )
        if self.budget_verdicts:
            from ..obs.budget import format_verdicts

            lines.append("span budgets (docs/OBS.md):")
            lines.extend(
                "  " + ln
                for ln in format_verdicts(self.budget_verdicts).splitlines()
            )
        if self.profile_file:
            lines.append(f"sampling profile: {self.profile_file}")
        if self.trace_files:
            lines.append("node trace rings (docs/TRACE.md):")
            for p in self.trace_files:
                lines.append(f"  {p}")
            lines.append(
                "  summarize: python -m cometbft_tpu.trace summarize "
                + os.path.dirname(self.trace_files[0])
            )
        if not self.ok:
            lines.append(
                "replay: python -m cometbft_tpu.chaos --seed "
                f"{self.seed} --schedule <saved schedule json>"
            )
        return "\n".join(lines)


class ChaosNet:
    def __init__(
        self,
        n_nodes: int,
        seed: int,
        base_dir: str,
        table: Optional[LinkTable] = None,
        config_hook=None,
        enable_rpc: bool = False,
    ):
        self.seed = seed
        self.base_dir = base_dir
        # optional Config mutator applied to every node build — chaos
        # runs can pin feature knobs (e.g. mempool.async_recheck)
        # without forking the harness
        self.config_hook = config_hook
        # statesync_join needs real RPC listeners (the light-client
        # state provider bootstraps over HTTP); everything else keeps
        # them off — invariants read stores directly
        self.enable_rpc = enable_rpc
        self.table = table or LinkTable(seed)
        self.genesis, pvs = make_genesis(
            n_nodes, chain_id=f"chaos-{seed}"
        )
        self.nodes: List[ChaosNode] = []
        for i, pv in enumerate(pvs):
            home = os.path.join(base_dir, f"n{i}")
            os.makedirs(home, exist_ok=True)
            self.nodes.append(
                ChaosNode(i, f"n{i}", NodeKey.generate(), pv, home)
            )
        self.agreement = AgreementChecker()
        self.wal_checker = WALReplayChecker()
        self._snapshots: Dict[int, Dict[int, bytes]] = {}
        self._byz_tasks: List[asyncio.Future] = []
        self.stop_guard = None
        # self-healing plane telemetry: failed dials routed to the
        # reconnect plane + conns killed by pong-timeout injection
        self.dial_failures = 0
        self.conns_killed = 0
        # serving-fleet harness (FleetHarness) attached by
        # run_schedule(fleet=N); replica_kill dispatches through it
        self.fleet_harness: Optional["FleetHarness"] = None

    # --- node lifecycle -----------------------------------------------

    def _build(self, cn: ChaosNode) -> Node:
        cfg = test_config(cn.home)
        cfg.base.moniker = cn.name
        cfg.base.db_backend = "sqlite"  # restart needs persistence
        if not self.enable_rpc:
            cfg.rpc.laddr = ""  # invariants read stores directly
        cfg.blocksync.enable = False
        cfg.p2p.pex = False
        # determinism pin: the WAL group-commit router keys on
        # MEASURED fsync walls (load-dependent), but a chaos run's
        # structure must be a pure function of its seed — the seam
        # stays off here unless the run opts in (matrix --fastpath's
        # config_hook re-enables it, under the fixed fsync model)
        cfg.consensus.wal_group_commit_ms = 0.0
        if self.config_hook is not None:
            self.config_hook(cfg)
        for dotted, value in cn.build_overrides.items():
            section, field_ = dotted.split(".", 1)
            setattr(getattr(cfg, section), field_, value)
        info = NodeInfo(
            node_id=cn.node_id,
            network=self.genesis.chain_id,
            moniker=cn.name,
        )
        transport = MemoryTransport(
            cn.node_key, info, link_hook=self.table
        )
        return Node(
            cfg,
            self.genesis,
            privval=cn.privval,
            node_key=cn.node_key,
            transport=transport,
            home=cn.home,
        )

    @staticmethod
    def _track(cn: ChaosNode) -> None:
        """Keep diagnostics handles that must survive a crash."""
        cn.tracers.append(cn.node.parts.tracer)
        if cn.node.loop_watchdog is not None:
            cn.watchdogs.append(cn.node.loop_watchdog)

    async def start(self) -> None:
        for cn in self.nodes:
            cn.node = self._build(cn)
            self._track(cn)
            await cn.node.start()
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                await self._dial(a, b)
        # wait for the full mesh
        for cn in self.nodes:
            for _ in range(200):
                if cn.node.switch.num_peers() >= len(self.nodes) - 1:
                    break
                await asyncio.sleep(POLL_S)

    async def _dial(self, a: ChaosNode, b: ChaosNode) -> None:
        try:
            await a.node.dial(
                f"{b.node_id}@mem://{b.node_id}", persistent=True
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # partitioned/crashed target: the failed PERSISTENT dial
            # was handed to the self-healing reconnect plane inside
            # dial_peer (p2p/reconnect.py note_dial_failure) — verify
            # that handoff instead of trusting a comment, and count
            # the failure for the report. schedule() legitimately
            # no-ops when the peer is ALREADY connected (an inbound
            # conn raced this failing dial) or banned — only the
            # none-of-the-above case is a dropped retry.
            self.dial_failures += 1
            sw = a.node.switch
            if not (
                sw.reconnect.is_scheduled(b.node_id)
                or b.node_id in sw.peers
                or b.node_id in sw.banned
            ):
                raise AssertionError(
                    f"failed persistent dial {a.name}->{b.name} was "
                    "NOT scheduled on the reconnect plane"
                ) from e
            _log.debug(
                "chaos: dial failed, reconnect plane owns the retry",
                src=a.name, dst=b.name, err=repr(e),
            )

    async def crash(self, idx: int) -> None:
        cn = self.nodes[idx]
        if cn.node is None:
            return
        self._snapshots[idx] = self.wal_checker.pre_crash(cn.node)
        _log.info("chaos: crashing node", node=cn.name, height=cn.node.height)
        try:
            # bounded (ASY110): kill() is internally stage-budgeted
            # (obs/shutdown.py) — this outer bound covers the case
            # where the loop never even schedules those stages
            await asyncio.wait_for(
                cn.node.kill(),
                cn.node.config.instrumentation.shutdown_stage_budget_s
                * 9,
            )
        except asyncio.TimeoutError:
            _log.error("chaos: node kill wedged, abandoning",
                       node=cn.name)
        inner = getattr(cn.node, "shutdown_guard", None)
        if inner is not None:
            cn.shutdown_stalls.extend(inner.stalls)
        cn.node = None

    async def restart(self, idx: int) -> None:
        cn = self.nodes[idx]
        if cn.node is not None:
            return
        cn.node = self._build(cn)
        self._track(cn)
        await cn.node.start()
        # WAL-replay consistency right after recovery, before the node
        # re-joins gossip
        self.wal_checker.post_restart(
            cn.name, cn.node, self._snapshots.get(idx, {})
        )
        _log.info(
            "chaos: restarted node", node=cn.name, height=cn.node.height
        )
        for other in self.nodes:
            if other.idx != idx and other.running:
                await self._dial(cn, other)

    async def statesync_join(
        self,
        via: Optional[List[int]] = None,
        timeout_s: float = 90.0,
    ) -> str:
        """A FRESH non-validator node joins the running net through
        the full statesync path: p2p snapshot discovery, light-client
        verified restore against two running nodes' RPC, blocksync
        tail-follow. Requires ``enable_rpc=True`` at net build.

        Blocks (bounded) until the joiner's store holds its first
        blocksynced block — i.e. the snapshot restore + handoff
        really landed; the tail-follow continues in the background
        and the end-of-run liveness check holds the joiner to the
        same bar as everyone else. Raises InvariantViolation when the
        join fails or times out: a node that cannot join a healthy
        net IS a robustness failure."""
        if via:
            sources = [
                self.nodes[i] for i in via if self.nodes[i].running
            ]
        else:
            sources = [cn for cn in self.nodes if cn.running]
        sources = [
            cn for cn in sources
            if cn.node is not None and cn.node.rpc_server is not None
        ]
        if not sources:
            raise InvariantViolation(
                "statesync-join",
                "no running RPC sources (build ChaosNet with "
                "enable_rpc=True and keep a source alive)",
            )
        # trust root anchored at the source's BASE, not block 1: a
        # retention-pruned source (store/retention.py) no longer
        # holds the early heights, and a joiner bootstrapping from it
        # must trust from a height the source can actually serve
        src_store = sources[0].node.parts.block_store
        trust_h = max(1, src_store.base())
        trust = src_store.load_block(trust_h)
        if trust is None:
            raise InvariantViolation(
                "statesync-join",
                f"source has no block {trust_h} for the trust root",
            )
        idx = len(self.nodes)
        name = f"j{idx}"
        home = os.path.join(self.base_dir, name)
        os.makedirs(home, exist_ok=True)
        cn = ChaosNode(idx, name, NodeKey.generate(), None, home)
        cn.build_overrides = {
            "statesync.enable": True,
            "statesync.rpc_servers": [
                s.node.rpc_server.listen_addr for s in sources[:2]
            ],
            "statesync.trust_height": trust_h,
            "statesync.trust_hash": bytes(trust.hash()).hex(),
            # discovery exits as soon as ONE snapshot lands, so this
            # only bounds the FAILURE case — and on a contended box
            # the joiner's 4 secret-connection handshakes alone can
            # eat >10s before any peer can even answer, so a short
            # window misreads load as "no viable snapshots"
            "statesync.discovery_time_s": 45.0,
            "blocksync.enable": True,
        }
        self.nodes.append(cn)
        cn.node = self._build(cn)
        self._track(cn)
        await cn.node.start()
        for other in self.nodes:
            if other.idx != idx and other.running:
                await self._dial(cn, other)
        _log.info("chaos: statesync join started", node=name)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while True:
            node = cn.node
            if node is None or node.statesync_error is not None:
                err = (
                    repr(node.statesync_error) if node else "stopped"
                )
                cn.node = None  # a dead joiner must drop out of the
                # running set or end-of-run store scans hit closed fds
                raise InvariantViolation(
                    "statesync-join", f"{name} failed to join: {err}"
                )
            if node.height >= 1:
                # snapshot restored + first tail block stored; the
                # follow continues in the background
                _log.info(
                    "chaos: statesync join bootstrapped",
                    node=name,
                    height=node.height,
                    base=node.parts.block_store.base(),
                )
                return name
            if loop.time() > deadline:
                try:
                    # bounded like crash(): a wedged joiner kill must
                    # not hang the run that is reporting its failure
                    await asyncio.wait_for(
                        node.kill(),
                        node.config.instrumentation
                        .shutdown_stage_budget_s * 9,
                    )
                except asyncio.TimeoutError:
                    _log.error(
                        "chaos: joiner kill wedged, abandoning",
                        node=name,
                    )
                cn.node = None
                raise InvariantViolation(
                    "statesync-join",
                    f"{name} did not bootstrap within {timeout_s:.0f}s",
                )
            await asyncio.sleep(POLL_S)

    async def wal_torn_tail(self, idx: int, garbage: bytes) -> dict:
        """Power-cut the node (if running), append a torn tail — the
        partial record a real power cut leaves — to its consensus WAL
        head, then restart. The restart path must repair the tail
        (consensus/wal.py truncate_corrupt_tail on open) and the
        WAL-replay checker holds it to the no-amnesia bar; without
        the repair, records APPENDED after the garbage would be
        unreadable on the following restart."""
        cn = self.nodes[idx]
        was_running = cn.node is not None
        if was_running:
            await self.crash(idx)
        wal_path = os.path.join(cn.home, "cs.wal")
        appended = 0
        if os.path.exists(wal_path):
            with open(wal_path, "ab") as f:
                f.write(garbage)
            appended = len(garbage)
            _log.info(
                "chaos: tore WAL tail", node=cn.name, bytes=appended
            )
        await self.restart(idx)
        return {
            "node": cn.name,
            "torn_bytes": appended,
            "was_running": was_running,
        }

    async def crash_mid_prune(self, idx: int, abort_after: int) -> dict:
        """Abort a retention reconcile pass after ``abort_after``
        bounded batches (the in-process stand-in for the
        ``retention-prune-batch`` fail_point power cut), then crash +
        restart the node and run ONE resume pass. The crash-safety
        contract under test (store/retention.py): every committed
        batch carried its own base-marker advance, so the partial
        pass is a consistent (just less-pruned) store, the WAL-replay
        checker holds the restart to the no-amnesia bar, and the
        resume pass idempotently re-computes the same targets and
        finishes the job — no gap, no double-delete, no wedge."""
        cn = self.nodes[idx]
        if cn.node is None:
            raise InvariantViolation(
                "crash-mid-prune", f"{cn.name} is not running"
            )
        ret = cn.node.parts.retention
        if ret is None or not ret.enabled:
            raise InvariantViolation(
                "crash-mid-prune",
                f"{cn.name} has no retention plane (schedule a "
                "lifecycle run: [storage] knobs are auto-set when "
                "this action is present)",
            )

        class _PruneAborted(RuntimeError):
            pass

        calls = 0

        def hook():
            nonlocal calls
            calls += 1
            if calls > abort_after:
                raise _PruneAborted()

        ret.batch_hook = hook
        aborted = False
        try:
            try:
                await asyncio.to_thread(ret.reconcile_once)
            except _PruneAborted:
                aborted = True
        finally:
            ret.batch_hook = None
        bs = cn.node.parts.block_store
        mid_base = bs.base()
        mid_height = bs.height()
        await self.crash(idx)
        await self.restart(idx)
        node = cn.node
        ret2 = node.parts.retention
        resumed = await asyncio.to_thread(ret2.reconcile_once)
        bs2 = node.parts.block_store
        base2 = bs2.base()
        if base2 < mid_base:
            raise InvariantViolation(
                "crash-mid-prune",
                f"{cn.name} base regressed across crash/resume: "
                f"{base2} < {mid_base}",
            )
        # the retained range must be fully readable and the pruned
        # range fully gone — a half-applied delete batch would break
        # one side or the other
        probe = max(1, base2)
        if bs2.height() >= probe and bs2.load_block(probe) is None:
            raise InvariantViolation(
                "crash-mid-prune",
                f"{cn.name} block {probe} (the base) unreadable "
                "after resume",
            )
        if base2 > 1 and bs2.load_block(base2 - 1) is not None:
            raise InvariantViolation(
                "crash-mid-prune",
                f"{cn.name} block {base2 - 1} still present below "
                f"base {base2} after resume",
            )
        ti = node.parts.tx_indexer
        idx_base = ti.base_height() if ti is not None else 0
        # trace determinism (the conn_kill rule): the record carries
        # the CONFIGURED/seeded parameters only — the bases and prune
        # counts depend on how far the live network committed during
        # the crash/restart window (wall-clock), so they go to the log
        _log.info(
            "crash_mid_prune detail",
            node=cn.name,
            aborted=aborted,
            mid_base=mid_base,
            mid_height=mid_height,
            resumed_base=base2,
            index_base=idx_base,
            resumed=resumed,
        )
        return {"node": cn.name, "abort_after": abort_after}

    async def snapshot_during_prune(self, idx: int) -> dict:
        """Park a retention reconcile pass mid-batch, then serve the
        node's newest on-disk snapshot chunk-by-chunk — under the
        in-flight-serve pin — while the prune pass is live, and
        verify the reassembled blob hashes to the advertised hash.
        The floor contract under test (store/retention.py): a joiner
        mid-download must never see a snapshot rot out from under it,
        prune pass or not."""
        import hashlib as _hashlib
        import threading as _threading

        cn = self.nodes[idx]
        if cn.node is None:
            raise InvariantViolation(
                "snapshot-during-prune", f"{cn.name} is not running"
            )
        node = cn.node
        ret = node.parts.retention
        snaps_store = node.parts.snapshot_store
        if (
            ret is None
            or not ret.enabled
            or snaps_store is None
        ):
            raise InvariantViolation(
                "snapshot-during-prune",
                f"{cn.name} has no retention plane + snapshot store",
            )
        # one plain pass first so a snapshot is guaranteed held
        # (mirrors the app's newest advertised snapshot to disk)
        await asyncio.to_thread(ret.reconcile_once)
        snaps = snaps_store.list_snapshots()
        if not snaps:
            raise InvariantViolation(
                "snapshot-during-prune",
                f"{cn.name} holds no snapshot (trigger this action "
                "at a height past the app's snapshot cadence)",
            )
        newest = snaps[-1]
        parked = _threading.Event()
        release = _threading.Event()
        first = [True]

        def hook():
            if first[0]:
                first[0] = False
                parked.set()
                release.wait(timeout=10.0)

        ret.batch_hook = hook
        try:
            pass_task = asyncio.ensure_future(
                asyncio.to_thread(ret.reconcile_once)
            )
            # wait (bounded) for the pass to park mid-batch; a pass
            # with nothing left to prune never parks — the serve
            # check below still runs, just not concurrently
            parked_hit = await asyncio.to_thread(parked.wait, 5.0)

            def serve() -> bytes:
                with ret.serving(newest.height):
                    parts = []
                    for i in range(newest.chunks):
                        parts.append(
                            node.parts.proxy.snapshot
                            .load_snapshot_chunk(
                                newest.height, newest.format, i
                            )
                            or b""
                        )
                    return b"".join(parts)

            blob = await asyncio.to_thread(serve)
        finally:
            release.set()
            await pass_task
            ret.batch_hook = None
        if _hashlib.sha256(blob).digest() != newest.hash:
            raise InvariantViolation(
                "snapshot-during-prune",
                f"{cn.name} snapshot {newest.height} served during "
                "an active prune pass did not hash-verify",
            )
        if snaps_store.latest_height() < newest.height:
            raise InvariantViolation(
                "snapshot-during-prune",
                f"{cn.name} snapshot {newest.height} rotated away "
                "while pinned by an in-flight serve",
            )
        # trace determinism: snapshot height/chunk count and whether
        # the pass actually parked depend on the momentary chain
        # height (wall-clock) — log them, record only the verdict
        _log.info(
            "snapshot_during_prune detail",
            node=cn.name,
            snapshot_height=newest.height,
            chunks=newest.chunks,
            concurrent=bool(parked_hit),
        )
        return {"node": cn.name, "verified": True}

    def kill_conns(
        self,
        idx: int,
        count: Optional[int] = None,
        reason: str = "pong timeout (injected)",
    ) -> List[str]:
        """Kill up to ``count`` (None = all) of node ``idx``'s live
        connections via pong-timeout injection — the conn death a
        partition's silent blackhole eventually produces, without
        waiting out ping_interval + pong_timeout. Both ends observe
        the death (the remote reads a closed conn), so both ends'
        reconnect planes engage. Deterministic kill order (sorted
        peer id)."""
        cn = self.nodes[idx]
        if cn.node is None:
            return []
        killed: List[str] = []
        for pid in sorted(cn.node.switch.peers):
            if count is not None and len(killed) >= count:
                break
            peer = cn.node.switch.peers.get(pid)
            if peer is None:
                continue
            peer.inject_error(ConnectionError(reason))
            killed.append(pid)
        self.conns_killed += len(killed)
        _log.info(
            "chaos: injected conn kills",
            node=cn.name, killed=len(killed), reason=reason,
        )
        return killed

    def valset_churn(self, idx: int, power: int) -> dict:
        """Submit a validator power-change tx (kvstore
        ``val:<hex pubkey>!<power>``) for validator ``idx``'s key
        through the first running node's mempool — live valset churn
        without adding absent signers (the target keeps signing with
        the same key at its new power)."""
        target = self.nodes[idx]
        if target.privval is None:
            raise ValueError(f"{target.name} is not a validator")
        pub = target.privval.pub_key()
        tx = (
            b"val:" + pub.key_bytes.hex().encode()
            + b"!" + str(power).encode()
        )
        for cn in self.nodes:
            if cn.running:
                res = cn.node.parts.mempool.check_tx(tx)
                code = getattr(res, "code", 0)
                _log.info(
                    "chaos: valset churn submitted",
                    validator=target.name,
                    power=power,
                    via=cn.name,
                    code=code,
                )
                return {
                    "validator": target.name,
                    "power": power,
                    "via": cn.name,
                    "code": code,
                }
        raise InvariantViolation(
            "valset-churn", "no running node to submit through"
        )

    async def stop(self) -> None:
        """Bounded teardown (obs/shutdown.py): each node's stop runs
        under a budget sized to its staged shutdown; a node that
        wedges anyway is flight-recorded, cancelled, abandoned — and
        its store handles are force-released so the loop exits and a
        later incarnation can still reopen the home dir. This is the
        fix for the full-suite wedge class: an un-timeouted
        ``await net.stop()`` tail could previously hang the suite
        with the loop alive and store fds open."""
        from ..obs import ShutdownGuard

        for t in self._byz_tasks:
            t.cancel()
        guard = ShutdownGuard(
            tracer=global_tracer(), name="chaosnet"
        )
        self.stop_guard = guard
        for cn in self.nodes:
            node, cn.node = cn.node, None
            if node is None:
                continue
            # Node._shutdown is itself staged (~7 stages); this outer
            # budget only trips when the staged path is wedged at a
            # level its own guard cannot see (e.g. the loop never
            # schedules the stage task)
            per_stage = (
                node.config.instrumentation.shutdown_stage_budget_s
            )
            done = await guard.stage(
                f"stop.{cn.name}", node.stop(),
                budget_s=max(10.0, per_stage * 9),
            )
            inner = getattr(node, "shutdown_guard", None)
            if inner is not None:
                cn.shutdown_stalls.extend(inner.stalls)
            if not done:
                # abandoned: free the store fds regardless, bounded
                await guard.stage(
                    f"close_stores.{cn.name}",
                    asyncio.to_thread(node.parts.close_stores),
                    budget_s=5.0,
                )
        for cn in self.nodes:
            cn.shutdown_stalls.extend(
                r for r in guard.stalls
                if str(r.get("stage", "")).endswith("." + cn.name)
            )

    def shutdown_stall_records(self) -> List[dict]:
        """Every bounded-shutdown breach captured across the run
        (per-node inner stage stalls + net-level outer stalls)."""
        out: List[dict] = []
        for cn in self.nodes:
            out.extend(dict(r) for r in cn.shutdown_stalls)
        return out

    # --- byzantine commit corruption ----------------------------------

    def inject_commit_corruption(self, idx: int, tamper: bytes) -> None:
        """Rewrite the designated node's NEXT committed block ID in its
        own store — the observable footprint of a byzantine commit,
        used to prove the agreement checker actually fires."""
        cn = self.nodes[idx]

        async def corrupt():
            target_h = (cn.node.height if cn.node else 0) + 1
            while cn.node is None or cn.node.height < target_h:
                await asyncio.sleep(POLL_S)
            store = cn.node.parts.block_store
            meta = store.load_block_meta(target_h)
            meta.block_id = T.BlockID(
                tamper, meta.block_id.part_set_header
            )
            store.db.set(_hkey(b"H:", target_h), meta.encode())
            _log.info(
                "chaos: corrupted commit", node=cn.name, height=target_h
            )

        self._byz_tasks.append(spawn(corrupt(), name="chaos-byzantine"))

    # --- introspection -------------------------------------------------

    def fleet_size(self) -> int:
        h = self.fleet_harness
        return h.size() if h is not None else 0

    async def replica_kill(self, idx: int) -> dict:
        """Kill one fleet follower mid-stream (nemesis
        ``replica_kill``); the router's failover is judged by
        FleetHarness.finish()."""
        if self.fleet_harness is None:
            raise RuntimeError(
                "replica_kill requires a fleet: "
                "run_schedule(..., fleet=N)"
            )
        return await self.fleet_harness.replica_kill(idx)

    def running_nodes(self):
        return [
            (cn.name, cn.node) for cn in self.nodes if cn.node is not None
        ]

    def max_height(self) -> int:
        return max(
            (cn.node.height for cn in self.nodes if cn.node is not None),
            default=0,
        )

    def heights(self) -> Dict[str, int]:
        return {
            cn.name: (cn.node.height if cn.node else -1)
            for cn in self.nodes
        }

    def stall_records(self) -> List[dict]:
        """Every flight record captured by any incarnation's loop
        watchdog, time-ordered (obs/watchdog.py)."""
        out: List[dict] = []
        for cn in self.nodes:
            for wd in cn.watchdogs:
                out.extend(dict(r) for r in wd.stalls)
        out.sort(key=lambda r: r.get("ts_ns", 0))
        return out

    @staticmethod
    def _anchored(tr) -> list:
        """Ring snapshot with its monotonic→wall clock anchor
        guaranteed present: a lapped ring drops the ``clock.anchor``
        instant, but the anchor also rides ``Tracer.meta`` (recorded
        at build, node/inprocess.record_clock_anchor), so it is
        re-synthesized here — the cross-node timeline rebase must
        never lose a ring's clock alignment to ring churn."""
        events = tr.snapshot()
        mono = tr.meta.get("anchor_mono_ns")
        if (
            events
            and mono
            and not any(e["name"] == "clock.anchor" for e in events)
        ):
            events.insert(
                0,
                {
                    "seq": -1,
                    "name": "clock.anchor",
                    "ph": "i",
                    "ts_ns": mono,
                    "dur_ns": 0,
                    "tid": "main",
                    "args": {"wall_ns": tr.meta["anchor_wall_ns"]},
                },
            )
        return events

    def ring_snapshots(self) -> Dict[str, list]:
        """{label: events} over every incarnation's ring plus the
        process ring — the in-memory form dump_traces writes out and
        the span-budget evaluation reads."""
        by_node: Dict[str, list] = {}
        for cn in self.nodes:
            for gen, tr in enumerate(cn.tracers):
                events = self._anchored(tr)
                if not events:
                    continue
                label = (
                    cn.name if len(cn.tracers) == 1
                    else f"{cn.name}.{gen}"
                )
                by_node[label] = events
        proc = self._anchored(global_tracer())
        if proc:
            by_node["process"] = proc
        return by_node

    def dump_traces(self, out_dir: str) -> List[str]:
        """Write every node's trace ring (one JSONL per incarnation —
        restarts get a fresh ring, so n1 that crashed and came back
        dumps n1.0 and n1.1) plus the crypto plane's process ring and
        one merged Perfetto-loadable trace.json. Returns the files.

        Per-ring JSONL keeps raw monotonic timestamps (each carries
        its ``clock.anchor``); the MERGED trace.json is rebased via
        those anchors and stable-sorted per ring, so node timelines
        line up in Perfetto instead of landing at arbitrary
        monotonic offsets (docs/TRACE.md "Cross-node timelines")."""
        os.makedirs(out_dir, exist_ok=True)
        files: List[str] = []
        by_node = self.ring_snapshots()
        for label, events in by_node.items():
            files.append(
                write_jsonl(
                    os.path.join(out_dir, f"{label}.trace.jsonl"),
                    label,
                    events,
                )
            )
        if by_node:
            rebased, _offsets, _base = timeline_rebase(by_node)
            files.append(
                write_chrome(
                    os.path.join(out_dir, "trace.json"), rebased
                )
            )
        return files


def _run_light_storm_sync(
    net: "ChaosNet", sessions: int, seed: int, workers: int = 16
) -> dict:
    """Seeded N-session light-client serving storm against the most
    advanced LIVE node (ISSUE 13 satellite): every session opens on
    the shared LightServingPlane, requests a seeded height, and the
    served block's hash is asserted against the node's own store
    (live verdict parity). Spans land on the target node's trace ring
    so `trace timeline --strict` and the span budgets see the storm.

    Runs on a worker thread pool (the plane is the thread-facing
    seam); the caller wraps it in asyncio.to_thread."""
    import concurrent.futures
    import random as _random
    import time as _time

    from ..light import Client, LightServingPlane, TrustOptions
    from ..light.provider import StoreBackedProvider

    running = net.running_nodes()
    if not running:
        raise RuntimeError("no running node to storm")
    name, node = max(running, key=lambda t: t[1].height)
    chain_id = net.genesis.chain_id
    store = node.parts.block_store
    provider = StoreBackedProvider(
        chain_id, store, node.parts.state_store
    )
    root = provider.light_block(1)
    tracer = node.parts.tracer
    pool = [
        Client(
            chain_id,
            TrustOptions(
                period_ns=24 * 3600 * 10**9,
                height=1,
                hash=root.hash(),
            ),
            provider,
        )
        for _ in range(4)
    ]
    plane = LightServingPlane(
        pool,
        max_sessions=sessions + workers,
        max_inflight=workers,
        tracer=tracer,
    )
    top = max(2, node.height)
    rng = _random.Random(seed ^ 0x11C0)
    heights = [rng.randint(2, top) for _ in range(sessions)]
    lat_ms: List[float] = []
    lat_lock = threading.Lock()

    def one_session(sid: int) -> None:
        h = heights[sid]
        t0 = _time.monotonic()
        with plane.open_session() as s:
            lb = s.verified_block(h)
        dt = (_time.monotonic() - t0) * 1e3
        meta = store.load_block_meta(h)
        if meta is None or bytes(lb.hash()) != bytes(
            meta.block_id.hash
        ):
            raise RuntimeError(
                f"storm session {sid}: served block at {h} does not "
                "match the node's store"
            )
        with lat_lock:
            lat_ms.append(dt)

    with concurrent.futures.ThreadPoolExecutor(workers) as ex:
        for f in [
            ex.submit(one_session, sid) for sid in range(sessions)
        ]:
            f.result()  # re-raise any session failure
    lat_ms.sort()

    def pct(p: float) -> float:
        return round(lat_ms[int(p * (len(lat_ms) - 1))], 3)

    return {
        "sessions": sessions,
        "workers": workers,
        "target_node": name,
        "top_height": top,
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "plane": plane.stats(),
    }


async def _run_subscriber_storm(
    net: "ChaosNet", n: int, seed: int, events_each: int = 2
) -> dict:
    """N real websocket subscribers storm the most advanced LIVE
    node's fan-out plane (rpc/fanout.py, ISSUE 15) while consensus
    keeps committing: every subscriber must receive ``events_each``
    consecutive NewBlock events whose heights exist in the node's
    store (delivery parity), zero frames may be shed (the stub
    sockets drain at network speed), and the hub must have paid ~one
    serialization per event, not per subscriber."""
    import json as _json

    import aiohttp

    running = [
        (name, node)
        for name, node in net.running_nodes()
        if getattr(node, "rpc_server", None) is not None
    ]
    if not running:
        raise RuntimeError("no running RPC node to storm")
    name, node = max(running, key=lambda t: t[1].height)
    hub = node.rpc_server.fanout
    encodes0, delivered0 = hub.encodes, hub.delivered
    dropped0 = hub.queue_stats()["dropped"]
    base = "http://" + node.rpc_server.listen_addr
    q = "tm.event='NewBlock'"
    t0 = asyncio.get_running_loop().time()
    # default connector caps 100 conns/host — websocket conns never
    # free their slot, so subscriber 101 would deadlock the storm
    connector = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=connector) as sess:
        wss = []
        try:
            for i in range(n):
                ws = await sess.ws_connect(base + "/websocket")
                await ws.send_json(
                    {
                        "jsonrpc": "2.0",
                        "id": i,
                        "method": "subscribe",
                        "params": {"query": q},
                    }
                )
                wss.append(ws)

            async def collect(ws) -> list:
                heights = []
                while len(heights) < events_each:
                    msg = await asyncio.wait_for(ws.receive(), 90.0)
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        raise RuntimeError(
                            f"storm socket closed early: {msg.type}"
                        )
                    body = _json.loads(msg.data)
                    if body.get("error"):
                        raise RuntimeError(
                            f"storm subscribe error: {body['error']}"
                        )
                    res = body.get("result") or {}
                    if res.get("query") == q:
                        heights.append(
                            int(
                                res["data"]["value"]["block"]["header"][
                                    "height"
                                ]
                            )
                        )
                return heights

            results = await asyncio.wait_for(
                asyncio.gather(*[collect(ws) for ws in wss]), 180.0
            )
        finally:
            for ws in wss:
                try:
                    await asyncio.wait_for(ws.close(), 5.0)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass  # a dead socket is already closed
    wall_s = asyncio.get_running_loop().time() - t0
    parity_ok = True
    store = node.parts.block_store
    for hs in results:
        # consecutive heights, every one a block this node committed
        if [h - hs[0] for h in hs] != list(range(len(hs))):
            parity_ok = False
        for h in hs:
            if store.load_block_meta(h) is None:
                raise RuntimeError(
                    f"storm delivered height {h} missing from the "
                    f"store of {name}"
                )
    stats = hub.queue_stats()
    dropped = stats["dropped"] - dropped0
    encodes = hub.encodes - encodes0
    delivered = hub.delivered - delivered0
    if dropped:
        raise RuntimeError(
            f"subscriber storm shed {dropped} frames — the fan-out "
            "plane must deliver a draining subscriber everything"
        )
    if not parity_ok:
        raise RuntimeError(
            "subscriber storm: non-consecutive event stream delivered"
        )
    # one-pass check: ~one serialization per DISTINCT event for the
    # single query group. Bound on events, not delivered//subscriber:
    # a block committed during the sequential attach phase costs a
    # full encode while only a few subscribers are attached, which a
    # frames-per-subscriber bound misreads as per-subscriber
    # encoding. Late joiners may also split one event across group
    # membership snapshots — so bound (2x + slack), don't pin.
    distinct_events = len({h for hs in results for h in hs})
    if delivered and encodes > 4 + 2 * distinct_events:
        raise RuntimeError(
            f"fan-out paid {encodes} serializations for {delivered} "
            "frames — per-subscriber encoding crept back"
        )
    return {
        "subscribers": n,
        "events_each": events_each,
        "target_node": name,
        "encodes": encodes,
        "delivered": delivered,
        "dropped": dropped,
        "parity_ok": parity_ok,
        "wall_s": round(wall_s, 3),
    }


class _FleetSink:
    """In-process frame sink for one routed fleet session: records
    every delivered frame's height so zero-lost-commits is checkable
    as stream contiguity."""

    __slots__ = ("heights", "frames")

    def __init__(self):
        self.heights: List[int] = []
        self.frames = 0

    async def send_str(self, frame: str) -> None:
        from ..fleet.router import _HEIGHT_RE

        self.frames += 1
        m = _HEIGHT_RE.search(frame)
        if m:
            self.heights.append(int(m.group(1)))


class FleetHarness:
    """In-process serving fleet riding a chaos net (docs/FLEET.md,
    docs/CHAOS.md ``replica_kill``): N FollowerNode replicas tail a
    StreamSource pumped from the committee's most advanced store, a
    SessionRouter fronts them, and a pool of routed subscriber
    sessions streams NewBlock commits for the whole schedule — so a
    mid-schedule ``replica_kill`` strands real sessions and the
    router's failover contract (zero lost commits) is judged on their
    recorded streams. ``finish()`` also runs the lag-shed isolation
    probe: stall one survivor past max_lag_heights and assert only
    ITS clients shed, then recover it."""

    MAX_LAG = 6

    def __init__(self, net: "ChaosNet", n_replicas: int, seed: int,
                 n_sessions: int = 24):
        from ..fleet import FollowerNode, SessionRouter, StreamSource

        self.net = net
        self.source = StreamSource()
        self.replicas = [
            FollowerNode(
                f"fleet-r{i}", self.source, tracer=global_tracer()
            )
            for i in range(n_replicas)
        ]
        self.router = SessionRouter(
            self.replicas,
            store_source=self.source,
            max_lag_heights=self.MAX_LAG,
            lag_poll_s=0.05,
            tracer=global_tracer(),
        )
        self.n_sessions = n_sessions
        self.sinks: List[_FleetSink] = []
        self.sessions: List = []
        self.killed: List[str] = []
        self.violations: List[str] = []
        self._pump_task: Optional[asyncio.Future] = None
        self._fed = 0

    async def start(self) -> None:
        self._pump_task = spawn(self._pump(), name="fleet-pump")
        for r in self.replicas:
            await r.start(from_height=self.source.height())
        await self.router.start()
        for _ in range(self.n_sessions):
            sink = _FleetSink()
            sess = await self.router.subscribe(
                sink, "tm.event='NewBlock'"
            )
            self.sinks.append(sink)
            self.sessions.append(sess)

    async def _pump(self) -> None:
        """Feed the fleet source from the committee: the in-process
        stand-in for blocksync tail-follow (same blocks, same order)."""
        while True:
            try:
                running = self.net.running_nodes()
                if running:
                    _, top = max(running, key=lambda t: t[1].height)
                    store = top.parts.block_store
                    if self._fed < store.base() - 1:
                        self._fed = store.base() - 1
                    while self._fed < store.height():
                        b = store.load_block(self._fed + 1)
                        if b is None:
                            break
                        self.source.advance(b)
                        self._fed += 1
            except asyncio.CancelledError:
                raise
            except Exception:
                # a crash closed the store under the reader; the next
                # pass re-reads from a survivor
                pass
            await asyncio.sleep(POLL_S)

    def size(self) -> int:
        return len(self.replicas)

    async def replica_kill(self, idx: int) -> dict:
        r = self.replicas[idx % len(self.replicas)]
        stranded = sum(
            1
            for rep in self.router._sessions.values()
            if rep is r
        )
        await r.kill()
        self.killed.append(r.name)
        return {"replica": r.name, "stranded_sessions": stranded}

    async def _wait(self, pred, timeout_s: float) -> bool:
        deadline = asyncio.get_running_loop().time() + timeout_s
        while not pred():
            if asyncio.get_running_loop().time() >= deadline:
                return False
            await asyncio.sleep(POLL_S)
        return True

    async def finish(self) -> dict:
        """Judge the fleet contract and return the report section."""
        v = self.violations
        # failover must have re-homed every stranded session off the
        # dead replicas (or shed it honestly — counted)
        dead = [r for r in self.replicas if not r.alive]
        ok = await self._wait(
            lambda: not any(
                rep in dead for rep in self.router._sessions.values()
            ),
            10.0,
        )
        if not ok:
            v.append(
                "fleet: sessions still mapped to a dead replica "
                "after failover window"
            )
        if self.killed:
            if self.router.failovers == 0:
                v.append(
                    "fleet: replica_kill executed but the router "
                    "recorded no failover"
                )
            if self.router.sessions_resumed == 0:
                v.append(
                    "fleet: replica_kill stranded sessions but none "
                    "were resumed (all shed)"
                )
        # lag-shed isolation probe on a survivor with sessions —
        # requires the committee to still be committing (it is: the
        # probe runs before net.stop())
        probe: Dict[str, object] = {}
        victim = next(
            (
                r
                for r in self.replicas
                if r.alive
                and any(
                    rep is r
                    for rep in self.router._sessions.values()
                )
            ),
            None,
        )
        if victim is not None:
            others_before = [
                s
                for s, rep in self.router._sessions.items()
                if rep is not victim
            ]
            victim.stalled = True
            degraded = await self._wait(
                lambda: any(
                    r["degraded"]
                    for r in self.router.fleet_status()["replicas"]
                    if r["name"] == victim.name
                ),
                20.0,
            )
            if not degraded:
                v.append(
                    f"fleet: stalled {victim.name} past "
                    f"max_lag_heights but it was never degraded"
                )
            else:
                # isolation: every session that was on another replica
                # is untouched; the victim serves no one
                bystanders_shed = [
                    s for s in others_before if s.closed
                ]
                if bystanders_shed:
                    v.append(
                        f"fleet: lag shed closed "
                        f"{len(bystanders_shed)} sessions of OTHER "
                        f"replicas — shedding must isolate the "
                        f"stalled follower's clients"
                    )
                if victim.members() != 0:
                    v.append(
                        f"fleet: degraded {victim.name} still holds "
                        f"{victim.members()} sessions"
                    )
            victim.stalled = False
            recovered = await self._wait(
                lambda: not any(
                    r["degraded"]
                    for r in self.router.fleet_status()["replicas"]
                    if r["name"] == victim.name
                ),
                20.0,
            )
            if degraded and not recovered:
                v.append(
                    f"fleet: {victim.name} caught back up but was "
                    f"never rotated back in"
                )
            probe = {
                "victim": victim.name,
                "degraded": degraded,
                "recovered": recovered,
                "sheds_lag": self.router.sheds_lag,
            }
        # zero lost commits: every live session's recorded stream is
        # contiguous (resumed ones replayed their gap from the store)
        resumed = 0
        for sink, sess in zip(self.sinks, self.sessions):
            hs = sink.heights
            if sess.resumes:
                resumed += 1
            if sess.closed and sess.close_reason in (
                "shed_lag", "failover_shed",
            ):
                continue  # honestly shed, not silently lossy
            if hs and [h - hs[0] for h in hs] != list(
                range(len(hs))
            ):
                v.append(
                    f"fleet: session (resumes={sess.resumes}, "
                    f"reason={sess.close_reason!r}) delivered a "
                    f"non-contiguous stream — commits were lost"
                )
        if self.killed and resumed == 0:
            v.append(
                "fleet: no surviving session was resumed after "
                "replica_kill"
            )
        status = self.router.fleet_status()
        return {
            "replicas": status["replicas"],
            "killed": self.killed,
            "sessions": self.n_sessions,
            "sessions_resumed": self.router.sessions_resumed,
            "failovers": self.router.failovers,
            "sheds": status["sheds"],
            "lag_probe": probe,
            "delivered_frames": sum(s.frames for s in self.sinks),
        }

    async def stop(self) -> None:
        t, self._pump_task = self._pump_task, None
        if t is not None and not t.done():
            t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(t, return_exceptions=True), 5.0
                )
            except asyncio.TimeoutError:
                pass
        # bounded teardown (ASY110): a wedged router/replica must not
        # hang the chaos run past its liveness verdict
        try:
            await asyncio.wait_for(self.router.close(), 5.0)
        except asyncio.TimeoutError:
            pass
        for r in self.replicas:
            try:
                await asyncio.wait_for(r.stop(), 5.0)
            except asyncio.TimeoutError:
                pass


async def run_schedule(
    schedule: FaultSchedule,
    seed: int,
    base_dir: str,
    n_nodes: int = 4,
    settle_heights: int = 2,
    liveness_bound_s: float = 60.0,
    fuzz_config=None,
    trace_dir: Optional[str] = None,
    config_hook=None,
    budget_file: Optional[str] = None,
    profile_hz: float = 19.0,
    workload=None,
    enable_rpc: Optional[bool] = None,
    light_storm: int = 0,
    subscriber_storm: int = 0,
    fleet: int = 0,
) -> ChaosReport:
    """Execute one seeded chaos run end-to-end and return its report
    (violations recorded, not raised — callers assert on report.ok).

    Trace dumps: with ``trace_dir`` set every node's trace ring is
    exported there unconditionally; without it a VIOLATED run still
    dumps the rings to a fresh persistent directory next to the seed
    + fault trace in the report — the timeline of what each node was
    doing is part of the replay contract.

    Health plane (docs/OBS.md): a low-rate sampling profiler runs for
    the whole schedule (``profile_hz``; 0 disables) and its folded
    stacks land beside any trace dump as profile.folded. With
    ``budget_file`` set, span budgets are evaluated over every ring
    at end of run; a breach dumps traces exactly like an invariant
    violation (report.budget_ok goes False, the CLI exits nonzero)."""
    table = LinkTable(seed, fuzz_config=fuzz_config)
    # lifecycle actions need the retention plane live on every node:
    # small windows, tiny batches (so an abort lands mid-pass), the
    # kvstore snapshot cadence mirrored to disk, and a background
    # interval long enough that only the nemesis drives reconciles —
    # deterministic counters per (seed, schedule)
    if any(
        e.action in ("crash_mid_prune", "snapshot_during_prune")
        for e in schedule.events
    ):
        _inner_hook = config_hook

        def config_hook(cfg, _inner=_inner_hook):  # noqa: F811
            if _inner is not None:
                _inner(cfg)
            s = cfg.storage
            s.retain_blocks = 4
            s.retain_states = 6
            s.retain_index = 4
            s.prune_batch = 2
            s.prune_interval_s = 3600.0
            s.snapshot_interval = 10
            s.snapshot_keep_recent = 2

    if fleet == 0 and any(
        e.action == "replica_kill" for e in schedule.events
    ):
        # a scheduled replica_kill implies a fleet: default to the
        # 3-replica deployment shape the action was designed against
        fleet = 3
    if enable_rpc is None:
        # the statesync joiner bootstraps over the sources' RPC, and
        # the subscriber storm needs a websocket endpoint — switch
        # the listeners on exactly when the run needs them
        enable_rpc = (
            any(e.action == "statesync_join" for e in schedule.events)
            or subscriber_storm > 0
        )
    net = ChaosNet(
        n_nodes,
        seed,
        base_dir,
        table=table,
        config_hook=config_hook,
        enable_rpc=enable_rpc,
    )
    report = ChaosReport(seed=seed, schedule_json=schedule.to_json())
    nemesis = Nemesis(net, schedule)
    # runtime concurrency sanitizer (analysis/runtime.py): chaos nodes
    # build with it ON (test_config); isolate this run's findings
    from ..analysis.runtime import get_sanitizer, injected_finding

    sanitizer = get_sanitizer()
    sanitizer.reset()
    inversion_scheduled = any(
        e.action == "lock_inversion" for e in schedule.events
    )
    quadratic_scheduled = any(
        e.action == "scaling_probe" and e.inject_quadratic
        for e in schedule.events
    )
    driver = None
    if workload is not None and workload.pattern != "none":
        from .workload import WorkloadDriver

        driver = WorkloadDriver(workload, seed)
    profiler = None
    if profile_hz and profile_hz > 0:
        from ..obs import SamplingProfiler

        profiler = SamplingProfiler(hz=profile_hz).start()

    stop_polling = asyncio.Event()

    async def agreement_poll():
        while not stop_polling.is_set():
            try:
                net.agreement.check(net.running_nodes())
            except asyncio.CancelledError:
                raise
            except InvariantViolation as v:
                report.violations.append(str(v))
                return
            except Exception:
                # a crash landed mid-scan and closed the node's stores
                # under the reader; the next pass re-reads (and the
                # end-of-run final_check is authoritative regardless)
                pass
            await asyncio.sleep(2 * POLL_S)

    fleet_harness = None
    try:
        await net.start()
        if fleet > 0:
            # fleet rides the run from the start so a mid-schedule
            # replica_kill strands sessions that are actually live
            fleet_harness = FleetHarness(net, fleet, seed)
            net.fleet_harness = fleet_harness
            await fleet_harness.start()
        if driver is not None:
            driver.start(net)
        poller = asyncio.create_task(agreement_poll())
        try:
            # schedule execution itself can surface violations (a
            # WAL-replay check on restart, an unreachable trigger on a
            # dead net) — they belong in the report, not a traceback
            try:
                await nemesis.run()
            except InvariantViolation as v:
                report.violations.append(str(v))
            # let pending byzantine corruptions land before judging
            if net._byz_tasks:
                await asyncio.wait(net._byz_tasks, timeout=30.0)
            # liveness: every running node must advance past the
            # post-schedule height within the bound — and SOME node
            # must be running (an empty net is the ultimate liveness
            # failure, not a vacuous pass)
            target = net.max_height() + settle_heights
            if not net.running_nodes():
                # the schedule ended with every node down; nothing can
                # restart them now, so don't burn the bound waiting
                report.violations.append(
                    str(liveness_violation(net.heights(), target, 0.0))
                )
            else:
                deadline = (
                    asyncio.get_running_loop().time() + liveness_bound_s
                )
                while asyncio.get_running_loop().time() < deadline:
                    running = net.running_nodes()
                    if running and all(
                        node.height >= target for _, node in running
                    ):
                        break
                    await asyncio.sleep(POLL_S)
                else:
                    report.violations.append(
                        str(
                            liveness_violation(
                                net.heights(), target, liveness_bound_s
                            )
                        )
                    )
            if light_storm > 0 and net.running_nodes():
                # serving-plane leg: storm a LIVE node with light
                # sessions while consensus keeps running — a session
                # failure or parity mismatch is a violation
                try:
                    report.light_storm = await asyncio.to_thread(
                        _run_light_storm_sync, net, light_storm, seed
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    report.violations.append(
                        f"light serving storm failed: {e!r}"
                    )
            if subscriber_storm > 0 and net.running_nodes():
                # fan-out plane leg (ISSUE 15): websocket subscribers
                # storm a live node while consensus keeps committing;
                # a shed, parity break or per-subscriber-encode
                # regression is a violation
                try:
                    report.subscriber_storm = await _run_subscriber_storm(
                        net, subscriber_storm, seed
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    report.violations.append(
                        f"subscriber storm failed: {e!r}"
                    )
            if fleet_harness is not None and net.running_nodes():
                # judge the fleet contract while the committee still
                # commits (the lag-shed probe needs live ingest)
                try:
                    report.fleet = await fleet_harness.finish()
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    report.violations.append(
                        f"fleet leg failed: {e!r}"
                    )
                report.violations.extend(fleet_harness.violations)
        finally:
            stop_polling.set()
            try:
                await asyncio.wait_for(poller, 5.0)
            except asyncio.TimeoutError:
                poller.cancel()
        # authoritative end-of-run agreement re-scan
        try:
            net.agreement.final_check(net.running_nodes())
        except InvariantViolation as v:
            if str(v) not in report.violations:
                report.violations.append(str(v))
    finally:
        report.final_heights = net.heights()
        try:
            # keys are regenerated per run, so the stable identity is
            # the NODE NAME (n0..nN follow sorted validator order,
            # node/inprocess.make_genesis) — that is what same-seed
            # runs must reproduce per height
            addr_to_name = {
                bytes(
                    cn.privval.pub_key().address()
                ).hex(): cn.name
                for cn in net.nodes
                if cn.privval is not None
            }
            running = net.running_nodes()
            if running:
                _, top = max(running, key=lambda t: t[1].height)
                store = top.parts.block_store
                for h in range(max(1, store.base()), top.height + 1):
                    meta = store.load_block_meta(h)
                    if meta is not None:
                        addr = bytes(
                            meta.header.proposer_address
                        ).hex()
                        report.proposers[h] = addr_to_name.get(
                            addr, addr[:12]
                        )
                    commit = store.load_block_commit(h)
                    if commit is not None:
                        report.rounds[h] = commit.round
        except Exception:
            pass  # fingerprint is best-effort diagnostics
        if driver is not None:
            await driver.stop()
            report.workload = driver.stats()
        if fleet_harness is not None:
            await fleet_harness.stop()
        await net.stop()
        if profiler is not None:
            profiler.stop()
        report.stall_records = net.stall_records()
        report.shutdown_stalls = net.shutdown_stall_records()
        report.dial_failures = net.dial_failures
        report.conns_killed = net.conns_killed
        # sanitizer findings ride the pipeline as invariant-style
        # violations: an un-injected lock-order cycle or affinity
        # breach fails the run (trace dump + seed-line replay), and a
        # scheduled lock_inversion must PROVE detection — a sanitizer
        # that cannot flag its own injection proves nothing
        report.sanitizer_findings = sanitizer.snapshot()
        for f in report.sanitizer_findings:
            if not injected_finding(f):
                report.violations.append(
                    f"sanitizer[{f.get('kind')}]: {f.get('message')}"
                )
        if inversion_scheduled:
            got = {
                f.get("kind")
                for f in report.sanitizer_findings
                if injected_finding(f)
            }
            for want in ("lock-order-cycle", "loop-affinity"):
                if want not in got:
                    report.violations.append(
                        "lock_inversion injected but the sanitizer "
                        f"reported no {want} finding"
                    )
        # scaling-probe results ride the same contract: an un-injected
        # exponent breach fails the run, and a scheduled quadratic
        # plant the probe did NOT flag also fails it
        from ..analysis.scaling import drain_chaos_results

        scaling_results = drain_chaos_results()
        report.scaling_results = [r.as_dict() for r in scaling_results]
        for r in scaling_results:
            if not r.ok and not r.injected:
                report.violations.append(
                    f"scaling[{r.site}]: exponent {r.exponent:.2f} "
                    f"over budget {r.budget:.2f}"
                )
        if quadratic_scheduled and not any(
            r.injected and not r.ok for r in scaling_results
        ):
            report.violations.append(
                "scaling_probe injected a quadratic site but the "
                "probe reported no breach for it"
            )
        if budget_file:
            # evaluated over the in-memory rings so a breach can force
            # the dump below even when no invariant tripped
            try:
                from ..obs.budget import evaluate_budgets, load_budgets
                from ..trace import summarize

                report.budget_verdicts = evaluate_budgets(
                    summarize(net.ring_snapshots()),
                    load_budgets(budget_file),
                )
            except Exception as e:
                report.violations.append(
                    f"budget evaluation failed: {e!r}"
                )
        # rings survive node stop (ChaosNode holds the tracers)
        try:
            dump_dir = trace_dir
            if dump_dir is None and (
                report.violations or not report.budget_ok
            ):
                dump_dir = tempfile.mkdtemp(
                    prefix=f"chaos_trace_{seed}_"
                )
            if dump_dir is not None:
                report.trace_files = net.dump_traces(dump_dir)
                if profiler is not None and profiler.samples:
                    report.profile_file = profiler.write_folded(
                        os.path.join(dump_dir, "profile.folded")
                    )
        except OSError:
            pass  # trace dump is best-effort diagnostics

    report.trace = nemesis.trace
    report.link_decisions = table.decision_counts()
    report.wal_checks = net.wal_checker.checks
    if not report.ok:
        # the replay contract: seed + schedule + trace on any failure
        _log.error("chaos invariants violated", seed=seed)
        print(report.format())
    return report
