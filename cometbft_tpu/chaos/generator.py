"""Scenario factory: seeded workload × network × lifecycle chaos
matrix (ROADMAP item 5, docs/CHAOS.md "Scenario factory").

From ONE master seed the generator composes whole scenarios along
three axes:

- **workload** (chaos/workload.py): sustained vs bursty tx storms
  through the PR 5 ingest plane, large-tx storms, live valset churn;
- **network** (chaos/links.py): majority partitions, asymmetric
  per-link loss, latency+jitter storms over the seeded link plane;
- **lifecycle**: crash/restart waves, adaptive-sync catchup under
  traffic, ``statesync_join`` of a fresh node mid-load, WAL
  torn-tail corruption across restart.

Determinism is the whole point: scenario ``i`` of master seed ``S``
is a pure function of ``(S, i)`` — independent of ``--count`` and of
every other scenario — so the single printed seed line

    SCENARIO m<S>-<i> ... replay: python -m cometbft_tpu.chaos matrix
        --seed <S> --only <i>

replays the exact schedule JSON, workload spec and per-link decision
streams byte-for-byte. The lifecycle axis cycles deterministically
(index mod len(LIFECYCLES)), so ANY window of >= 5 consecutive
indexes covers crash_wave, statesync_join, wal_torn_tail,
adaptive_catchup and the canonical crash/restart+churn shape — the
coverage guarantee the 5-scenario smoke matrix relies on.

Every generated scenario is expected invariant-clean AND
budget-clean (tools/span_budgets.toml): the matrix runner evaluates
the BFT invariant checkers and the per-scenario p95/p99 span budgets
over each run's trace rings, exactly like a hand-written schedule.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .schedule import FaultEvent, FaultSchedule
from .workload import WorkloadSpec

# lifecycle axis, cycled by index: any 5 consecutive indexes cover
# all of it (the smoke-matrix coverage guarantee)
LIFECYCLES = (
    "crash_wave",
    "statesync_join",
    "wal_torn_tail",
    "adaptive_catchup",
    "crash_restart",
)
WORKLOADS = ("sustained", "sustained_heavy", "bursty", "large_tx")
NETWORKS = (
    "clean", "partition", "asym_loss", "jitter_storm",
    "reconnect_storm",
)


@dataclass
class ScenarioSpec:
    """One fully-described, replayable scenario."""

    master_seed: int
    index: int
    seed: int  # derived run seed (LinkTable + nemesis draws)
    n_nodes: int
    axes: Dict[str, str]
    workload: WorkloadSpec
    schedule: FaultSchedule
    liveness_bound_s: float = 90.0
    settle_heights: int = 2
    notes: List[str] = field(default_factory=list)
    # generation inputs the replay line must carry: the soak profile
    # consumes an extra committee-size rng draw and an explicit
    # --nodes override skips it, so omitting either from the seed
    # line would regenerate a DIFFERENT scenario
    profile: str = "smoke"
    forced_nodes: Optional[int] = None

    @property
    def scenario_id(self) -> str:
        return f"m{self.master_seed}-{self.index}"

    def seed_line(self) -> str:
        """The single line that replays this scenario byte-for-byte."""
        ax = ",".join(
            f"{k}:{self.axes[k]}"
            for k in ("workload", "network", "lifecycle")
        )
        replay = (
            f"python -m cometbft_tpu.chaos matrix "
            f"--seed {self.master_seed} --only {self.index}"
        )
        if self.profile != "smoke":
            replay += f" --profile {self.profile}"
        if self.forced_nodes is not None:
            replay += f" --nodes {self.forced_nodes}"
        return (
            f"SCENARIO {self.scenario_id} seed={self.seed} "
            f"nodes={self.n_nodes} axes=[{ax}] replay: " + replay
        )

    def to_dict(self) -> dict:
        return {
            "scenario_id": self.scenario_id,
            "master_seed": self.master_seed,
            "index": self.index,
            "seed": self.seed,
            "n_nodes": self.n_nodes,
            "axes": dict(self.axes),
            "workload": self.workload.to_dict(),
            "schedule": json.loads(self.schedule.to_json()),
            "liveness_bound_s": self.liveness_bound_s,
            "settle_heights": self.settle_heights,
            "notes": list(self.notes),
            "profile": self.profile,
            "forced_nodes": self.forced_nodes,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, raw: str) -> "ScenarioSpec":
        d = json.loads(raw)
        return cls(
            master_seed=d["master_seed"],
            index=d["index"],
            seed=d["seed"],
            n_nodes=d["n_nodes"],
            axes=d["axes"],
            workload=WorkloadSpec.from_dict(d["workload"]),
            schedule=FaultSchedule.from_json(
                json.dumps(d["schedule"])
            ),
            liveness_bound_s=d.get("liveness_bound_s", 90.0),
            settle_heights=d.get("settle_heights", 2),
            notes=d.get("notes", []),
            profile=d.get("profile", "smoke"),
            forced_nodes=d.get("forced_nodes"),
        )


# --- axis builders ------------------------------------------------------


def _workload_for(kind: str, rng: random.Random) -> WorkloadSpec:
    if kind == "sustained":
        return WorkloadSpec("sustained", tps=20.0)
    if kind == "sustained_heavy":
        return WorkloadSpec("sustained", tps=60.0)
    if kind == "bursty":
        return WorkloadSpec(
            "bursty",
            burst_txs=rng.choice([32, 64]),
            burst_gap_s=rng.choice([0.3, 0.6]),
        )
    # large_tx: sustained trickle of fat txs (gossip framing + WAL
    # record sizes), rate kept low so bytes dominate
    return WorkloadSpec("sustained", tps=10.0, tx_bytes=512)


def _network_events(
    kind: str, rng: random.Random, n_nodes: int
) -> List[FaultEvent]:
    if kind == "partition":
        # majority keeps committing; heal is height-triggered
        minority = rng.randrange(n_nodes)
        majority = [i for i in range(n_nodes) if i != minority]
        return [
            FaultEvent(
                "partition", at_height=2,
                groups=[majority, [minority]],
            ),
            FaultEvent("heal", at_height=4),
        ]
    if kind == "asym_loss":
        # one-way loss on one seeded link, cleared later: progress
        # continues (gossip retransmits), the decision stream records
        src = rng.randrange(n_nodes)
        dst = (src + 1 + rng.randrange(n_nodes - 1)) % n_nodes
        return [
            FaultEvent(
                "set_link", at_height=2, src=src, dst=dst,
                link={"loss": 0.15}, symmetric=False,
            ),
            FaultEvent(
                "set_link", at_height=5, src=src, dst=dst,
                link={"loss": 0.0}, symmetric=False,
            ),
        ]
    if kind == "reconnect_storm":
        # repeated partition/heal cycles + pong-timeout conn kills on
        # one victim: the exact compound that used to exhaust the
        # finite reconnect budget and permanently isolate a healed
        # minority. The self-healing plane (p2p/reconnect.py) must
        # re-converge after every heal, inside the p2p.reconnect span
        # budget.
        victim = rng.randrange(n_nodes)
        return [
            FaultEvent(
                "reconnect_storm", at_height=2, node=victim,
                cycles=2, hold_s=1.2, gap_s=0.8,
            )
        ]
    if kind == "jitter_storm":
        # latency+jitter on two symmetric links, calmed later; stays
        # well under the propose timeout so rounds keep closing
        a = rng.randrange(n_nodes)
        b = (a + 1) % n_nodes
        c = (a + 2) % n_nodes
        return [
            FaultEvent(
                "set_link", at_height=2, src=a, dst=b,
                link={"latency_s": 0.02, "jitter_s": 0.06},
            ),
            FaultEvent(
                "set_link", at_height=2, src=b, dst=c,
                link={"latency_s": 0.01, "jitter_s": 0.05},
            ),
            FaultEvent(
                "set_link", at_height=6, src=a, dst=b,
                link={"latency_s": 0.0, "jitter_s": 0.0},
            ),
            FaultEvent(
                "set_link", at_height=6, src=b, dst=c,
                link={"latency_s": 0.0, "jitter_s": 0.0},
            ),
        ]
    return []  # clean


def _lifecycle_events(
    kind: str, rng: random.Random, n_nodes: int, after_height: int
) -> List[FaultEvent]:
    h = after_height
    if kind == "crash_wave":
        # wave of 2 (quorum parks while both are down, restarts heal
        # it); larger committees lose a real minority
        wave_n = 2 if n_nodes <= 4 else max(2, (n_nodes - 1) // 3)
        members = rng.sample(range(n_nodes), wave_n)
        return [
            FaultEvent(
                "crash_wave", at_height=h, nodes=members,
                stagger_s=0.2, restart_after_s=1.0,
            )
        ]
    if kind == "statesync_join":
        # join needs a source snapshot (kvstore snapshots every 10
        # heights): trigger past height 11. A valset-churn leg rides
        # ahead of the join so the un-pinned compound
        # (partition x statesync_join x churn) exercises joining into
        # a net whose validator set changed mid-run.
        churn_target = rng.randrange(n_nodes)
        return [
            FaultEvent("valset_churn", at_height=h, node=churn_target),
            FaultEvent("statesync_join", at_height=max(h + 1, 11)),
        ]
    if kind == "wal_torn_tail":
        victim = rng.randrange(n_nodes)
        return [
            FaultEvent("wal_torn_tail", at_height=h, node=victim),
            # a SECOND crash/restart of the same node proves records
            # appended after the repaired tail survive (no amnesia
            # one crash later)
            FaultEvent("crash", at_height=h + 2, node=victim),
            FaultEvent("restart", after_s=0.5, node=victim),
        ]
    if kind == "adaptive_catchup":
        # one node stays down long enough to fall behind, then
        # rejoins via blocksync adaptive sync while txs keep flowing
        victim = rng.randrange(n_nodes)
        return [
            FaultEvent(
                "crash_wave", at_height=h, nodes=[victim],
                stagger_s=0.0, restart_after_s=2.5, blocksync=True,
            )
        ]
    # crash_restart: canonical single crash/restart + live valset
    # churn (the workload-axis churn leg rides here so any 5-window
    # also exercises a valset change)
    victim = rng.randrange(n_nodes)
    churn_target = rng.randrange(n_nodes)
    return [
        FaultEvent("valset_churn", at_height=h, node=churn_target),
        FaultEvent("crash", at_height=h + 1, node=victim),
        FaultEvent("restart", after_s=0.5, node=victim),
    ]


# --- generation ---------------------------------------------------------


def generate_scenario(
    master_seed: int,
    index: int,
    n_nodes: Optional[int] = None,
    profile: str = "smoke",
) -> ScenarioSpec:
    """Scenario ``index`` of master seed ``master_seed`` — a pure
    function of its arguments (module doc)."""
    rng = random.Random(f"scenario|{master_seed}|{index}")
    lifecycle = LIFECYCLES[index % len(LIFECYCLES)]
    workload_kind = WORKLOADS[rng.randrange(len(WORKLOADS))]
    network_kind = NETWORKS[rng.randrange(len(NETWORKS))]
    forced_nodes = n_nodes
    if n_nodes is None:
        # larger committees only in the soak profile (and never for
        # statesync_join, which already runs extra RPC servers): the
        # smoke matrix must stay cheap enough for tier-1
        if profile == "soak" and lifecycle != "statesync_join":
            n_nodes = rng.choice([4, 4, 5, 7])
        else:
            n_nodes = 4
    # NOTE: statesync_join used to PIN the network axis to "clean" —
    # the finite-attempts reconnect gave a partitioned/conn-killed
    # minority no reliable way back, so join-under-faults starved.
    # The self-healing plane (p2p/reconnect.py: never-give-up budgeted
    # redial + incarnation-safe dialing) removed the hole, so
    # partition x statesync_join x churn now runs un-pinned; the
    # longer horizon is absorbed by the liveness bound below.

    events = _network_events(network_kind, rng, n_nodes)
    last_net_h = max(
        [e.at_height for e in events if e.at_height is not None],
        default=2,
    )
    events += _lifecycle_events(
        lifecycle, rng, n_nodes, after_height=last_net_h + 1
    )
    workload = _workload_for(workload_kind, rng)

    liveness = 90.0
    if lifecycle == "statesync_join":
        liveness = 120.0  # the join itself waits through discovery
        if network_kind != "clean":
            # un-pinned compound (join under network faults): the
            # faulted horizon is longer — heal-then-catch-up rides on
            # top of snapshot discovery
            liveness = 150.0
    return ScenarioSpec(
        master_seed=master_seed,
        index=index,
        seed=_derive_seed(master_seed, index),
        n_nodes=n_nodes,
        axes={
            "workload": workload_kind,
            "network": network_kind,
            "lifecycle": lifecycle,
        },
        workload=workload,
        schedule=FaultSchedule(events),
        liveness_bound_s=liveness,
        profile=profile,
        forced_nodes=forced_nodes,
    )


def _derive_seed(master_seed: int, index: int) -> int:
    """Stable sub-seed: decouples the run's decision streams from the
    master rng so scenario i never depends on scenarios < i."""
    return random.Random(f"seed|{master_seed}|{index}").getrandbits(31)


def generate_matrix(
    master_seed: int,
    count: int,
    n_nodes: Optional[int] = None,
    profile: str = "smoke",
    only: Optional[List[int]] = None,
) -> List[ScenarioSpec]:
    idxs = list(range(count)) if not only else sorted(set(only))
    return [
        generate_scenario(master_seed, i, n_nodes=n_nodes, profile=profile)
        for i in idxs
    ]
