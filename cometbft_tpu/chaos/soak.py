"""Compressed-time storage lifecycle soak (ISSUE 17, docs/STORAGE.md).

The bounded-disk claim is only as good as a long run: the retention
plane must hold disk AND RSS flat over thousands of heights while the
windows churn — sqlite pages recycle, WAL groups rotate and prune,
snapshots rotate, markers (``base`` / ``idx:base`` / ``idx:last``)
stay mutually consistent, and pruned heights answer RPC with the
structured below-base error, not a shapeless miss.

Compressed time: blocks come from the chain generator
(utils/chaingen.py — real signed commits through the real
BlockExecutor, no consensus rounds), the WAL is driven synthetically
(the generator bypasses consensus, so end-height records + rotation
are written directly — same group files, same prune leg), and
``reconcile_once`` runs on a slice cadence instead of the wall-clock
timer. 10k heights take ~a minute instead of ~3 hours.

The workload writes a BOUNDED keyspace (``k<h mod keys>=v<h>``): the
app state must plateau for the storage plateau to be attributable to
retention, not masked by state growth. Every checkpoint records disk
(recursive du of the node home) and RSS (/proc VmRSS); after the
warmup fraction — the window must saturate first — no later
checkpoint may exceed the warmup watermark by more than the allowed
factor.

A restart leg at the end rebuilds the node from the same home: the
ABCI handshake must replay ONLY the retained tail (the persisted app
restarts at its committed height — a pruned node cannot replay from
block 1), and the chain must extend cleanly afterwards.

Run it::

    python -m cometbft_tpu.chaos soak --heights 10000 --step 50

Exit 0 iff every assert held; the JSON report carries the checkpoint
series either way. The tier-1 slice (tests/test_retention.py) runs a
few hundred heights; the full soak rides the ``slow`` marker and the
chaos smoke script.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
from typing import List, Optional

from ..utils.log import get_logger

_log = get_logger("chaos.soak")


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return None


def _soak_config(home: str):
    from ..config.config import test_config

    cfg = test_config(home)
    cfg.base.db_backend = "sqlite"
    cfg.tx_index.indexer = "kv"
    s = cfg.storage
    s.retain_blocks = 64
    s.retain_states = 64
    s.retain_index = 64
    s.prune_batch = 16
    # the soak drives reconciles on its own slice cadence — the
    # background timer must never race it mid-measurement
    s.prune_interval_s = 3600.0
    s.snapshot_interval = 20
    s.snapshot_keep_recent = 2
    return cfg


class _Violation:
    """Accumulator: the soak runs to completion and reports EVERY
    broken assert, not just the first (a plateau breach at checkpoint
    40 and a marker skew at 90 are different bugs)."""

    def __init__(self):
        self.items: List[str] = []

    def check(self, ok: bool, msg: str) -> None:
        if not ok:
            self.items.append(msg)
            _log.error("soak violation", detail=msg)


def _check_markers(v: _Violation, node, where: str) -> None:
    bs = node.block_store
    base, height = bs.base(), bs.height()
    v.check(1 <= base <= height, f"{where}: base {base} outside [1, {height}]")
    v.check(
        bs.load_block(base) is not None,
        f"{where}: block {base} (the base) unreadable",
    )
    if base > 1:
        v.check(
            bs.load_block(base - 1) is None,
            f"{where}: block {base - 1} still present below base {base}",
        )
    ti = node.tx_indexer
    if ti is not None:
        ib = ti.base_height()
        last = ti.last_indexed_height()
        v.check(
            last == height,
            f"{where}: idx:last {last} != chain height {height}",
        )
        v.check(
            ib <= last + 1,
            f"{where}: idx:base {ib} ran ahead of idx:last {last}",
        )
        if ib > 1:
            # no orphan block-event row below the marker (the block
            # indexer shares the db and the idx:base advance)
            import struct

            key = (
                b"blk:e:block.height="
                + str(ib - 1).encode()
                + b":"
                + struct.pack(">Q", ib - 1)
            )
            v.check(
                ti.db.get(key) is None,
                f"{where}: block-event row at {ib - 1} below idx:base {ib}",
            )


def _check_rpc_pruned(v: _Violation, node, chain_id: str) -> None:
    """Every pruned height must answer with the structured error."""
    from ..rpc import core
    from ..rpc.env import Environment

    base = node.block_store.base()
    if base <= 1:
        return
    env = Environment(
        chain_id=chain_id,
        block_store=node.block_store,
        state_store=node.state_store,
        tx_indexer=node.tx_indexer,
        block_indexer=node.block_indexer,
        genesis=node.genesis,
        proxy=node.proxy,
        config=node.config,
        retention=node.retention,
    )
    try:
        core.block(env, height=base - 1)
        v.check(False, f"rpc: block({base - 1}) below base {base} did not error")
    except core.RPCError as e:
        v.check(
            "pruned" in (e.data or "") and f"base={base}" in str(e),
            f"rpc: below-base error not structured: {e} data={e.data!r}",
        )
    st = core.status(env)
    got = st["sync_info"]["earliest_block_height"]
    v.check(
        got == str(base),
        f"rpc: status earliest_block_height {got} != base {base}",
    )


def _check_snapshots(v: _Violation, node, keep_recent: int) -> None:
    ss = node.snapshot_store
    snaps = ss.list_snapshots()
    v.check(bool(snaps), "snapshots: none held after warmup")
    v.check(
        len(snaps) <= keep_recent,
        f"snapshots: {len(snaps)} held > keep_recent {keep_recent}",
    )
    for s in snaps:
        blob = ss.load_blob(s.height)
        v.check(
            blob is not None and hashlib.sha256(blob).digest() == s.hash,
            f"snapshots: blob at height {s.height} does not hash-verify",
        )


def run_soak(
    seed: int = 1337,
    heights: int = 10_000,
    step: int = 50,
    keys: int = 64,
    warmup_frac: float = 0.25,
    disk_factor: float = 1.5,
    rss_factor: float = 1.5,
    home: Optional[str] = None,
) -> dict:
    """Drive ``heights`` blocks through a lifecycle-enabled node in
    ``step``-height slices with a reconcile per slice; returns the
    report dict (``ok``, ``violations``, checkpoint series)."""
    import shutil

    from ..consensus.wal import WAL, _group_files
    from ..node.inprocess import build_node, make_genesis
    from ..utils.chaingen import make_chain

    own_home = home is None
    home = home or tempfile.mkdtemp(prefix="soak_")
    v = _Violation()
    checkpoints: List[dict] = []
    try:
        genesis, pvs = make_genesis(1, chain_id=f"soak-{seed}")
        privs = [pv.priv_key for pv in pvs]
        cfg = _soak_config(home)
        node = build_node(
            genesis, None, config=cfg, home=home, wal=True
        )
        # synthetic WAL group: the generator bypasses consensus, so
        # the soak writes the end-height records itself — tiny head
        # limit so rotation churns and the prune leg has sealed files
        # to collect every slice
        wal = WAL(node.cs._wal_path, head_size_limit=2048)
        keep_recent = cfg.storage.snapshot_keep_recent
        warmup_end = max(1, int((heights // step) * warmup_frac))
        disk_mark = rss_mark = None

        done = 0
        while done < heights:
            n = min(step, heights - done)
            for _ in range(n):
                h = node.block_store.height() + 1
                # bounded keyspace: k0..k{keys-1} overwritten forever
                node.mempool.check_tx(b"k%d=v%d" % (h % keys, h))
                make_chain(genesis, privs, 1, txs_per_block=0, node=node)
                wal.write_end_height(h)
            done += n
            out = node.retention.reconcile_once()
            ck = {
                "height": node.block_store.height(),
                "base": node.block_store.base(),
                "index_base": node.tx_indexer.base_height(),
                "disk_bytes": node.retention.disk_bytes(),
                "rss_bytes": _rss_bytes(),
                "wal_files": len(_group_files(node.cs._wal_path)),
                "pruned": out,
            }
            checkpoints.append(ck)
            i = len(checkpoints)
            _check_markers(v, node, f"ckpt {i} (h={ck['height']})")
            if i == warmup_end:
                disk_mark, rss_mark = ck["disk_bytes"], ck["rss_bytes"]
            elif i > warmup_end:
                # the plateau contract: past warmup the window is
                # saturated — later checkpoints may wobble (sqlite
                # page recycling, allocator noise) but never trend
                v.check(
                    ck["disk_bytes"] <= disk_mark * disk_factor,
                    f"ckpt {i}: disk {ck['disk_bytes']} > "
                    f"{disk_factor}x warmup mark {disk_mark}",
                )
                if ck["rss_bytes"] and rss_mark:
                    v.check(
                        ck["rss_bytes"] <= rss_mark * rss_factor
                        + 32 * 1024 * 1024,
                        f"ckpt {i}: rss {ck['rss_bytes']} > "
                        f"{rss_factor}x warmup mark {rss_mark} + 32MB",
                    )
                v.check(
                    ck["wal_files"] <= 8,
                    f"ckpt {i}: {ck['wal_files']} WAL group files — "
                    "rotation outran the prune leg",
                )
        wal.close()

        stats = node.retention.stats()
        v.check(
            stats["pruned_blocks_total"] > 0, "no blocks were ever pruned"
        )
        v.check(
            stats["pruned_index_total"] > 0, "no index rows were ever pruned"
        )
        v.check(
            stats["pruned_wal_files"] > 0, "no WAL files were ever pruned"
        )
        _check_rpc_pruned(v, node, genesis.chain_id)
        _check_snapshots(v, node, keep_recent)

        # restart leg: same home, fresh node — the handshake must
        # replay ONLY the retained tail (persisted app height), the
        # markers must survive, and the chain must extend cleanly
        pre_base = node.block_store.base()
        pre_height = node.block_store.height()
        node.close_stores()
        try:
            node2 = build_node(
                genesis, None, config=_soak_config(home), home=home, wal=True
            )
        except Exception as e:  # a replay-from-block-1 attempt lands here
            v.check(False, f"restart: rebuild from pruned home failed: {e!r}")
            node2 = None
        if node2 is not None:
            v.check(
                node2.block_store.base() == pre_base
                and node2.block_store.height() == pre_height,
                f"restart: store moved "
                f"({node2.block_store.base()},{node2.block_store.height()})"
                f" != ({pre_base},{pre_height})",
            )
            make_chain(genesis, privs, step, txs_per_block=0, node=node2)
            node2.retention.reconcile_once()
            _check_markers(v, node2, "post-restart")
            node2.close_stores()

        report = {
            "seed": seed,
            "heights": heights,
            "step": step,
            "warmup_checkpoints": warmup_end,
            "ok": not v.items,
            "violations": v.items,
            "retention": stats,
            "checkpoints": checkpoints,
        }
        return report
    finally:
        if own_home:
            shutil.rmtree(home, ignore_errors=True)


def soak_main(argv) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m cometbft_tpu.chaos soak",
        description="compressed-time storage lifecycle soak",
    )
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--heights", type=int, default=10_000)
    ap.add_argument("--step", type=int, default=50)
    ap.add_argument("--keys", type=int, default=64)
    ap.add_argument("--home", help="node home (default: fresh temp dir)")
    ap.add_argument("--json", help="write the report as JSON here")
    args = ap.parse_args(argv)

    report = run_soak(
        seed=args.seed,
        heights=args.heights,
        step=args.step,
        keys=args.keys,
        home=args.home,
    )
    last = report["checkpoints"][-1] if report["checkpoints"] else {}
    print(
        f"soak seed={report['seed']}: "
        f"{'OK' if report['ok'] else 'VIOLATIONS'}"
    )
    print(
        f"  heights={report['heights']} base={last.get('base')} "
        f"disk={last.get('disk_bytes')} rss={last.get('rss_bytes')}"
    )
    for k in (
        "pruned_blocks_total",
        "pruned_index_total",
        "pruned_wal_files",
        "snapshots_taken",
        "reconciles",
    ):
        print(f"  {k}={report['retention'][k]}")
    for item in report["violations"]:
        print(f"  VIOLATION: {item}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    return 0 if report["ok"] else 1
