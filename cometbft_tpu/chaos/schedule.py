"""Declarative fault schedules for the nemesis scheduler.

A schedule is an ordered list of FaultEvents. Events execute strictly
in order; each one waits for its trigger first:

- ``at_height=N`` — fire once the network's max committed height
  (over running nodes) reaches N. Use for events downstream of
  progress (a majority-side partition keeps committing, so its heal
  can be height-triggered).
- ``after_s=T`` — fire T seconds after the previous event executed
  (or after run start for the first event). Use when the trigger side
  cannot make progress (e.g. healing a 2-2 split that halts the
  chain).

Actions (mirroring the e2e runner's perturbations, but in-process,
deterministic and fast):

====================  =================================================
``partition``         ``groups=[[0,1],[2,3]]`` node-index groups; links
                      across groups go down (silent blackhole)
``heal``              all links back up
``set_link``          ``src``/``dst`` node indexes + ``link`` dict of
                      LinkState fields (loss, latency_s, jitter_s,
                      duplicate, reorder, up); ``symmetric`` (default
                      True) applies both directions
``crash``             ``node=i``: in-process power cut (Node.kill)
``restart``           ``node=i``: rebuild from the same home dir —
                      recovery runs WAL replay + ABCI handshake replay
``stall``             ``duration_s=T``: block the (shared in-process)
                      event loop with a synchronous callback for T
                      seconds — the loop-stall the obs watchdog's
                      flight recorder must catch mid-flight
                      (docs/OBS.md; the snapshot must contain
                      ``chaos_stall``)
``byzantine``         ``node=i``: corrupt the node's NEXT commit (its
                      stored block ID at that height is rewritten with
                      seeded tamper bytes). This simulates the
                      observable effect of a byzantine commit so the
                      AGREEMENT CHECKER ITSELF is validated — a
                      checker that cannot flag an injected fork proves
                      nothing (the same discipline Jepsen applies to
                      its checkers).
``crash_wave``        ``nodes=[i,...]``: power-cut the listed nodes in
                      order, ``stagger_s`` apart; after
                      ``restart_after_s`` restart them in the same
                      order (same stagger). ``blocksync=True`` rebuilds
                      the wave's nodes with blocksync + adaptive sync
                      enabled, so recovery exercises adaptive-sync
                      catchup under traffic instead of consensus
                      catch-up gossip.
``statesync_join``    a FRESH non-validator node joins mid-run by
                      statesync: snapshot discovery over p2p,
                      light-verified restore against the RPC of two
                      running nodes (``via=[i,j]``; defaults to the
                      first two running), then blocksync follows the
                      tail. Requires the net to run with RPC enabled
                      (run_schedule switches it on automatically when
                      the schedule contains this action) and a source
                      app snapshot (kvstore snapshots every 10
                      heights — trigger at height >= 11).
``valset_churn``      churn the validator set under load: submit a
                      power-change tx for validator ``node``'s key
                      (new power drawn from the MASTER rng in
                      [power_min, power_max], or pass ``power``
                      explicitly). Changes the valset hash + proposer
                      rotation live without adding absent signers.
``wal_torn_tail``     ``node=i``: power-cut the node (if running),
                      append seeded garbage (``garbage`` bytes, drawn
                      from the MASTER rng) to its consensus WAL head —
                      the torn partial record a real power cut leaves —
                      then restart it. Recovery must repair the tail
                      (consensus/wal.py truncate_corrupt_tail) and
                      extend the committed prefix unchanged.
``conn_kill``         ``node=i``: kill up to ``count`` (default: all)
                      of the node's live connections via pong-timeout
                      injection (MConnection.inject_error) — the conn
                      death a silent blackhole eventually produces,
                      without waiting out ping_interval+pong_timeout.
                      Persistent-peer reconnect (p2p/reconnect.py)
                      must heal every kill.
``lock_inversion``    deliberately exercise the runtime concurrency
                      sanitizer (analysis/runtime.py, docs/LINT.md
                      "Runtime sanitizer"): acquire two
                      sanitizer-wrapped locks in A-B then B-A order
                      (a deterministic ABBA inversion — the
                      lock-order graph records ORDER, not
                      contention) and touch a tagged loop-affine
                      probe from a foreign thread. The run asserts
                      the sanitizer REPORTS both (a sanitizer that
                      cannot flag an injected inversion proves
                      nothing — the same checker-validation
                      discipline as ``byzantine``); the injected
                      findings themselves are expected, not
                      violations.
``reconnect_storm``   ``node=i``: ``cycles`` repetitions of
                      {partition the victim off, pong-timeout-kill its
                      conns, hold ``hold_s``, heal, wait ``gap_s``} —
                      the compound that used to exhaust the finite
                      reconnect budget and permanently isolate a
                      healed minority. The self-healing plane must
                      re-converge after every heal (gated by the
                      ``p2p.reconnect`` span budget).
``scaling_probe``     run the committee-scaling exponent probe
                      (analysis/scaling.py, docs/LINT.md "Complexity
                      rules") mid-schedule in a worker thread: the
                      flagged hot-path sites are driven at small
                      committee sizes and their log-log exponents
                      judged against tools/scaling_budgets.toml. An
                      un-injected budget breach is a VIOLATION;
                      with ``inject_quadratic=True`` a deliberate
                      O(n^2) site (``chaos.``-prefixed, like
                      lock_inversion's probe locks) is planted and
                      the run asserts the probe FLAGS it — the same
                      checker-validation discipline.
``verify_storm``      run the unified-verify-scheduler storm
                      (chaos/verify_storm.py) in a worker thread: a
                      light-session storm + a blocksync-style
                      catch-up storm + a synthetic live-wave feeder,
                      all through the ONE process-wide scheduler the
                      net's own consensus is verifying on. Verdict
                      parity (bad signatures included) is asserted on
                      every ticket, the live class's p95 wall is
                      gated on ``live_budget_ms`` (the
                      crypto.sched.dispatch budget), and the catch-up
                      lane must keep completing tickets for the whole
                      ``storm_s`` — a starved lane, a breached live
                      budget, or a diverged verdict is a VIOLATION.
``crash_mid_prune``   ``node=i``: abort a retention reconcile pass
                      after ``abort_after`` bounded batches (drawn
                      from the MASTER rng when unset — the crash
                      lands at a seeded batch boundary), power-cut
                      the node, restart it and run one resume pass.
                      Every batch commits its deletes + base-marker
                      advance atomically (store/retention.py), so
                      the partial pass must read as a consistent
                      less-pruned store, the restart must pass the
                      WAL-replay checker, and the resume must finish
                      the same targets idempotently. Requires the
                      lifecycle storage knobs — run_schedule auto-
                      sets them when this action is scheduled.
``snapshot_during_prune`` ``node=i``: park a reconcile pass
                      mid-batch, then serve the node's newest
                      on-disk snapshot chunk-by-chunk under the
                      in-flight-serve pin while the pass is live;
                      the reassembled blob must hash-verify and the
                      snapshot must not rotate away while pinned
                      (the serve-floor contract). Trigger past the
                      app's snapshot cadence (kvstore: height >=
                      11). Auto-sets the storage knobs like
                      ``crash_mid_prune``.
``replica_kill``      kill one serving-fleet follower replica
                      mid-stream (``replica=i``, or a seeded draw
                      from the MASTER rng when unset). Requires the
                      net to run with a fleet attached
                      (``run_schedule(..., fleet=N)``); the
                      SessionRouter must fail the dead replica's
                      sessions over to the survivors with ZERO lost
                      commits — every resumed subscriber's stream is
                      store-verified gap-free — and lag shedding must
                      stay isolated to the killed replica's own
                      clients (docs/FLEET.md).
====================  =================================================

Schedules round-trip through JSON so failing runs can be archived and
replayed byte-for-byte alongside their seed.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

ACTIONS = (
    "partition", "heal", "set_link", "crash", "restart", "byzantine",
    "stall", "crash_wave", "statesync_join", "valset_churn",
    "wal_torn_tail", "conn_kill", "reconnect_storm", "lock_inversion",
    "scaling_probe", "crash_mid_prune", "snapshot_during_prune",
    "verify_storm", "replica_kill",
)


@dataclass
class FaultEvent:
    action: str
    at_height: Optional[int] = None
    after_s: Optional[float] = None
    groups: Optional[List[List[int]]] = None  # partition
    node: Optional[int] = None  # crash / restart / byzantine
    src: Optional[int] = None  # set_link
    dst: Optional[int] = None  # set_link
    link: Optional[Dict[str, float]] = None  # set_link LinkState fields
    symmetric: bool = True  # set_link: apply both directions
    duration_s: Optional[float] = None  # stall: loop-block length
    nodes: Optional[List[int]] = None  # crash_wave members, in order
    stagger_s: float = 0.2  # crash_wave: gap between wave members
    restart_after_s: Optional[float] = 1.0  # crash_wave: None = stay down
    blocksync: bool = False  # crash_wave restart: adaptive-sync catchup
    via: Optional[List[int]] = None  # statesync_join: RPC source nodes
    power: Optional[int] = None  # valset_churn: explicit new power
    power_min: int = 5  # valset_churn: seeded draw range
    power_max: int = 15
    garbage: Optional[int] = None  # wal_torn_tail: torn bytes (seeded)
    count: Optional[int] = None  # conn_kill: conns to kill (None=all)
    cycles: int = 2  # reconnect_storm: partition/heal repetitions
    hold_s: float = 1.2  # reconnect_storm: partition hold per cycle
    gap_s: float = 0.8  # reconnect_storm: healed gap between cycles
    inject_quadratic: bool = False  # scaling_probe: plant an O(n^2) site
    abort_after: Optional[int] = None  # crash_mid_prune: batches before
    # the abort (None = seeded draw from the MASTER rng)
    storm_s: float = 1.5  # verify_storm: storm duration
    live_budget_ms: float = 2500.0  # verify_storm: live-class p95 gate
    # (the crypto.sched.dispatch budget, tools/span_budgets.toml)
    replica: Optional[int] = None  # replica_kill: fleet replica index
    # (None = seeded draw from the MASTER rng)

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        if (self.at_height is None) == (self.after_s is None):
            raise ValueError(
                f"{self.action}: exactly one of at_height/after_s required"
            )
        if self.action == "partition" and not self.groups:
            raise ValueError("partition: groups required")
        if self.action in (
            "crash", "restart", "byzantine", "valset_churn",
            "wal_torn_tail", "conn_kill", "reconnect_storm",
            "crash_mid_prune", "snapshot_during_prune",
        ) and self.node is None:
            raise ValueError(f"{self.action}: node required")
        if self.action == "reconnect_storm" and self.cycles < 1:
            raise ValueError("reconnect_storm: cycles >= 1 required")
        if self.action == "set_link" and (
            self.src is None or self.dst is None or not self.link
        ):
            raise ValueError("set_link: src, dst and link required")
        if self.action == "stall" and not (
            self.duration_s and self.duration_s > 0
        ):
            raise ValueError("stall: duration_s > 0 required")
        if self.action == "crash_wave" and not self.nodes:
            raise ValueError("crash_wave: nodes required")
        if self.action == "valset_churn" and not (
            0 < self.power_min <= self.power_max
        ):
            raise ValueError("valset_churn: 0 < power_min <= power_max")


@dataclass
class FaultSchedule:
    events: List[FaultEvent] = field(default_factory=list)

    def to_json(self) -> str:
        """Minimal lossless form: fields still at their dataclass
        default are dropped (from_json restores the same defaults),
        so an event's JSON carries exactly what was set — generated
        matrices stay readable. An EXPLICIT None over a non-None
        default (crash_wave restart_after_s=None = "stay down") is
        kept as JSON null: dropping it would replay with the default
        and silently change semantics."""
        defaults = {
            f.name: f.default for f in dataclasses.fields(FaultEvent)
        }
        return json.dumps(
            [
                {
                    k: v
                    for k, v in asdict(e).items()
                    if k == "action" or v != defaults.get(k)
                }
                for e in self.events
            ],
            indent=2,
        )

    @classmethod
    def from_json(cls, raw: str) -> "FaultSchedule":
        return cls([FaultEvent(**d) for d in json.loads(raw)])


def default_schedule(byzantine_node: Optional[int] = None) -> FaultSchedule:
    """The canonical 4-node smoke schedule: majority partition at h2,
    heal at h4, crash node 1 at h5, restart it shortly after. With
    ``byzantine_node`` set, a commit corruption is injected after the
    heal — a run the agreement checker MUST flag."""
    events = [
        FaultEvent("partition", at_height=2, groups=[[0, 1, 2], [3]]),
        FaultEvent("heal", at_height=4),
    ]
    if byzantine_node is not None:
        events.append(FaultEvent("byzantine", at_height=4, node=byzantine_node))
    events += [
        FaultEvent("crash", at_height=5, node=1),
        FaultEvent("restart", after_s=0.5, node=1),
    ]
    return FaultSchedule(events)
