"""Declarative fault schedules for the nemesis scheduler.

A schedule is an ordered list of FaultEvents. Events execute strictly
in order; each one waits for its trigger first:

- ``at_height=N`` — fire once the network's max committed height
  (over running nodes) reaches N. Use for events downstream of
  progress (a majority-side partition keeps committing, so its heal
  can be height-triggered).
- ``after_s=T`` — fire T seconds after the previous event executed
  (or after run start for the first event). Use when the trigger side
  cannot make progress (e.g. healing a 2-2 split that halts the
  chain).

Actions (mirroring the e2e runner's perturbations, but in-process,
deterministic and fast):

====================  =================================================
``partition``         ``groups=[[0,1],[2,3]]`` node-index groups; links
                      across groups go down (silent blackhole)
``heal``              all links back up
``set_link``          ``src``/``dst`` node indexes + ``link`` dict of
                      LinkState fields (loss, latency_s, jitter_s,
                      duplicate, reorder, up); ``symmetric`` (default
                      True) applies both directions
``crash``             ``node=i``: in-process power cut (Node.kill)
``restart``           ``node=i``: rebuild from the same home dir —
                      recovery runs WAL replay + ABCI handshake replay
``stall``             ``duration_s=T``: block the (shared in-process)
                      event loop with a synchronous callback for T
                      seconds — the loop-stall the obs watchdog's
                      flight recorder must catch mid-flight
                      (docs/OBS.md; the snapshot must contain
                      ``chaos_stall``)
``byzantine``         ``node=i``: corrupt the node's NEXT commit (its
                      stored block ID at that height is rewritten with
                      seeded tamper bytes). This simulates the
                      observable effect of a byzantine commit so the
                      AGREEMENT CHECKER ITSELF is validated — a
                      checker that cannot flag an injected fork proves
                      nothing (the same discipline Jepsen applies to
                      its checkers).
====================  =================================================

Schedules round-trip through JSON so failing runs can be archived and
replayed byte-for-byte alongside their seed.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

ACTIONS = (
    "partition", "heal", "set_link", "crash", "restart", "byzantine",
    "stall",
)


@dataclass
class FaultEvent:
    action: str
    at_height: Optional[int] = None
    after_s: Optional[float] = None
    groups: Optional[List[List[int]]] = None  # partition
    node: Optional[int] = None  # crash / restart / byzantine
    src: Optional[int] = None  # set_link
    dst: Optional[int] = None  # set_link
    link: Optional[Dict[str, float]] = None  # set_link LinkState fields
    symmetric: bool = True  # set_link: apply both directions
    duration_s: Optional[float] = None  # stall: loop-block length

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        if (self.at_height is None) == (self.after_s is None):
            raise ValueError(
                f"{self.action}: exactly one of at_height/after_s required"
            )
        if self.action == "partition" and not self.groups:
            raise ValueError("partition: groups required")
        if self.action in ("crash", "restart", "byzantine") and (
            self.node is None
        ):
            raise ValueError(f"{self.action}: node required")
        if self.action == "set_link" and (
            self.src is None or self.dst is None or not self.link
        ):
            raise ValueError("set_link: src, dst and link required")
        if self.action == "stall" and not (
            self.duration_s and self.duration_s > 0
        ):
            raise ValueError("stall: duration_s > 0 required")


@dataclass
class FaultSchedule:
    events: List[FaultEvent] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            [
                {k: v for k, v in asdict(e).items() if v is not None}
                for e in self.events
            ],
            indent=2,
        )

    @classmethod
    def from_json(cls, raw: str) -> "FaultSchedule":
        return cls([FaultEvent(**d) for d in json.loads(raw)])


def default_schedule(byzantine_node: Optional[int] = None) -> FaultSchedule:
    """The canonical 4-node smoke schedule: majority partition at h2,
    heal at h4, crash node 1 at h5, restart it shortly after. With
    ``byzantine_node`` set, a commit corruption is injected after the
    heal — a run the agreement checker MUST flag."""
    events = [
        FaultEvent("partition", at_height=2, groups=[[0, 1, 2], [3]]),
        FaultEvent("heal", at_height=4),
    ]
    if byzantine_node is not None:
        events.append(FaultEvent("byzantine", at_height=4, node=byzantine_node))
    events += [
        FaultEvent("crash", at_height=5, node=1),
        FaultEvent("restart", after_s=0.5, node=1),
    ]
    return FaultSchedule(events)
