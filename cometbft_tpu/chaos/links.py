"""Link fault plane: per-(src, dst) network faults for MemoryTransport.

The LinkTable is the pluggable ``link_hook`` of
``p2p.transport.MemoryTransport``: every in-memory connection side is
wrapped in a ChaosConnection that consults the table's mutable
per-directed-link state on each write. Supported faults:

- **partition** (``up=False``): writes blackhole silently (the
  connection stays up; reliability comes from the consensus reactor's
  gossip retransmission once the link heals) and new dials are
  refused;
- **loss**: one-way drop probability per message;
- **latency + jitter**: fixed delay plus uniform jitter per message
  (applied in the sender's write path, preserving per-link ordering
  like a real FIFO link);
- **duplication**: the message is written twice;
- **reordering**: the message is held back and swapped with the next
  write on the same link (a held message still pending at close is
  dropped — reordering degrades to loss at stream end).

Determinism: the table owns a master ``random.Random(seed)`` (used by
the nemesis scheduler for schedule-level draws); each directed link
draws from its own ``random.Random`` derived from the master seed and
the link's stable (src, dst) key. Per-link decision streams are
therefore a pure function of (seed, link, op index) — independent of
cross-link scheduler interleaving — which is what makes a failing run
replayable: same seed + same schedule => same decision stream on
every link. Each decision is appended to a bounded per-link log (the
fault trace).

Reordering/duplication caveat: faults land between the mux layer and
the wire, so a reordered or duplicated mid-message chunk corrupts
MConnection framing and tears the connection down — which the p2p
stack must survive (persistent-peer reconnect). Invariant schedules
that want steady progress keep those probabilities at 0 and use
partitions/loss/latency instead.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, replace
from typing import Dict, Iterable, List, Optional, Tuple

from ..p2p.fuzz import FuzzConnConfig, FuzzedConnection

# decision codes recorded in the per-link trace
DROP_PARTITION = "P"
DROP_LOSS = "L"
DUPLICATE = "2"
HOLD_REORDER = "R"
PASS = "."

_TRACE_LIMIT = 20_000


@dataclass
class LinkState:
    """Mutable fault state of one directed link."""

    up: bool = True
    loss: float = 0.0
    latency_s: float = 0.0
    jitter_s: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0


class LinkTable:
    """Per-(src, dst) link states + seeded randomness + fault trace.

    Satisfies MemoryTransport's ``link_hook`` protocol:
    ``allow_dial(src, dst)`` and ``wrap(sconn, src, dst)``.
    """

    def __init__(
        self,
        seed: int,
        default: Optional[LinkState] = None,
        fuzz_config: Optional[FuzzConnConfig] = None,
    ):
        self.seed = seed
        self.rng = random.Random(seed)  # master: nemesis-level draws
        self.default = default or LinkState()
        self.fuzz_config = fuzz_config  # optional composed conn fuzzer
        self._links: Dict[Tuple[str, str], LinkState] = {}
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self._decisions: Dict[Tuple[str, str], List[str]] = {}

    # --- state --------------------------------------------------------

    def link(self, src: str, dst: str) -> LinkState:
        key = (src, dst)
        st = self._links.get(key)
        if st is None:
            st = self._links[key] = replace(self.default)
        return st

    def set_link(self, src: str, dst: str, **fields) -> None:
        """Mutate one directed link while the network runs."""
        st = self.link(src, dst)
        for k, v in fields.items():
            if not hasattr(st, k):
                raise ValueError(f"unknown link fault field {k!r}")
            setattr(st, k, v)

    def set_symmetric(self, a: str, b: str, **fields) -> None:
        self.set_link(a, b, **fields)
        self.set_link(b, a, **fields)

    def partition(self, groups: Iterable[Iterable[str]]) -> None:
        """Partition the named nodes into isolated groups: links
        between different groups go down, links within a group come
        back up. Nodes absent from every group are untouched."""
        gs = [list(g) for g in groups]
        for i, ga in enumerate(gs):
            for j, gb in enumerate(gs):
                for a in ga:
                    for b in gb:
                        if a != b:
                            self.link(a, b).up = i == j

    def heal(self) -> None:
        """Bring every link back up (other faults keep their state)."""
        for st in self._links.values():
            st.up = True

    # --- transport hook protocol --------------------------------------

    def allow_dial(self, src: str, dst: str) -> bool:
        return self.link(src, dst).up and self.link(dst, src).up

    def wrap(self, sconn, src: str, dst: str):
        if self.fuzz_config is not None and self.fuzz_config.enable:
            # compose with the point fuzzer (p2p/fuzz.py), sharing the
            # link's deterministic stream
            sconn = FuzzedConnection(
                sconn, self.fuzz_config, rng=self.rng_for(src, dst)
            )
        return ChaosConnection(sconn, self, src, dst)

    # --- determinism / trace ------------------------------------------

    def rng_for(self, src: str, dst: str) -> random.Random:
        """The directed link's private stream: derived from the master
        seed + stable link key, persistent across reconnects, so its
        decision sequence depends only on the link's own op index."""
        key = (src, dst)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = random.Random(
                f"{self.seed}|{src}->{dst}"
            )
        return rng

    def record(self, src: str, dst: str, code: str) -> None:
        log = self._decisions.setdefault((src, dst), [])
        if len(log) < _TRACE_LIMIT:
            log.append(code)

    def decision_log(self, src: str, dst: str) -> str:
        return "".join(self._decisions.get((src, dst), []))

    def decision_counts(self) -> Dict[str, Dict[str, int]]:
        """{src->dst: {code: count}} summary for reports."""
        out: Dict[str, Dict[str, int]] = {}
        for (src, dst), log in sorted(self._decisions.items()):
            counts: Dict[str, int] = {}
            for c in log:
                counts[c] = counts.get(c, 0) + 1
            out[f"{src[:8]}->{dst[:8]}"] = counts
        return out


class ChaosConnection:
    """SecretConnection-surface wrapper applying the (src, dst) link's
    faults to every outbound message. Reads pass through — one-way
    semantics come from each side wrapping its own write direction."""

    def __init__(self, sconn, table: LinkTable, src: str, dst: str):
        self._sconn = sconn
        self._table = table
        self._src = src
        self._dst = dst
        self._rng = table.rng_for(src, dst)
        self._held: Optional[bytes] = None

    def __getattr__(self, name):
        # identity/lifecycle passthrough (remote_pubkey, ...)
        return getattr(self._sconn, name)

    async def write_msg(self, data: bytes) -> int:
        st = self._table.link(self._src, self._dst)
        rec = self._table.record
        if not st.up:
            rec(self._src, self._dst, DROP_PARTITION)
            return len(data)  # blackhole: sender believes it sent
        if st.loss > 0 and self._rng.random() < st.loss:
            rec(self._src, self._dst, DROP_LOSS)
            return len(data)
        if st.latency_s > 0 or st.jitter_s > 0:
            delay = st.latency_s
            if st.jitter_s > 0:
                delay += self._rng.random() * st.jitter_s
            if delay > 0:
                await asyncio.sleep(delay)
        out = [data]
        if (
            st.reorder > 0
            and self._held is None
            and self._rng.random() < st.reorder
        ):
            self._held = data
            rec(self._src, self._dst, HOLD_REORDER)
            return len(data)
        if self._held is not None:
            out.append(self._held)  # delivered AFTER the newer message
            self._held = None
        if st.duplicate > 0 and self._rng.random() < st.duplicate:
            out.append(data)
            rec(self._src, self._dst, DUPLICATE)
        else:
            rec(self._src, self._dst, PASS)
        n = 0
        for frame in out:
            n += await self._sconn.write_msg(frame)
        return n

    async def read_chunk(self) -> bytes:
        return await self._sconn.read_chunk()

    async def read_msg(self) -> bytes:
        return await self._sconn.read_msg()

    def close(self) -> None:
        self._held = None  # reorder hold-back degrades to loss at close
        self._sconn.close()
