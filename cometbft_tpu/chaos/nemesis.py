"""Nemesis: executes a declarative fault schedule against a ChaosNet.

Events run strictly in order. Height triggers poll the network's max
committed height over running nodes; time triggers are relative to the
previous event's execution. Every executed event is appended to
``trace`` with its CONFIGURED trigger plus any seed-derived parameters
(e.g. the byzantine tamper bytes, drawn from the LinkTable's master
rng in schedule order) — so two runs with the same seed + schedule
produce byte-identical traces, and per-link message-level decisions
are separately deterministic by (seed, link, op index)
(chaos/links.py). That pair is the replay contract printed on any
invariant violation.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import List

from ..utils.log import get_logger
from .invariants import InvariantViolation
from .schedule import FaultEvent, FaultSchedule

_log = get_logger("chaos.nemesis")

_POLL_S = 0.05


def chaos_stall(duration_s: float) -> None:
    """Deliberately block the event loop with a synchronous callback —
    the fault the obs watchdog's flight recorder exists to catch. The
    function name is the needle: a correct flight record's loop-thread
    snapshot (and this frame inside it) must contain ``chaos_stall``.
    ``time.sleep`` releases the GIL, so the off-loop monitor threads
    observe the stall mid-flight and snapshot THIS frame."""
    time.sleep(duration_s)


class Nemesis:
    def __init__(self, net, schedule: FaultSchedule):
        self.net = net
        self.schedule = schedule
        self.trace: List[dict] = []

    async def run(self) -> None:
        for i, ev in enumerate(self.schedule.events):
            await self._wait_trigger(ev)
            record = await self._execute(ev)
            record.update(
                index=i,
                action=ev.action,
                at_height=ev.at_height,
                after_s=ev.after_s,
            )
            self.trace.append(record)
            _log.info("nemesis event", **{
                k: v for k, v in record.items() if v is not None
            })

    async def _wait_trigger(self, ev: FaultEvent) -> None:
        if ev.after_s is not None:
            await asyncio.sleep(ev.after_s)
            return
        while self.net.max_height() < ev.at_height:
            if not self.net.running_nodes():
                # a dead network can never commit: waiting would hang
                # the run forever — surface it as a liveness violation
                raise InvariantViolation(
                    "liveness",
                    f"{ev.action} trigger at_height={ev.at_height} "
                    "unreachable: no nodes running",
                )
            await asyncio.sleep(_POLL_S)

    async def _execute(self, ev: FaultEvent) -> dict:
        net = self.net
        if ev.action == "partition":
            groups = [
                [net.nodes[i].node_id for i in g] for g in ev.groups
            ]
            net.table.partition(groups)
            return {
                "groups": [
                    [net.nodes[i].name for i in g] for g in ev.groups
                ]
            }
        if ev.action == "heal":
            net.table.heal()
            return {}
        if ev.action == "set_link":
            src = net.nodes[ev.src]
            dst = net.nodes[ev.dst]
            if ev.symmetric:
                net.table.set_symmetric(
                    src.node_id, dst.node_id, **ev.link
                )
            else:
                net.table.set_link(src.node_id, dst.node_id, **ev.link)
            return {
                "src": src.name,
                "dst": dst.name,
                "link": dict(ev.link),
                "symmetric": ev.symmetric,
            }
        if ev.action == "crash":
            await net.crash(ev.node)
            return {"node": net.nodes[ev.node].name}
        if ev.action == "restart":
            await net.restart(ev.node)
            return {"node": net.nodes[ev.node].name}
        if ev.action == "stall":
            # runs ON the loop on purpose: every in-process node
            # shares it, so every node's watchdog sees the stall
            chaos_stall(ev.duration_s)
            return {"duration_s": ev.duration_s}
        if ev.action == "crash_wave":
            crashed = []
            for n in ev.nodes:
                await net.crash(n)
                crashed.append(net.nodes[n].name)
                if ev.stagger_s > 0 and n != ev.nodes[-1]:
                    await asyncio.sleep(ev.stagger_s)
            restarted = []
            if ev.restart_after_s is not None:
                await asyncio.sleep(ev.restart_after_s)
                for n in ev.nodes:
                    if ev.blocksync:
                        # adaptive-sync catchup under traffic: the
                        # rebuilt node blocksyncs the gap while its
                        # consensus state machine already runs
                        net.nodes[n].build_overrides.update(
                            {
                                "blocksync.enable": True,
                                "blocksync.adaptive_sync": True,
                            }
                        )
                    try:
                        await net.restart(n)
                    finally:
                        if ev.blocksync:
                            # scoped to THIS wave's restart: a later
                            # plain crash/restart of the same node in
                            # the schedule must not silently inherit
                            # the blocksync path
                            for k in (
                                "blocksync.enable",
                                "blocksync.adaptive_sync",
                            ):
                                net.nodes[n].build_overrides.pop(
                                    k, None
                                )
                    restarted.append(net.nodes[n].name)
                    if ev.stagger_s > 0 and n != ev.nodes[-1]:
                        await asyncio.sleep(ev.stagger_s)
            return {
                "crashed": crashed,
                "restarted": restarted,
                "blocksync": ev.blocksync,
            }
        if ev.action == "conn_kill":
            net.kill_conns(ev.node, count=ev.count)
            # trace determinism: record the victim + HOW MANY we asked
            # for, not the momentary peer set (wall-clock-dependent)
            return {
                "node": net.nodes[ev.node].name,
                "count": ev.count,
            }
        if ev.action == "reconnect_storm":
            # repeated partition/heal cycles + targeted pong-timeout
            # conn kills: the compound that used to exhaust the finite
            # reconnect budget and permanently isolate the victim —
            # the self-healing plane must re-converge after EVERY heal
            victim = ev.node
            others = [
                i for i in range(len(net.nodes)) if i != victim
            ]
            for cycle in range(ev.cycles):
                net.table.partition([
                    [net.nodes[i].node_id for i in others],
                    [net.nodes[victim].node_id],
                ])
                net.kill_conns(victim)
                await asyncio.sleep(ev.hold_s)
                net.table.heal()
                await asyncio.sleep(ev.gap_s)
            return {
                "node": net.nodes[victim].name,
                "cycles": ev.cycles,
                "hold_s": ev.hold_s,
                "gap_s": ev.gap_s,
            }
        if ev.action == "lock_inversion":
            # deterministic sanitizer exercise (analysis/runtime.py):
            # sequential ABBA + a foreign-thread affinity touch — no
            # timing race, so detection replays from the seed line
            from ..analysis.runtime import inject_lock_inversion

            return inject_lock_inversion()
        if ev.action == "scaling_probe":
            # committee-scaling exponent probe (analysis/scaling.py):
            # pure-CPU timing loops, so it runs in a worker thread —
            # blocking the loop here would trip the stall detector
            # the matrix itself polices. Results accumulate in the
            # module drain; net.py folds them into the report after
            # the run (sanitizer-findings discipline).
            from ..analysis.scaling import probe_for_chaos

            return await asyncio.to_thread(
                probe_for_chaos, ev.inject_quadratic
            )
        if ev.action == "verify_storm":
            # three-class storm through the ONE process-wide verify
            # scheduler the net's live consensus shares — worker
            # thread for the same loop-stall reason as scaling_probe
            from .verify_storm import storm_for_chaos

            return await asyncio.to_thread(
                storm_for_chaos, ev.storm_s, ev.live_budget_ms
            )
        if ev.action == "statesync_join":
            name = await net.statesync_join(via=ev.via)
            return {"joined": name}
        if ev.action == "valset_churn":
            # the new power comes from the MASTER rng unless pinned:
            # schedule execution is sequential, so the draw is
            # deterministic per (seed, schedule)
            power = ev.power
            if power is None:
                power = net.table.rng.randint(
                    ev.power_min, ev.power_max
                )
            return net.valset_churn(ev.node, power)
        if ev.action == "wal_torn_tail":
            # torn bytes from the MASTER rng, same determinism rule
            n = ev.garbage or 37
            garbage = bytes(
                net.table.rng.getrandbits(8) for _ in range(n)
            )
            rec = await net.wal_torn_tail(ev.node, garbage)
            rec["garbage_sha8"] = hashlib.sha256(garbage).hexdigest()[:8]
            return rec
        if ev.action == "crash_mid_prune":
            # the abort batch index comes from the MASTER rng unless
            # pinned: schedule execution is sequential, so the crash
            # lands at a deterministic batch boundary per (seed,
            # schedule) — the byte-identical-replay contract
            abort_after = ev.abort_after
            if abort_after is None:
                abort_after = net.table.rng.randint(1, 3)
            return await net.crash_mid_prune(ev.node, abort_after)
        if ev.action == "snapshot_during_prune":
            return await net.snapshot_during_prune(ev.node)
        if ev.action == "replica_kill":
            # the victim replica comes from the MASTER rng unless
            # pinned: schedule execution is sequential, so the draw is
            # deterministic per (seed, schedule)
            idx = ev.replica
            if idx is None:
                idx = net.table.rng.randint(
                    0, max(0, net.fleet_size() - 1)
                )
            return await net.replica_kill(idx)
        if ev.action == "byzantine":
            # tamper bytes come from the MASTER rng: schedule execution
            # is sequential, so the draw is deterministic per run
            tamper = bytes(
                net.table.rng.getrandbits(8) for _ in range(32)
            )
            net.inject_commit_corruption(ev.node, tamper)
            return {
                "node": net.nodes[ev.node].name,
                "tamper": tamper.hex()[:16],
            }
        raise ValueError(f"unknown action {ev.action!r}")
