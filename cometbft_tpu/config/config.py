"""Node configuration (reference config/config.go, 12 sections + TOML).

Dataclass-backed with TOML round-trip (tomllib read; simple writer).
Includes the fork-added sections: BlockSync.adaptive_sync
(config.go:1194) and the crypto backend selection for the TPU verifier.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field
from typing import List, Optional

try:
    import tomllib
except ImportError:  # pragma: no cover - py<3.11: same-API backport
    try:
        import tomli as tomllib
    except ImportError:
        tomllib = None


@dataclass
class BaseConfig:
    chain_id: str = ""
    moniker: str = "tpu-node"
    db_backend: str = "sqlite"
    db_dir: str = "data"
    log_level: str = "info"
    genesis_file: str = "config/genesis.json"
    priv_validator_key_file: str = "config/priv_validator_key.json"
    priv_validator_state_file: str = "data/priv_validator_state.json"
    node_key_file: str = "config/node_key.json"
    # when set, keys live with a REMOTE signer that dials in here
    # (reference PrivValidatorListenAddr)
    priv_validator_laddr: str = ""
    abci: str = "kvstore"
    # out-of-process app: address of an abci.server.ABCIServer /
    # GRPCServer (reference proxy_app, config/config.go Base); when set
    # (and abci is "socket" or "grpc") the node dials instead of
    # building an in-process app
    proxy_app: str = ""
    filter_peers: bool = False


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    # legacy gRPC broadcast API (Ping/BroadcastTx) beside JSON-RPC
    # (reference GRPCListenAddress, rpc/grpc/api.go); "" = disabled
    grpc_laddr: str = ""
    max_open_connections: int = 900
    max_subscription_clients: int = 100
    timeout_broadcast_tx_commit_s: float = 10.0
    # expose the unsafe route set (reference --rpc.unsafe: dial_seeds,
    # dial_peers, unsafe_flush_mempool); never enable on public nodes
    unsafe: bool = False


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    external_address: str = ""
    seeds: str = ""
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    flush_throttle_ms: int = 10
    max_packet_msg_payload_size: int = 1024
    send_rate: int = 5_120_000
    recv_rate: int = 5_120_000
    pex: bool = True
    seed_mode: bool = False
    handshake_timeout_s: float = 20.0
    dial_timeout_s: float = 3.0
    use_libp2p_equivalent: bool = False  # fork: lp2p transport selection
    use_autopool: bool = False  # fork: autopool reactor msg draining
    # --- self-healing connectivity plane (p2p/reconnect.py) -----------
    # full-jitter backoff for the per-peer fast reconnect lane
    reconnect_base_s: float = 1.0
    reconnect_cap_s: float = 30.0
    # fast-lane dial BUDGET per outage (not a give-up bound: spending
    # it parks the peer in the never-give-up slow lane)
    reconnect_fast_attempts: int = 12
    # slow-lane sweep period: steady-state redial load for peers whose
    # fast budget is spent
    reconnect_slow_interval_s: float = 30.0
    # zero peers for this long = starving (PEX re-learn storm on every
    # dial success; cometbft_p2p_starvation_seconds accumulates)
    starvation_s: float = 10.0
    # RPC health `connectivity` verdict: degraded below this many
    # peers (once the node has evidence it is meant to be connected)
    min_peers: int = 1


@dataclass
class MempoolConfig:
    type_: str = "clist"  # clist | nop | app (fork)
    recheck: bool = True
    broadcast: bool = True
    size: int = 5000
    cache_size: int = 10000
    max_tx_bytes: int = 1024 * 1024
    max_txs_bytes: int = 64 * 1024 * 1024
    # ingest plane (docs/PERF.md "Mempool ingest plane"): micro-batch
    # coalescing in front of CheckTx — max txs per batch, and how long
    # the drainer waits after the first tx before flushing a partial
    # batch (latency bound for a lone RPC submission)
    batch_max_txs: int = 256
    batch_flush_ms: float = 2.0
    # post-commit recheck off the consensus critical section:
    # update() snapshots and returns; verdicts apply in the
    # background, height-guarded, with unrechecked txs masked from
    # reap. Off = the reference's synchronous recheck-inside-update.
    async_recheck: bool = True


@dataclass
class StateSyncConfig:
    enable: bool = False
    rpc_servers: List[str] = field(default_factory=list)
    trust_height: int = 0
    trust_hash: str = ""
    trust_period_s: float = 168 * 3600.0
    discovery_time_s: float = 15.0
    chunk_request_timeout_s: float = 10.0


@dataclass
class BlockSyncConfig:
    enable: bool = True
    adaptive_sync: bool = False  # fork feature (config.go:1194)


@dataclass
class ConsensusConfig:
    wal_file: str = "data/cs.wal/wal"
    timeout_propose_s: float = 3.0
    timeout_propose_delta_s: float = 0.5
    timeout_prevote_s: float = 1.0
    timeout_prevote_delta_s: float = 0.5
    timeout_precommit_s: float = 1.0
    timeout_precommit_delta_s: float = 0.5
    timeout_commit_s: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval_s: float = 0.0
    peer_gossip_sleep_s: float = 0.1
    peer_query_maj23_sleep_s: float = 2.0
    # max allowed difference between proposed block time and wall clock
    # (reference config/config.go:1265-1286, default 60s; 0 disables)
    block_time_tolerance_ns: int = 60_000_000_000
    # --- live-consensus fast path (docs/PERF.md) ---------------------
    # WAL group commit: sync-barrier records written within this
    # window coalesce into ONE fsync (consensus/wal.py write_group);
    # externalization (own vote/proposal broadcast) is deferred until
    # the covering fsync lands, so the WAL-before-act contract holds
    # with a bounded (~window) barrier. Routing is calibrated: the
    # seam only engages when the measured fsync cost exceeds the
    # ticket-handoff cost (slow sync-through disks), so a cached-NVMe
    # box keeps the strict inline barrier automatically. 0 disables
    # the seam entirely (the reference's one-fsync-per-barrier path).
    wal_group_commit_ms: float = 2.0
    # in-round vote-verify micro-batching: peer votes for the current
    # height arriving within this window are signature-verified as one
    # batch through the crypto coalesce/parallel engine and resolve as
    # cache hits in add_vote (the blocksync pre-verify pattern applied
    # to live rounds). 0 (default) = serial inline verification — the
    # batch only wins once committee vote waves are large enough to
    # out-earn the dispatch handoff (docs/PERF.md); the p2p reactor's
    # always-on coalescing continues to serve networked nodes either
    # way.
    vote_batch_window_ms: float = 0.0
    # pipelined finalize: block persist + WAL end-height + ABCI apply
    # run off-loop (one in-flight height, barrier before the next
    # commit) while the loop keeps relaying gossip; next-height
    # messages park and replay at height entry. Off = the reference's
    # blocking finalize.
    finalize_pipeline: bool = False
    # native finalize lane riding the pipeline (docs/PERF.md): the
    # hash/encode/persist leg of the ABCI apply (one GIL-releasing
    # native pass per block, state/native_finalize.py) takes a second
    # to_thread hop so the loop keeps relaying gossip through it.
    # Only engages when finalize_pipeline is on; off = the apply runs
    # whole on-loop exactly like the serial path.
    finalize_offload_apply: bool = True

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose_s + self.timeout_propose_delta_s * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote_s + self.timeout_prevote_delta_s * round_

    def precommit_timeout(self, round_: int) -> float:
        return (
            self.timeout_precommit_s + self.timeout_precommit_delta_s * round_
        )


@dataclass
class StorageConfig:
    discard_abci_responses: bool = False
    # --- retention plane (store/retention.py, docs/STORAGE.md) -----
    # blocks/states/index rows kept behind the committed head; 0 =
    # retain everything (reference semantics — pruning entirely off).
    # The effective prune target is min-reconciled with the app's
    # retain_height from ABCI Commit; node-side windows only ever
    # TIGHTEN what the app allows, never override it upward.
    retain_blocks: int = 0
    retain_states: int = 0
    retain_index: int = 0
    # background reconcile cadence + per-batch height budget: each
    # batch is ONE atomic write_batch (deletes + base-marker advance)
    # so a crash mid-prune resumes idempotently
    prune_interval_s: float = 10.0
    prune_batch: int = 100
    # node-side snapshot generation (statesync/snapshots.py): take an
    # on-disk chunked app snapshot every `snapshot_interval` heights
    # (0 = off), rotating to the newest `snapshot_keep_recent`
    snapshot_interval: int = 0
    snapshot_keep_recent: int = 2


@dataclass
class TxIndexConfig:
    indexer: str = "kv"  # kv | null | psql
    # connection string for the psql sink (reference [tx-index]
    # psql-conn); required when indexer = "psql"
    psql_conn: str = ""


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    # profiling listener (reference pprof_laddr, node/node.go:624):
    # serves /debug/pprof/{stacks,profile,heap} when set
    pprof_laddr: str = ""
    # stuck-await watchdog (the deadlock-detection analog, reference
    # libs/sync/deadlock.go): tasks suspended at the same await point
    # longer than this are reported with their stack; 0 disables
    watchdog_stall_s: float = 0.0
    # always-on tracing plane (cometbft_tpu/trace, docs/TRACE.md):
    # per-node fixed-memory event ring; the disabled fast path is a
    # single attribute check, the enabled cost is ~2us per span
    trace_enabled: bool = True
    # events retained per node (ring slots, preallocated; oldest
    # events are overwritten once the ring laps)
    trace_ring_size: int = 16384
    # cross-node causal tracing (docs/TRACE.md "Cross-node
    # timelines"): consensus/mempool/blocksync p2p messages carry a
    # compact trace-context stamp (origin, height/round/kind, send
    # instant) so receivers record correlated recv instants and the
    # `trace timeline` CLI can stitch all rings into one view.
    # Decoding and receive-side arrival recording are always on
    # (while the tracer is enabled); this only gates the OUTBOUND
    # stamp — and is moot while trace_enabled is false.
    trace_msg_stamp: bool = True
    # runtime health plane (cometbft_tpu/obs, docs/OBS.md): the
    # event-loop watchdog measures scheduling lag via a monotonic
    # heartbeat and fires the loop-stall flight recorder (thread +
    # task stack snapshot into the trace ring) when a callback blocks
    # the loop past the stall threshold. Always-on by default — the
    # heartbeat is one task wakeup per interval.
    loop_watchdog: bool = True
    # heartbeat period (the lag-sample rate; also bounds how quickly a
    # stall is noticed: detection latency ~ interval + stall threshold)
    loop_lag_interval_ms: float = 100.0
    # loop blocked longer than this => flight record (0 < stall)
    loop_stall_ms: float = 500.0
    # bounded shutdown (obs/shutdown.py, docs/OBS.md): per-stage
    # budget for Node._shutdown — a stage (reactor stops, peer
    # drain, consensus halt, store release) that overruns is
    # flight-recorded into the trace ring, cancelled, and if it
    # ignores the cancel, abandoned so the remaining stages (store
    # fd release above all) still run. Turns the stop-path wedge
    # class into a diagnosed bounded failure.
    shutdown_stage_budget_s: float = 5.0
    # runtime concurrency sanitizer (analysis/runtime.py, docs/LINT.md
    # "Runtime sanitizer"): lock-order graph with deadlock-potential
    # cycle detection, loop-affinity guard on hot-plane objects, and
    # stall attribution for the watchdog's flight records. The
    # enablement is PER-PROCESS and construction-time (hot-plane
    # locks are wrapped as planes are built), matching the per-
    # process lock-order graph. Default OFF for production nodes —
    # disabled mode costs nothing (raw locks come back unchanged);
    # config.test_config and the chaos net switch it ON, so the whole
    # tier-1 suite + 50-scenario matrix run sanitized.
    sanitizer: bool = False


@dataclass
class CryptoConfig:
    """TPU-native addition: signature-verification backend knobs.

    batch_backend names an entry in the crypto/batch.py backend
    registry: "tpu" (device lanes, host-routed batches ride the
    parallel plane), "cpu" (serial host baseline), "cpu-parallel"
    (multi-core host plane, crypto/parallel_verify — the production
    host policy when no device is reachable), "mesh" (multi-chip:
    lanes shard over every local device via the shard_map/
    PartitionSpec program, crypto/mesh_backend; DEGRADABLE — with
    fewer than two devices it verifies on the cpu-parallel host
    plane, so selecting it on a throttled no-mesh box is safe).
    Empty (the default) inherits the process-wide default
    (crypto/batch.set_default_backend — "tpu" unless the embedder
    changed it); a non-empty value is applied at node build
    (node/inprocess.build_node). The unified verify scheduler
    (crypto/scheduler.py) routes every consumer's batches by this
    backend. The parallel plane's own knobs are env-based:
    GRAFT_VERIFY_WORKERS / _TIER / _CHUNK_TARGET_MS / _MIN_PARALLEL
    (docs/PERF.md host plane)."""

    batch_backend: str = ""  # "" (inherit) | tpu | cpu | cpu-parallel | mesh
    min_batch_for_tpu: int = 2
    coalesce_window_ms: float = 2.0
    max_lanes: int = 131072


@dataclass
class FleetConfig:
    """TPU-native addition: serving-fleet knobs (cometbft_tpu/fleet,
    docs/FLEET.md). The SessionRouter in front of N follower replicas
    admits at most max_sessions concurrent routed sessions, holds
    consistency-token barrier waits to token_wait_s, degrades a
    replica stalled past max_lag_heights behind the committee head
    (checked every lag_poll_s), and on failover replays at most
    resume_replay_max heights from the store per resumed session
    (beyond that the session is shed honestly rather than resumed
    with a gap)."""

    max_sessions: int = 4096
    admit_timeout_s: float = 0.25
    max_lag_heights: int = 8
    lag_poll_s: float = 0.1
    token_wait_s: float = 2.0
    resume_replay_max: int = 512
    drain_timeout_s: float = 5.0


# single source of truth for the fault-injection knobs ([fuzz] TOML
# section, reference config/config.go:896)
from ..p2p.fuzz import FuzzConnConfig  # noqa: E402


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    statesync: StateSyncConfig = field(default_factory=StateSyncConfig)
    blocksync: BlockSyncConfig = field(default_factory=BlockSyncConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    tx_index: TxIndexConfig = field(default_factory=TxIndexConfig)
    instrumentation: InstrumentationConfig = field(
        default_factory=InstrumentationConfig
    )
    fuzz: FuzzConnConfig = field(default_factory=FuzzConnConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    root_dir: str = "."

    def path(self, rel: str) -> str:
        return os.path.join(self.root_dir, rel)


def default_config(root_dir: str = ".") -> Config:
    c = Config()
    c.root_dir = root_dir
    return c


def test_config(root_dir: str = ".") -> Config:
    """Short timeouts for in-process tests (reference config.TestConfig)."""
    c = default_config(root_dir)
    c.consensus.timeout_propose_s = 0.4
    c.consensus.timeout_propose_delta_s = 0.1
    c.consensus.timeout_prevote_s = 0.2
    c.consensus.timeout_prevote_delta_s = 0.1
    c.consensus.timeout_precommit_s = 0.2
    c.consensus.timeout_precommit_delta_s = 0.1
    c.consensus.timeout_commit_s = 0.1
    c.consensus.peer_gossip_sleep_s = 0.01
    c.base.db_backend = "memdb"
    c.rpc.laddr = "tcp://127.0.0.1:0"  # ephemeral port per test node
    c.p2p.laddr = "tcp://127.0.0.1:0"
    # tests run with the runtime concurrency sanitizer ON (the
    # "race detector in CI" default; docs/LINT.md)
    c.instrumentation.sanitizer = True
    return c


def load_toml(path: str) -> Config:
    assert tomllib is not None
    with open(path, "rb") as f:
        raw = tomllib.load(f)
    c = default_config(os.path.dirname(os.path.dirname(path)) or ".")
    for section, cls_name in (
        ("base", "base"),
        ("rpc", "rpc"),
        ("p2p", "p2p"),
        ("mempool", "mempool"),
        ("statesync", "statesync"),
        ("blocksync", "blocksync"),
        ("consensus", "consensus"),
        ("storage", "storage"),
        ("tx_index", "tx_index"),
        ("instrumentation", "instrumentation"),
        ("fuzz", "fuzz"),
        ("crypto", "crypto"),
        ("fleet", "fleet"),
    ):
        if section in raw:
            obj = getattr(c, cls_name)
            for k, v in raw[section].items():
                if hasattr(obj, k):
                    setattr(obj, k, v)
    return c


def write_toml(cfg: Config, path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def emit(name, obj):
        lines = [f"[{name}]"]
        for k, v in asdict(obj).items():
            if v is None:
                continue  # TOML has no null; absent key loads as default
            if isinstance(v, bool):
                lines.append(f"{k} = {'true' if v else 'false'}")
            elif isinstance(v, (int, float)):
                lines.append(f"{k} = {v}")
            elif isinstance(v, list):
                inner = ", ".join(f'"{x}"' for x in v)
                lines.append(f"{k} = [{inner}]")
            else:
                lines.append(f'{k} = "{v}"')
        return "\n".join(lines)

    sections = [
        ("base", cfg.base),
        ("rpc", cfg.rpc),
        ("p2p", cfg.p2p),
        ("mempool", cfg.mempool),
        ("statesync", cfg.statesync),
        ("blocksync", cfg.blocksync),
        ("consensus", cfg.consensus),
        ("storage", cfg.storage),
        ("tx_index", cfg.tx_index),
        ("instrumentation", cfg.instrumentation),
        ("fuzz", cfg.fuzz),
        ("crypto", cfg.crypto),
        ("fleet", cfg.fleet),
    ]
    with open(path, "w") as f:
        f.write("\n\n".join(emit(n, o) for n, o in sections) + "\n")
