from .config import (  # noqa: F401
    BaseConfig,
    BlockSyncConfig,
    Config,
    ConsensusConfig,
    MempoolConfig,
    P2PConfig,
    RPCConfig,
    StateSyncConfig,
    default_config,
)
