"""Minimal protobuf wire-format encoding (writer side) + varint framing.

The reference serializes every consensus artifact as gogo-protobuf
(reference proto/tendermint/*, canonical sign-bytes in
types/canonical.go, varint-delimited framing in libs/protoio). We only
need deterministic, self-consistent encodings — the hand-rolled writer
below emits standard proto wire format so sign bytes remain
canonical and portable without a codegen dependency.
"""

from __future__ import annotations

import struct

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


# one/two-byte fast paths: the overwhelming majority of varints in
# consensus artifacts are tags, lengths, and small ints (profiling a
# 10k-block replay showed ~9.5M varint calls = 26% of replay wall)
_V1 = [bytes([i]) for i in range(128)]
# offset by 128: no dead slots, and no non-canonical encodings exist
# anywhere in the table
_V2 = [
    bytes([(i & 0x7F) | 0x80, i >> 7]) for i in range(128, 1 << 14)
]


def varint(v: int) -> bytes:
    """Unsigned varint (LEB128)."""
    if 0 <= v < 128:
        return _V1[v]
    if 128 <= v < 1 << 14:
        return _V2[v - 128]
    if v < 0:
        v += 1 << 64  # two's-complement, 10 bytes, proto int64 semantics
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def field_varint(field: int, v: int) -> bytes:
    if v == 0:
        return b""
    return tag(field, WIRE_VARINT) + varint(v)


def field_sfixed64(field: int, v: int) -> bytes:
    if v == 0:
        return b""
    return tag(field, WIRE_FIXED64) + struct.pack("<q", v)


def field_bytes(field: int, v: bytes) -> bytes:
    if not v:
        return b""
    return tag(field, WIRE_BYTES) + varint(len(v)) + v


def field_string(field: int, v: str) -> bytes:
    return field_bytes(field, v.encode())


def field_message(field: int, v: bytes) -> bytes:
    """Embedded message: emitted even when empty iff v is not None."""
    if v is None:
        return b""
    return tag(field, WIRE_BYTES) + varint(len(v)) + v


def delimited(payload: bytes) -> bytes:
    """Length-prefixed framing (libs/protoio MarshalDelimited)."""
    return varint(len(payload)) + payload


def timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp from integer unix nanoseconds."""
    secs, nanos = divmod(ns, 1_000_000_000)
    return field_varint(1, secs) + field_varint(2, nanos)


# --- reader side --------------------------------------------------------


def read_varint(buf: bytes, pos: int):
    """Returns (value, new_pos); value fit to signed 64-bit."""
    shift = 0
    out = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")
    if out >= 1 << 63:
        out -= 1 << 64
    return out, pos


def parse(buf: bytes):
    """Parse a proto message into {field: [value, ...]} preserving order.

    varint/fixed -> int, length-delimited -> bytes. Unknown wire types
    raise (we only ever parse our own writer's output)."""
    import struct as _s

    if not isinstance(buf, (bytes, bytearray, memoryview)):
        # a mis-typed wire field (varint where a message was expected)
        # must surface as a decode error, not a TypeError
        raise ValueError(f"expected message bytes, got {type(buf).__name__}")
    out = {}
    pos = 0
    while pos < len(buf):
        key, pos = read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == WIRE_VARINT:
            v, pos = read_varint(buf, pos)
        elif wire == WIRE_FIXED64:
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64 field")
            (v,) = _s.unpack_from("<q", buf, pos)
            pos += 8
        elif wire == WIRE_BYTES:
            ln, pos = read_varint(buf, pos)
            v = bytes(buf[pos : pos + ln])
            if len(v) != ln:
                raise ValueError("truncated bytes field")
            pos += ln
        elif wire == WIRE_FIXED32:
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32 field")
            (v,) = _s.unpack_from("<i", buf, pos)
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def get1(msg, field, default=None):
    """First value of a field, typed by the default: a wire value whose
    type differs from the default's (varint where bytes are expected,
    or vice versa) raises ValueError — malformed input must surface as
    a decode error at the read, not an AttributeError/TypeError deep in
    a constructor (found by the hypothesis decode fuzz)."""
    vs = msg.get(field)
    if not vs:
        return default
    v = vs[0]
    if isinstance(default, (bytes, bytearray)):
        if not isinstance(v, (bytes, bytearray)):
            raise ValueError(
                f"field {field}: expected bytes, got {type(v).__name__}"
            )
    elif isinstance(default, int):
        if not isinstance(v, int):
            raise ValueError(
                f"field {field}: expected varint, got {type(v).__name__}"
            )
    return v


def parse_timestamp(b: bytes) -> int:
    if not b:
        return 0
    m = parse(b)
    return get1(m, 1, 0) * 1_000_000_000 + get1(m, 2, 0)


def read_delimited(buf: bytes, pos: int = 0):
    """Inverse of delimited(): returns (payload, new_pos)."""
    ln, pos = read_varint(buf, pos)
    if ln < 0 or pos + ln > len(buf):
        raise ValueError("truncated delimited message")
    return bytes(buf[pos : pos + ln]), pos + ln
