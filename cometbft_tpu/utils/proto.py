"""Minimal protobuf wire-format encoding (writer side) + varint framing.

The reference serializes every consensus artifact as gogo-protobuf
(reference proto/tendermint/*, canonical sign-bytes in
types/canonical.go, varint-delimited framing in libs/protoio). We only
need deterministic, self-consistent encodings — the hand-rolled writer
below emits standard proto wire format so sign bytes remain
canonical and portable without a codegen dependency.
"""

from __future__ import annotations

import struct

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_BYTES = 2
WIRE_FIXED32 = 5


def varint(v: int) -> bytes:
    """Unsigned varint (LEB128)."""
    if v < 0:
        v += 1 << 64  # two's-complement, 10 bytes, proto int64 semantics
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return varint((field << 3) | wire)


def field_varint(field: int, v: int) -> bytes:
    if v == 0:
        return b""
    return tag(field, WIRE_VARINT) + varint(v)


def field_sfixed64(field: int, v: int) -> bytes:
    if v == 0:
        return b""
    return tag(field, WIRE_FIXED64) + struct.pack("<q", v)


def field_bytes(field: int, v: bytes) -> bytes:
    if not v:
        return b""
    return tag(field, WIRE_BYTES) + varint(len(v)) + v


def field_string(field: int, v: str) -> bytes:
    return field_bytes(field, v.encode())


def field_message(field: int, v: bytes) -> bytes:
    """Embedded message: emitted even when empty iff v is not None."""
    if v is None:
        return b""
    return tag(field, WIRE_BYTES) + varint(len(v)) + v


def delimited(payload: bytes) -> bytes:
    """Length-prefixed framing (libs/protoio MarshalDelimited)."""
    return varint(len(payload)) + payload


def timestamp(ns: int) -> bytes:
    """google.protobuf.Timestamp from integer unix nanoseconds."""
    secs, nanos = divmod(ns, 1_000_000_000)
    return field_varint(1, secs) + field_varint(2, nanos)
