"""Auto-scaling worker pool (fork feature, reference
internal/autopool/pool.go:10-13 + scaler.go).

Workers drain a shared queue of callables; a scaler task grows the
pool when the queue stays deep and shrinks it when idle, between
min/max bounds. The fork uses this to process reactor messages
concurrently in its lp2p reactor set; here the Switch can use it the
same way (dispatch=pool.submit) so one slow reactor callback doesn't
serialize every peer's traffic."""

from __future__ import annotations

import asyncio
import traceback
from typing import Callable, Optional

SCALE_INTERVAL_S = 0.5
GROW_QUEUE_DEPTH = 32  # grow when backlog exceeds this per worker
SHRINK_IDLE_ROUNDS = 4  # shrink after this many idle scale checks


class AutoPool:
    def __init__(
        self,
        min_workers: int = 1,
        max_workers: int = 8,
        queue_size: int = 10_000,
    ):
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.queue: asyncio.Queue = asyncio.Queue(queue_size)
        self._workers: list = []
        self._scaler: Optional[asyncio.Task] = None
        self._idle_rounds = 0
        self.processed = 0
        self._stopped = False

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        for _ in range(self.min_workers):
            self._spawn()
        self._scaler = asyncio.create_task(self._scale_routine())

    async def stop(self) -> None:
        self._stopped = True
        if self._scaler:
            self._scaler.cancel()
        for w in self._workers:
            w.cancel()
        for w in self._workers:
            try:
                await w
            except asyncio.CancelledError:
                if not w.cancelled():
                    raise  # outer cancel of stop() itself: propagate
            except Exception:
                pass  # worker exceptions already logged in _worker
        self._workers.clear()

    # --- submission ---------------------------------------------------

    def submit(self, fn: Callable, *args) -> bool:
        """Queue fn(*args); False if the pool is saturated."""
        if self._stopped:
            return False
        try:
            self.queue.put_nowait((fn, args))
        except asyncio.QueueFull:
            return False
        return True

    # --- internals ----------------------------------------------------

    def _spawn(self) -> None:
        self._workers.append(asyncio.create_task(self._worker()))

    async def _worker(self) -> None:
        while True:
            fn, args = await self.queue.get()
            try:
                r = fn(*args)
                if asyncio.iscoroutine(r):
                    await r
            except asyncio.CancelledError:
                raise
            except Exception:
                traceback.print_exc()
            finally:
                self.processed += 1

    async def _scale_routine(self) -> None:
        try:
            while True:
                await asyncio.sleep(SCALE_INTERVAL_S)
                depth = self.queue.qsize()
                n = len(self._workers)
                if depth > GROW_QUEUE_DEPTH * n and n < self.max_workers:
                    self._spawn()
                    self._idle_rounds = 0
                elif depth == 0:
                    self._idle_rounds += 1
                    if (
                        self._idle_rounds >= SHRINK_IDLE_ROUNDS
                        and n > self.min_workers
                    ):
                        w = self._workers.pop()
                        w.cancel()
                        self._idle_rounds = 0
                else:
                    self._idle_rounds = 0
        except asyncio.CancelledError:
            raise

    @property
    def size(self) -> int:
        return len(self._workers)
