"""Crash-point injection (reference libs/fail/fail.go:28).

Every call to fail_point() increments a process-wide counter; when the
counter reaches the value of the FAIL_TEST_INDEX environment variable
the process exits hard (os._exit, no cleanup, no atexit — simulating a
power cut at exactly that interleaving). Used by crash/recovery tests
to prove WAL + handshake replay restore every intermediate state.

Callsites mirror the reference's (consensus/state.go:1769-1837,
state/execution.go:313-363): around block save, WAL end-height, ABCI
finalize and commit.
"""

from __future__ import annotations

import os

_counter = 0
_target = None


def _get_target():
    global _target
    if _target is None:
        v = os.environ.get("FAIL_TEST_INDEX", "")
        _target = int(v) if v else -1
    return _target


def fail_point(name: str = "") -> None:
    global _counter
    target = _get_target()
    if target < 0:
        return
    if _counter == target:
        import sys

        print(f"FAIL_TEST_INDEX={target} hit at {name!r}; dying",
              file=sys.stderr, flush=True)
        os._exit(99)
    _counter += 1


def reset() -> None:  # test helper
    global _counter, _target
    _counter = 0
    _target = None
