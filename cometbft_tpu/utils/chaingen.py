"""Chain generator: build a valid chain directly (no consensus rounds).

The reference generates test chains by running real consensus
(consensus/wal_generator.go) — fine for 10 blocks, hopeless for the
north-star 10k-block replay corpus. This builder signs real commits
with the validators' keys and applies blocks through the real
BlockExecutor, so the product is byte-for-byte a valid chain: every
sync path (blocksync, light, statesync, handshake replay) can be
exercised against it at scale.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .. import types as T
from ..node.inprocess import NodeParts, build_node
from ..types.genesis import GenesisDoc


def make_chain(
    genesis: GenesisDoc,
    privs,
    n_blocks: int,
    txs_per_block: int = 1,
    node: Optional[NodeParts] = None,
) -> NodeParts:
    """Returns a NodeParts whose stores hold a `n_blocks`-high chain."""
    node = node or build_node(genesis, None)
    state = node.state_store.load()
    chain_id = state.chain_id
    # Keep generated block times strictly increasing AND in the past:
    # 1s per block when the genesis backdate allows it, else shrink the
    # step so even a 10k-block corpus ends >=60s before "now" (wall
    # clock checks: block-time tolerance, light-client drift).
    now = time.time_ns()
    margin_ns = 60 * 1_000_000_000
    t = state.last_block_time_ns or (
        now - margin_ns - (n_blocks + 1) * 1_000_000_000
    )
    step_ns = 1_000_000_000
    if t + (n_blocks + 1) * step_ns > now - margin_ns:
        step_ns = max(1, (now - margin_ns - t) // (n_blocks + 1))
    addr_to_priv = {p.pub_key().address(): p for p in privs}

    for h in range(
        state.last_block_height + 1, state.last_block_height + 1 + n_blocks
    ):
        proposer = state.validators.get_proposer()
        last_commit = (
            node.block_store.load_seen_commit(h - 1)
            if h > state.initial_height
            else None
        )
        for i in range(txs_per_block):
            node.mempool.check_tx(b"h%d_%d=v%d" % (h, i, h))
        t += step_ns
        block, parts = node.block_exec.create_proposal_block(
            h, state, last_commit, proposer.address, time_ns=t
        )
        bid = T.BlockID(block.hash(), parts.header)
        # sign precommits from every validator
        sigs = []
        for i, val in enumerate(state.validators.validators):
            priv = addr_to_priv[val.address]
            vote = T.Vote(
                type_=T.PRECOMMIT,
                height=h,
                round=0,
                block_id=bid,
                timestamp_ns=t,
                validator_address=val.address,
                validator_index=i,
            )
            vote.signature = priv.sign(vote.sign_bytes(chain_id))
            sigs.append(
                T.CommitSig(
                    block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                    validator_address=val.address,
                    timestamp_ns=t,
                    signature=vote.signature,
                )
            )
        commit = T.Commit(height=h, round=0, block_id=bid, signatures=sigs)
        node.block_store.save_block(block, parts, commit)
        state = node.block_exec.apply_verified_block(state, bid, block)
    node.state = state
    return node


class StorePeerClient:
    """Blocksync peer client serving blocks from a node's store
    (the in-memory stand-in for a network peer)."""

    def __init__(self, node: NodeParts, delay_s: float = 0.0):
        self.node = node
        self.delay_s = delay_s

    @property
    def base(self) -> int:
        return self.node.block_store.base()

    @property
    def height(self) -> int:
        return self.node.block_store.height()

    async def request_block(self, height: int):
        if self.delay_s:
            import asyncio

            await asyncio.sleep(self.delay_s)
        blk = self.node.block_store.load_block(height)
        if blk is not None:
            # mirror the net reactor: ship the stored extended commit
            # out-of-band (blocksync/net_reactor.py MSG_BLOCK_RESPONSE)
            ec = self.node.block_store.load_extended_commit(height)
            if ec:
                blk._ec_bytes = ec
        return blk


class TamperingPeerClient(StorePeerClient):
    """Serves a corrupted block at one height (bad-peer testing)."""

    def __init__(self, node, bad_height: int):
        super().__init__(node)
        self.bad_height = bad_height

    async def request_block(self, height: int):
        blk = await super().request_block(height)
        if blk is not None and height == self.bad_height:
            blk.data.txs = list(blk.data.txs) + [b"evil=1"]
            blk.data._hash = None
            if hasattr(blk, "_raw_bytes"):  # immutable-decode convention
                del blk._raw_bytes
        return blk
