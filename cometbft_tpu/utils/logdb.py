"""Native log-structured KV backend (ctypes binding for native/logdb.cpp).

The reference's block/state stores sit on goleveldb or pebble — native
LSM engines. This is the equivalent native component here: a C++
append-log + ordered-index engine with CRC-framed records (torn tails
truncate on replay), atomic batches, prefix iteration, and compaction.
Built on demand with g++ into the package build dir; `open_kv` selects
it via db_backend = "logdb".
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional, Tuple

from .kv import KV

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "logdb.cpp",
)
# build artifact lives OUTSIDE the source tree (read-only installs,
# no risk of committing a platform binary); override with LOGDB_SO_DIR
_SO = os.path.join(
    os.environ.get(
        "LOGDB_SO_DIR",
        os.path.join(
            os.path.expanduser("~"), ".cache", "cometbft_tpu"
        ),
    ),
    "liblogdb.so",
)

_lib = None
_build_lock = threading.Lock()


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:  # pragma: no cover
            return _lib
        if (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            os.makedirs(os.path.dirname(_SO), exist_ok=True)
            subprocess.run(
                [
                    "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                    _SRC, "-o", _SO,
                ],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_SO)
        lib.logdb_open.restype = ctypes.c_void_p
        lib.logdb_open.argtypes = [ctypes.c_char_p]
        lib.logdb_get.restype = ctypes.c_int
        lib.logdb_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.logdb_put.restype = ctypes.c_int
        lib.logdb_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.logdb_del.restype = ctypes.c_int
        lib.logdb_del.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.logdb_batch.restype = ctypes.c_int
        lib.logdb_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.logdb_iter_new.restype = ctypes.c_void_p
        lib.logdb_iter_new.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.logdb_iter_next.restype = ctypes.c_int
        lib.logdb_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.logdb_iter_free.argtypes = [ctypes.c_void_p]
        lib.logdb_compact.restype = ctypes.c_int64
        lib.logdb_compact.argtypes = [ctypes.c_void_p]
        lib.logdb_count.restype = ctypes.c_uint64
        lib.logdb_count.argtypes = [ctypes.c_void_p]
        lib.logdb_dead_bytes.restype = ctypes.c_uint64
        lib.logdb_dead_bytes.argtypes = [ctypes.c_void_p]
        lib.logdb_flush.argtypes = [ctypes.c_void_p]
        lib.logdb_close.argtypes = [ctypes.c_void_p]
        lib.logdb_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


# compact automatically once this much of the log is dead weight
AUTO_COMPACT_DEAD_BYTES = 64 * 1024 * 1024


class LogDB(KV):
    """KV interface over the native engine (thread-safe: the engine
    holds its own mutex; handles are guarded against double close)."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._h = lib.logdb_open(path.encode())
        if not self._h:
            raise OSError(
                f"logdb_open failed for {path} (locked by another "
                "process, unreadable, or unwritable)"
            )
        self._closed = False
        self._compacting = threading.Lock()

    def _handle(self):
        # every native call goes through here: a handle used after
        # close() would dereference freed memory in C++ (segfault, not
        # a Python exception)
        if self._closed:
            raise OSError("logdb handle is closed")
        return self._h

    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        outl = ctypes.c_uint32()
        rc = self._lib.logdb_get(
            self._handle(), bytes(key), len(key), ctypes.byref(out),
            ctypes.byref(outl),
        )
        if rc == 1:
            return None
        if rc != 0:
            raise OSError("logdb_get failed")
        try:
            return ctypes.string_at(out, outl.value)
        finally:
            self._lib.logdb_free(out)

    def set(self, key: bytes, value: bytes) -> None:
        if self._lib.logdb_put(
            self._handle(), bytes(key), len(key), bytes(value), len(value)
        ) != 0:
            raise OSError("logdb_put failed")

    def delete(self, key: bytes) -> None:
        if self._lib.logdb_del(self._handle(), bytes(key), len(key)) != 0:
            raise OSError("logdb_del failed")

    def write_batch(self, sets, deletes=()) -> None:
        parts = []
        sets = list(sets)
        deletes = list(deletes)
        parts.append(len(sets).to_bytes(4, "little"))
        for k, v in sets:
            k, v = bytes(k), bytes(v)
            parts.append(len(k).to_bytes(4, "little"))
            parts.append(len(v).to_bytes(4, "little"))
            parts.append(k)
            parts.append(v)
        parts.append(len(deletes).to_bytes(4, "little"))
        for k in deletes:
            k = bytes(k)
            parts.append(len(k).to_bytes(4, "little"))
            parts.append(k)
        buf = b"".join(parts)
        if self._lib.logdb_batch(self._handle(), buf, len(buf)) != 0:
            raise OSError("logdb_batch failed")
        if (
            self._lib.logdb_dead_bytes(self._h) > AUTO_COMPACT_DEAD_BYTES
            and self._compacting.acquire(blocking=False)
        ):
            # off the commit path: the caller's batch has already
            # committed; the rewrite happens on a background thread
            # (native mutex still serializes concurrent ops with it)
            def _bg():
                # _compacting is held from the acquire above until the
                # release here; close() blocks on it, so _closed cannot
                # flip mid-compaction (use-after-free on the native
                # handle otherwise)
                try:
                    if not self._closed:
                        self.compact()
                except OSError:
                    pass
                finally:
                    self._compacting.release()

            threading.Thread(
                target=_bg, daemon=True, name="logdb-compact"
            ).start()

    def iter_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.logdb_iter_new(
            self._handle(), bytes(prefix), len(prefix)
        )
        if not it:
            raise OSError("logdb_iter_new failed")
        try:
            k = ctypes.POINTER(ctypes.c_uint8)()
            v = ctypes.POINTER(ctypes.c_uint8)()
            kl = ctypes.c_uint32()
            vl = ctypes.c_uint32()
            while (
                self._lib.logdb_iter_next(
                    it, ctypes.byref(k), ctypes.byref(kl),
                    ctypes.byref(v), ctypes.byref(vl),
                )
                == 0
            ):
                yield (
                    ctypes.string_at(k, kl.value),
                    ctypes.string_at(v, vl.value),
                )
        finally:
            self._lib.logdb_iter_free(it)

    def compact(self) -> int:
        freed = self._lib.logdb_compact(self._handle())
        if freed < 0:
            raise OSError("logdb_compact failed")
        return int(freed)

    def count(self) -> int:
        return int(self._lib.logdb_count(self._handle()))

    def flush(self) -> None:
        self._lib.logdb_flush(self._handle())

    def close(self) -> None:
        # waits out any in-flight background compaction before freeing
        # the native handle
        with self._compacting:
            if not self._closed:
                self._closed = True
                self._lib.logdb_close(self._h)
