"""Structured logfmt logging with module scoping and lazy values.

Parity with the reference's ``libs/log`` (tm_logger.go:27): every
subsystem gets a module-scoped logger, records are logfmt lines
(``ts=... level=... module=consensus msg="entering new round"
height=5``), expensive values (block hashes!) are wrapped in
:class:`Lazy` so they are only rendered when the record is actually
emitted, and the level is config-selectable globally and per module
(reference's ``log_level`` config, e.g. ``"consensus:debug,*:info"``).

Design departures for this codebase: no dependency on stdlib
``logging`` (its handler/formatter machinery costs more than the
framework's message rates justify and buys nothing here), writer is
pluggable for tests, and bound key-value context (``with_fields``)
replaces the reference's ``logger.With(...)``.
"""

from __future__ import annotations

import io
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, TextIO

DEBUG, INFO, ERROR, NONE = 10, 20, 40, 100
_LEVEL_NAMES = {DEBUG: "debug", INFO: "info", ERROR: "error"}
_NAME_LEVELS = {"debug": DEBUG, "info": INFO, "error": ERROR, "none": NONE}

_lock = threading.Lock()
_writer: TextIO = sys.stderr
_global_level = _NAME_LEVELS.get(
    os.environ.get("CMT_LOG_LEVEL", "info").lower(), INFO
)
_module_levels: Dict[str, int] = {}
_loggers: Dict[str, "Logger"] = {}


class Lazy:
    """Defers a value computation until (and unless) the record is
    emitted — the analog of the reference's log.NewLazyBlockHash."""

    __slots__ = ("_fn",)

    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    def render(self) -> Any:
        try:
            return self._fn()
        except Exception as e:  # a log value must never raise
            return f"<lazy error: {e}>"


def lazy_hex(get_bytes: Callable[[], bytes], n: int = 8) -> Lazy:
    """Lazy short-hex of a hash-like value (first n bytes)."""
    return Lazy(lambda: get_bytes()[:n].hex())


def set_writer(w: TextIO) -> None:
    global _writer
    with _lock:
        _writer = w


def set_level(spec: str) -> None:
    """Level spec: ``"info"`` or ``"consensus:debug,p2p:error,*:info"``
    (reference config ``log_level``). Unknown names raise ValueError."""
    global _global_level
    mods: Dict[str, int] = {}
    glob = None
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            mod, name = part.rsplit(":", 1)
        else:
            mod, name = "*", part
        name = name.strip().lower()
        if name not in _NAME_LEVELS:
            raise ValueError(f"unknown log level {name!r}")
        if mod.strip() in ("*", ""):
            glob = _NAME_LEVELS[name]
        else:
            mods[mod.strip()] = _NAME_LEVELS[name]
    with _lock:
        _module_levels.clear()
        _module_levels.update(mods)
        if glob is not None:
            _global_level = glob


def _quote(v: Any) -> str:
    if isinstance(v, Lazy):
        v = v.render()
    if isinstance(v, float):
        s = f"{v:.6g}"
    elif isinstance(v, (bytes, bytearray)):
        s = v.hex()
    elif isinstance(v, bool):
        s = "true" if v else "false"
    else:
        s = str(v)
    if any(c in s for c in ' "=\n'):
        s = '"' + s.replace("\\", "\\\\").replace('"', '\\"').replace(
            "\n", "\\n"
        ) + '"'
    return s


class Logger:
    """Module-scoped logfmt logger with optional bound fields."""

    __slots__ = ("module", "_bound")

    def __init__(self, module: str, bound: Optional[Dict[str, Any]] = None):
        self.module = module
        self._bound = bound or {}

    def with_fields(self, **fields: Any) -> "Logger":
        """Bound-context child (reference logger.With)."""
        merged = dict(self._bound)
        merged.update(fields)
        return Logger(self.module, merged)

    def _enabled(self, level: int) -> bool:
        return level >= _module_levels.get(self.module, _global_level)

    def _emit(self, level: int, msg: str, fields: Dict[str, Any]) -> None:
        if not self._enabled(level):
            return
        buf = io.StringIO()
        now = time.time()  # single read: second + millis stay coherent
        buf.write(
            f"ts={time.strftime('%Y-%m-%dT%H:%M:%S', time.gmtime(now))}"
            f".{int(now * 1000) % 1000:03d}Z"
            f" level={_LEVEL_NAMES[level]} module={self.module}"
            f" msg={_quote(msg)}"
        )
        for k, v in self._bound.items():
            buf.write(f" {k}={_quote(v)}")
        for k, v in fields.items():
            buf.write(f" {k}={_quote(v)}")
        buf.write("\n")
        line = buf.getvalue()
        with _lock:
            try:
                _writer.write(line)
            except Exception:
                pass

    def debug(self, msg: str, **fields: Any) -> None:
        self._emit(DEBUG, msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._emit(INFO, msg, fields)

    def error(self, msg: str, **fields: Any) -> None:
        self._emit(ERROR, msg, fields)


def get_logger(module: str) -> Logger:
    """Module-scoped singleton (bound-field children are cheap copies)."""
    with _lock:
        lg = _loggers.get(module)
        if lg is None:
            lg = _loggers[module] = Logger(module)
        return lg
