"""Strong-referenced fire-and-forget tasks.

The event loop holds only a *weak* reference to tasks: a bare
``asyncio.create_task(...)`` / ``ensure_future(...)`` whose result is
dropped can be garbage-collected mid-flight, silently killing the
coroutine and losing its exception (CPython docs, asyncio.create_task
"Save a reference to the result").  bftlint rule ASY103 flags those
sites; this module is the sanctioned fix for genuinely
fire-and-forget work: the registry keeps each task alive until done,
then a done-callback drops it (and surfaces a swallowed exception to
the logger instead of the void).
"""
from __future__ import annotations

import asyncio
from typing import Coroutine, Optional, Set

from .log import get_logger

_log = get_logger("tasks")

_BACKGROUND: Set["asyncio.Future"] = set()


def spawn(
    coro: Coroutine, *, name: Optional[str] = None
) -> "asyncio.Future":
    """Schedule ``coro`` fire-and-forget, retaining a strong ref."""
    task = asyncio.ensure_future(coro)
    if name and hasattr(task, "set_name"):
        task.set_name(name)
    _BACKGROUND.add(task)
    task.add_done_callback(_finish)
    return task


def _finish(task: "asyncio.Future") -> None:
    _BACKGROUND.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        _log.error(
            "background task died",
            task=getattr(task, "get_name", lambda: "?")(),
            err=repr(exc),
        )


def pending_count() -> int:
    """Live background tasks (introspection / tests)."""
    return len(_BACKGROUND)
