"""Exponential backoff with full jitter and a cap.

One implementation for every reconnect/retry path (p2p switch
reconnects — both the native Switch and Lp2pSwitch share it through
the common peer lifecycle — and any future dial/retry loop). Full
jitter (delay_n = uniform(0, min(cap, base * factor**n))) spreads
synchronized reconnect storms better than equal jitter: after a
network-wide event every node would otherwise redial on the same
schedule.

The class is loop-agnostic: ``next_delay()`` is a pure draw, usable
from sync and async code alike. Pass a seeded ``random.Random`` for
deterministic schedules (the chaos harness does).
"""

from __future__ import annotations

import random
from typing import Optional


class Backoff:
    """Successive ``next_delay()`` calls return jittered, exponentially
    growing delays: uniform(0, min(cap_s, base_s * factor**attempt))."""

    def __init__(
        self,
        base_s: float = 1.0,
        cap_s: float = 30.0,
        factor: float = 2.0,
        rng: Optional[random.Random] = None,
    ):
        if base_s <= 0 or cap_s < base_s or factor < 1.0:
            raise ValueError(
                f"bad backoff params base={base_s} cap={cap_s} factor={factor}"
            )
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self._rng = rng or random.Random()
        self.attempt = 0

    def ceiling(self) -> float:
        """Current un-jittered ceiling (exposed for tests/metrics)."""
        return min(self.cap_s, self.base_s * self.factor ** self.attempt)

    def next_delay(self) -> float:
        d = self._rng.uniform(0.0, self.ceiling())
        self.attempt += 1
        return d

    def reset(self) -> None:
        """Back to the first attempt (call after a success)."""
        self.attempt = 0
