"""Pubsub query language (reference libs/pubsub/query).

Grammar subset (covers everything the reference's RPC docs use):
  query     = condition { "AND" condition }
  condition = key op value
  op        = "=" | "<" | ">" | "<=" | ">=" | "CONTAINS" | "EXISTS"
  value     = 'single-quoted string' | number
Keys are dotted event-attribute names ("tm.event", "tx.height",
"transfer.sender"). Numbers compare numerically; strings lexically.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Union

_TOKEN = re.compile(
    r"\s*(?:(?P<op><=|>=|=|<|>)|(?P<kw>AND\b|CONTAINS\b|EXISTS\b)"
    r"|(?P<str>'(?:[^'\\]|\\.)*')|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<key>[\w.\-/]+))"
)


@dataclass
class Condition:
    key: str
    op: str  # '=', '<', '>', '<=', '>=', 'CONTAINS', 'EXISTS'
    value: Union[str, float, None]


class Query:
    """Compiled query; match against {attr_key: [values...]}."""

    def __init__(self, conditions: List[Condition], source: str = ""):
        self.conditions = conditions
        self.source = source

    def __repr__(self) -> str:
        return f"Query({self.source!r})"

    def matches(self, attrs: Dict[str, List[str]]) -> bool:
        return all(self._match_one(c, attrs) for c in self.conditions)

    @staticmethod
    def _match_one(c: Condition, attrs: Dict[str, List[str]]) -> bool:
        values = attrs.get(c.key)
        if values is None:
            return False
        if c.op == "EXISTS":
            return True
        for v in values:
            if c.op == "CONTAINS":
                if str(c.value) in v:
                    return True
                continue
            if isinstance(c.value, float):
                try:
                    lhs = float(v)
                except ValueError:
                    continue
                rhs = c.value
            else:
                lhs, rhs = v, str(c.value)
            if (
                (c.op == "=" and lhs == rhs)
                or (c.op == "<" and lhs < rhs)
                or (c.op == ">" and lhs > rhs)
                or (c.op == "<=" and lhs <= rhs)
                or (c.op == ">=" and lhs >= rhs)
            ):
                return True
        return False


def parse(s: str) -> Query:
    toks = []
    pos = 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if m is None or m.end() == pos:
            if s[pos:].strip():
                raise ValueError(f"bad query near {s[pos:]!r}")
            break
        pos = m.end()
        kind = m.lastgroup
        toks.append((kind, m.group(kind)))
    conds: List[Condition] = []
    i = 0
    while i < len(toks):
        if toks[i] == ("kw", "AND"):
            i += 1
            continue
        if toks[i][0] != "key":
            raise ValueError(f"expected key, got {toks[i]}")
        key = toks[i][1]
        i += 1
        if i >= len(toks):
            raise ValueError("truncated condition")
        kind, tok = toks[i]
        if (kind, tok) == ("kw", "EXISTS"):
            conds.append(Condition(key, "EXISTS", None))
            i += 1
            continue
        if kind == "op":
            op = tok
        elif (kind, tok) == ("kw", "CONTAINS"):
            op = "CONTAINS"
        else:
            raise ValueError(f"expected operator, got {tok!r}")
        i += 1
        if i >= len(toks):
            raise ValueError("missing value")
        vkind, vtok = toks[i]
        if vkind == "str":
            value: Union[str, float] = (
                vtok[1:-1].replace("\\'", "'").replace("\\\\", "\\")
            )
        elif vkind == "num":
            value = float(vtok)
        else:
            raise ValueError(f"expected value, got {vtok!r}")
        conds.append(Condition(key, op, value))
        i += 1
    if not conds:
        raise ValueError("empty query")
    return Query(conds, s)
