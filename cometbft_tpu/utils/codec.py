"""Round-trip serialization for consensus artifacts (storage + wire).

Proto wire format via utils.proto (writer + reader). This is the
framework's own deterministic codec — behavioral parity with the
reference's gogoproto-generated types (proto/tendermint/types/*.pb.go)
without codegen. Field numbers are stable; changing them is a
chain-breaking change.
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto.keys import (
    ED25519_KEY_TYPE,
    SECP256K1_KEY_TYPE,
    Ed25519PubKey,
    PubKey,
    Secp256k1PubKey,
    pubkey_from_type_bytes,
)
from ..types.block import (
    Block,
    BlockID,
    Commit,
    CommitSig,
    Data,
    Header,
    PartSetHeader,
)
from ..types.validator_set import Validator, ValidatorSet
from ..types.vote import Proposal, Vote
from . import proto


def _native():
    """Native commit codec (native/wirecodec.cpp), or None — see
    utils/wirecodec.py; the pure-Python paths below remain the
    semantic source of truth and the no-compiler fallback."""
    from . import wirecodec

    return wirecodec.module()

# --- pubkeys ------------------------------------------------------------


def encode_pubkey(pk: PubKey) -> bytes:
    if isinstance(pk, Ed25519PubKey):
        return proto.field_bytes(1, pk.key_bytes)
    if isinstance(pk, Secp256k1PubKey):
        return proto.field_bytes(2, pk.key_bytes)
    raise ValueError("unknown pubkey type")


def decode_pubkey(b: bytes) -> PubKey:
    m = proto.parse(b)
    if 1 in m:
        return pubkey_from_type_bytes(ED25519_KEY_TYPE, m[1][0])
    if 2 in m:
        return pubkey_from_type_bytes(SECP256K1_KEY_TYPE, m[2][0])
    raise ValueError("empty pubkey")


# --- block id -----------------------------------------------------------


def encode_block_id(bid: BlockID) -> bytes:
    return bid.encode()


def decode_block_id(b: bytes) -> BlockID:
    m = proto.parse(b)
    pshb = proto.get1(m, 2, b"")
    psh = PartSetHeader()
    if pshb:
        pm = proto.parse(pshb)
        psh = PartSetHeader(proto.get1(pm, 1, 0), proto.get1(pm, 2, b""))
    return BlockID(proto.get1(m, 1, b""), psh)


# --- header -------------------------------------------------------------


def encode_header(h: Header) -> bytes:
    ver = proto.field_varint(1, h.version_block) + proto.field_varint(
        2, h.version_app
    )
    return b"".join(
        [
            proto.field_message(1, ver),
            proto.field_string(2, h.chain_id),
            proto.field_varint(3, h.height),
            proto.field_message(4, proto.timestamp(h.time_ns)),
            proto.field_message(5, h.last_block_id.encode()),
            proto.field_bytes(6, h.last_commit_hash),
            proto.field_bytes(7, h.data_hash),
            proto.field_bytes(8, h.validators_hash),
            proto.field_bytes(9, h.next_validators_hash),
            proto.field_bytes(10, h.consensus_hash),
            proto.field_bytes(11, h.app_hash),
            proto.field_bytes(12, h.last_results_hash),
            proto.field_bytes(13, h.evidence_hash),
            proto.field_bytes(14, h.proposer_address),
        ]
    )


def decode_header(b: bytes) -> Header:
    m = proto.parse(b)
    vb = va = 0
    if 1 in m:
        vm = proto.parse(m[1][0])
        vb, va = proto.get1(vm, 1, 0), proto.get1(vm, 2, 0)
    return Header(
        version_block=vb,
        version_app=va,
        chain_id=proto.get1(m, 2, b"").decode(),
        height=proto.get1(m, 3, 0),
        time_ns=proto.parse_timestamp(proto.get1(m, 4, b"")),
        last_block_id=decode_block_id(proto.get1(m, 5, b"")),
        last_commit_hash=proto.get1(m, 6, b""),
        data_hash=proto.get1(m, 7, b""),
        validators_hash=proto.get1(m, 8, b""),
        next_validators_hash=proto.get1(m, 9, b""),
        consensus_hash=proto.get1(m, 10, b""),
        app_hash=proto.get1(m, 11, b""),
        last_results_hash=proto.get1(m, 12, b""),
        evidence_hash=proto.get1(m, 13, b""),
        proposer_address=proto.get1(m, 14, b""),
    )


# --- commit -------------------------------------------------------------


def encode_commit_sig(cs: CommitSig) -> bytes:
    return (
        proto.field_varint(1, cs.block_id_flag)
        + proto.field_bytes(2, cs.validator_address)
        + proto.field_message(3, proto.timestamp(cs.timestamp_ns))
        + proto.field_bytes(4, cs.signature)
    )


def decode_commit_sig(b: bytes) -> CommitSig:
    m = proto.parse(b)
    return CommitSig(
        block_id_flag=proto.get1(m, 1, 0),
        validator_address=proto.get1(m, 2, b""),
        timestamp_ns=proto.parse_timestamp(proto.get1(m, 3, b"")),
        signature=proto.get1(m, 4, b""),
    )


def encode_commit(c: Commit) -> bytes:
    nat = _native()
    if nat is not None:
        try:
            return nat.encode_commit(
                c.height, c.round, c.block_id.encode(), c.signatures
            )
        except Exception:  # pragma: no cover - odd sig shapes
            pass
    out = proto.field_varint(1, c.height) + proto.field_varint(2, c.round)
    out += proto.field_message(3, c.block_id.encode())
    for cs in c.signatures:  # bftlint: disable=ASY117 — serializing an O(V) commit payload is O(V) by construction: work is proportional to bytes written, once per commit shipped
        out += proto.field_message(4, encode_commit_sig(cs))
    return out


def _decode_timestamp_ns(sub: bytes) -> int:
    secs = nanos = 0
    pos, n = 0, len(sub)
    rv = proto.read_varint
    while pos < n:
        key, pos = rv(sub, pos)
        f, w = key >> 3, key & 7
        if w != 0:
            return proto.parse_timestamp(sub)  # unusual shape: generic
        v, pos = rv(sub, pos)
        if f == 1:
            secs = v
        elif f == 2:
            nanos = v
    return secs * 1_000_000_000 + nanos


def _decode_commit_sig_fast(sub: bytes) -> CommitSig:
    """Inline scan of the 4 CommitSig fields — the replay pipeline
    decodes 150 of these per height (x2: block + seen commit); the
    generic parse()'s dict-of-lists costs ~2x this scanner."""
    flag = 0
    addr = b""
    ts = 0
    sig = b""
    pos, n = 0, len(sub)
    rv = proto.read_varint
    while pos < n:
        key, pos = rv(sub, pos)
        f, w = key >> 3, key & 7
        if w == 0:
            v, pos = rv(sub, pos)
            if f == 1:
                flag = v
            elif f in (2, 3, 4):
                raise ValueError(f"commit sig field {f}: expected bytes")
        elif w == 2:
            ln, pos = rv(sub, pos)
            if ln < 0 or pos + ln > n:
                raise ValueError("truncated bytes field")
            v = sub[pos : pos + ln]
            pos += ln
            if f == 1:
                raise ValueError("commit sig field 1: expected varint")
            if f == 2:
                addr = v
            elif f == 3:
                ts = _decode_timestamp_ns(v)
            elif f == 4:
                sig = v
        elif w == 1:
            if pos + 8 > n:
                raise ValueError("truncated fixed64 field")
            pos += 8
        elif w == 5:
            if pos + 4 > n:
                raise ValueError("truncated fixed32 field")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {w}")
    return CommitSig(
        block_id_flag=flag,
        validator_address=addr,
        timestamp_ns=ts,
        signature=sig,
    )


def decode_commit(b: bytes) -> Commit:
    if not isinstance(b, (bytes, bytearray, memoryview)):
        raise ValueError(f"expected message bytes, got {type(b).__name__}")
    nat = _native()
    if nat is not None:
        try:
            height, round_, bid_b, sig_ts = nat.decode_commit(bytes(b))
        except ValueError:
            # the native reader is (at most) stricter than the Python
            # one on unusual-but-parseable shapes: Python remains the
            # semantic source of truth, so malformed-looking input
            # re-parses through the pure path below — identical
            # behavior with or without the extension, and zero cost
            # for honest traffic
            pass
        else:
            c = Commit(
                height=height,
                round=round_,
                block_id=decode_block_id(
                    bid_b if bid_b is not None else b""
                ),
                signatures=[
                    CommitSig(
                        block_id_flag=f,
                        validator_address=a,
                        timestamp_ns=t,
                        signature=s,
                    )
                    for f, a, t, s in sig_ts
                ],
            )
            c._raw_bytes = bytes(b)
            return c
    height = round_ = 0
    bid = None
    sigs = []
    pos, n = 0, len(b)
    rv = proto.read_varint
    while pos < n:
        key, pos = rv(b, pos)
        f, w = key >> 3, key & 7
        if w == 0:
            v, pos = rv(b, pos)
            if f == 1:
                height = v
            elif f == 2:
                round_ = v
            elif f in (3, 4):
                raise ValueError(f"commit field {f}: expected bytes")
        elif w == 2:
            ln, pos = rv(b, pos)
            if ln < 0 or pos + ln > n:
                raise ValueError("truncated bytes field")
            sub = b[pos : pos + ln]
            pos += ln
            if f in (1, 2):
                raise ValueError(f"commit field {f}: expected varint")
            if f == 3:
                bid = decode_block_id(sub)
            elif f == 4:
                sigs.append(_decode_commit_sig_fast(sub))
        elif w == 1:
            if pos + 8 > n:
                raise ValueError("truncated fixed64 field")
            pos += 8
        elif w == 5:
            if pos + 4 > n:
                raise ValueError("truncated fixed32 field")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {w}")
    c = Commit(
        height=height,
        round=round_,
        block_id=bid if bid is not None else decode_block_id(b""),
        signatures=sigs,
    )
    c._raw_bytes = bytes(b)  # immutable-decode convention (see decode_block)
    return c


def encode_extended_commit(ec) -> bytes:
    """ExtendedCommit wire form (reference proto ExtendedCommitInfo
    storage shape): commit fields + per-sig extension data."""
    out = proto.field_varint(1, ec.height) + proto.field_varint(2, ec.round)
    out += proto.field_message(3, ec.block_id.encode())
    for s in ec.extended_signatures:  # bftlint: disable=ASY117 — serializing an O(V) extended-commit payload is O(V) by construction, once per finalized height
        body = (
            encode_commit_sig(s)
            + proto.field_bytes(5, s.extension)
            + proto.field_bytes(6, s.extension_signature)
        )
        out += proto.field_message(4, body)
    return out


def decode_extended_commit(b: bytes):
    from ..types.block import ExtendedCommit, ExtendedCommitSig

    m = proto.parse(b)
    sigs = []
    for x in m.get(4, []):
        sm = proto.parse(x)
        sigs.append(
            ExtendedCommitSig(
                block_id_flag=proto.get1(sm, 1, 0),
                validator_address=proto.get1(sm, 2, b""),
                timestamp_ns=proto.parse_timestamp(proto.get1(sm, 3, b"")),
                signature=proto.get1(sm, 4, b""),
                extension=proto.get1(sm, 5, b""),
                extension_signature=proto.get1(sm, 6, b""),
            )
        )
    return ExtendedCommit(
        height=proto.get1(m, 1, 0),
        round=proto.get1(m, 2, 0),
        block_id=decode_block_id(proto.get1(m, 3, b"")),
        extended_signatures=sigs,
    )


# --- vote / proposal ----------------------------------------------------


def encode_vote(v: Vote) -> bytes:
    return b"".join(
        [
            proto.field_varint(1, v.type_),
            proto.field_varint(2, v.height),
            proto.field_varint(3, v.round),
            proto.field_message(4, v.block_id.encode()),
            proto.field_message(5, proto.timestamp(v.timestamp_ns)),
            proto.field_bytes(6, v.validator_address),
            proto.field_varint(7, v.validator_index + 1),  # +1: 0 realizable
            proto.field_bytes(8, v.signature),
            proto.field_bytes(9, v.extension),
            proto.field_bytes(10, v.extension_signature),
        ]
    )


def decode_vote(b: bytes) -> Vote:
    m = proto.parse(b)
    return Vote(
        type_=proto.get1(m, 1, 0),
        height=proto.get1(m, 2, 0),
        round=proto.get1(m, 3, 0),
        block_id=decode_block_id(proto.get1(m, 4, b"")),
        timestamp_ns=proto.parse_timestamp(proto.get1(m, 5, b"")),
        validator_address=proto.get1(m, 6, b""),
        validator_index=proto.get1(m, 7, 0) - 1,
        signature=proto.get1(m, 8, b""),
        extension=proto.get1(m, 9, b""),
        extension_signature=proto.get1(m, 10, b""),
    )


def encode_proposal(p: Proposal) -> bytes:
    return b"".join(
        [
            proto.field_varint(1, p.height),
            proto.field_varint(2, p.round),
            proto.field_varint(3, p.pol_round + 2),  # offset: -1 realizable
            proto.field_message(4, p.block_id.encode()),
            proto.field_message(5, proto.timestamp(p.timestamp_ns)),
            proto.field_bytes(6, p.signature),
        ]
    )


def decode_proposal(b: bytes) -> Proposal:
    m = proto.parse(b)
    return Proposal(
        height=proto.get1(m, 1, 0),
        round=proto.get1(m, 2, 0),
        pol_round=proto.get1(m, 3, 2) - 2,
        block_id=decode_block_id(proto.get1(m, 4, b"")),
        timestamp_ns=proto.parse_timestamp(proto.get1(m, 5, b"")),
        signature=proto.get1(m, 6, b""),
    )


# --- block --------------------------------------------------------------


def encode_block(blk: Block) -> bytes:
    out = proto.field_message(1, encode_header(blk.header))
    data = b"".join(proto.field_bytes(1, tx) for tx in blk.data.txs)
    out += proto.field_message(2, data)
    if blk.last_commit is not None:
        out += proto.field_message(3, encode_commit(blk.last_commit))
    for ev in blk.evidence:
        out += proto.field_message(4, ev.encode())
    return out


def decode_block(b: bytes) -> Block:
    from ..evidence.types import decode_evidence

    m = proto.parse(b)
    datab = proto.get1(m, 2, b"")
    txs = proto.parse(datab).get(1, []) if datab else []
    lc = proto.get1(m, 3)
    blk = Block(
        header=decode_header(proto.get1(m, 1, b"")),
        data=Data(txs=txs),
        last_commit=decode_commit(lc) if lc is not None else None,
        evidence=[decode_evidence(e) for e in m.get(4, [])],
    )
    # Memoized wire form (replay hot path): the block store and the
    # blocksync apply loop re-serialize every synced block (PartSet
    # build, SC:/C: records) — carrying the already-canonical bytes
    # saves two full commit encodes + one block encode per height.
    # CONVENTION: decoded objects are immutable; any caller that
    # mutates one must `del obj._raw_bytes` first.
    blk._raw_bytes = b
    if blk.last_commit is not None:
        blk.last_commit._raw_bytes = lc
    return blk


# --- validators ---------------------------------------------------------


def encode_validator(v: Validator) -> bytes:
    return (
        proto.field_bytes(1, v.address)
        + proto.field_message(2, encode_pubkey(v.pub_key))
        + proto.field_varint(3, v.voting_power)
        + proto.field_sfixed64(4, v.proposer_priority)
    )


def decode_validator(b: bytes) -> Validator:
    m = proto.parse(b)
    return Validator(
        pub_key=decode_pubkey(proto.get1(m, 2, b"")),
        voting_power=proto.get1(m, 3, 0),
        address=proto.get1(m, 1, b""),
        proposer_priority=proto.get1(m, 4, 0),
    )


def encode_validator_set(vs: ValidatorSet) -> bytes:
    out = b"".join(
        proto.field_message(1, encode_validator(v)) for v in vs.validators
    )
    if vs.proposer is not None:
        out += proto.field_bytes(2, vs.proposer.address)
    return out


def decode_validator_set(b: bytes) -> ValidatorSet:
    m = proto.parse(b)
    vals = [decode_validator(x) for x in m.get(1, [])]
    vs = ValidatorSet.__new__(ValidatorSet)
    vs.validators = vals
    vs._by_address = {v.address: i for i, v in enumerate(vals)}
    prop_addr = proto.get1(m, 2, b"")
    vs.proposer = None
    if prop_addr and prop_addr in vs._by_address:
        vs.proposer = vals[vs._by_address[prop_addr]]
    return vs
