"""Loader for the native wire codec (native/wirecodec.cpp).

Follows the logdb pattern (utils/logdb.py): built on demand with g++
into ~/.cache/cometbft_tpu (override with WIRECODEC_SO_DIR), loaded as
a CPython extension module. ``module()`` returns the extension or None
— callers (utils/codec.py) keep the pure-Python path as both the
fallback and the semantic source of truth (the native decoder defers
to Python on any ValueError, so adversarial-input behavior is
identical across builds with and without a compiler).

Replay-profile motivation: docs/PERF.md round-4 "replay host
pipeline" — the commit encode/decode loop was ~25% of non-signature
host time. GRAFT_NATIVE_CODEC=0 disables.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_SRC = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native",
    "wirecodec.cpp",
)
_SO = os.path.join(
    os.environ.get(
        "WIRECODEC_SO_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "cometbft_tpu"),
    ),
    "_wirecodec.so",
)

_mod = None
_tried = False
_lock = threading.Lock()


def module():
    """The extension module, or None (no compiler / disabled)."""
    global _mod, _tried
    if _tried:
        return _mod
    with _lock:
        if _tried:  # pragma: no cover - race
            return _mod
        _tried = True
        if os.environ.get("GRAFT_NATIVE_CODEC") == "0":
            return None
        try:
            if (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    [
                        "g++",
                        "-O2",
                        "-std=c++17",
                        "-shared",
                        "-fPIC",
                        "-I",
                        sysconfig.get_paths()["include"],
                        _SRC,
                        "-o",
                        _SO,
                        "-ldl",  # sha256_many dlopens libcrypto
                    ],
                    check=True,
                    capture_output=True,
                )
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_wirecodec", _SO
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:  # pragma: no cover - toolchain-dependent
            _mod = None
        return _mod
