"""Loader for the native wire codec (native/wirecodec.cpp).

Follows the logdb pattern (utils/logdb.py): built on demand with g++
into ~/.cache/cometbft_tpu (override with WIRECODEC_SO_DIR), loaded as
a CPython extension module. ``module()`` returns the extension or None
— callers (utils/codec.py) keep the pure-Python path as both the
fallback and the semantic source of truth (the native decoder defers
to Python on any ValueError, so adversarial-input behavior is
identical across builds with and without a compiler).

Replay-profile motivation: docs/PERF.md round-4 "replay host
pipeline" — the commit encode/decode loop was ~25% of non-signature
host time. GRAFT_NATIVE_CODEC=0 disables.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_SRC = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native",
    "wirecodec.cpp",
)
_SO = os.path.join(
    os.environ.get(
        "WIRECODEC_SO_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "cometbft_tpu"),
    ),
    "_wirecodec.so",
)

_mod = None
_tried = False
_lock = threading.Lock()


def prewarm():
    """Kick the one-time native build on a daemon thread so no event
    loop ever pays the compile (node/inprocess.build_node calls this;
    ASY114 found the g++ run reachable from reactor hot paths).
    Free once the build has happened."""
    if _tried:
        return None
    t = threading.Thread(
        target=module, name="wirecodec-prewarm", daemon=True
    )
    t.start()
    return t


def module():
    """The extension module, or None (no compiler / disabled).

    Loop-safe by construction: while another thread is mid-build the
    lock acquire is NON-blocking and we return None for now — every
    caller already handles the no-native fallback, and the next call
    after the build finishes gets the module. Only the thread that
    wins the lock ever runs the compiler."""
    global _mod, _tried
    if _tried:
        return _mod
    if not _lock.acquire(blocking=False):
        # a build is in flight elsewhere (usually the prewarm
        # thread): fall back rather than park this thread on a
        # multi-second g++ run
        return None
    try:
        if _tried:
            return _mod
        _tried = True
        if os.environ.get("GRAFT_NATIVE_CODEC") == "0":
            return None
        try:
            if (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                # one-time lazy native build; loop callers never park
                # here (non-blocking acquire above + build_node
                # prewarm thread) — sanctioned blocking sink
                subprocess.run(  # bftlint: disable=ASY114 — one-time lazy native build; loop callers never park here (non-blocking acquire + prewarm)
                    [
                        "g++",
                        "-O2",
                        "-std=c++17",
                        "-shared",
                        "-fPIC",
                        "-I",
                        sysconfig.get_paths()["include"],
                        _SRC,
                        "-o",
                        _SO,
                        "-ldl",  # sha256_many dlopens libcrypto
                    ],
                    check=True,
                    capture_output=True,
                )
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_wirecodec", _SO
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:  # pragma: no cover - toolchain-dependent
            _mod = None
        return _mod
    finally:
        _lock.release()
