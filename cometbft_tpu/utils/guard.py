"""TTL'd LRU dedup cache (fork feature, reference internal/guard/guard.go)."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict


class TTLGuard:
    def __init__(self, ttl_s: float = 60.0, max_size: int = 100_000):
        self.ttl = ttl_s
        self.max_size = max_size
        self._od: "OrderedDict[bytes, float]" = OrderedDict()
        self._lock = threading.Lock()

    def check_and_set(self, key: bytes) -> bool:
        """True if key was NOT present (and is now recorded)."""
        now = time.monotonic()
        with self._lock:
            exp = self._od.get(key)
            if exp is not None and exp > now:
                return False
            self._od[key] = now + self.ttl
            self._od.move_to_end(key)
            # opportunistic pruning
            while len(self._od) > self.max_size:
                self._od.popitem(last=False)
            if len(self._od) % 1024 == 0:
                stale = [k for k, e in self._od.items() if e <= now]
                for k in stale:
                    del self._od[k]
            return True

    def __len__(self) -> int:
        return len(self._od)
