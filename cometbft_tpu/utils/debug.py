"""Runtime profiling + crash-dump tooling.

Reference analogs:
- pprof HTTP server gated by config (node/node.go:624-627,934-947) —
  here a small aiohttp app serving the Python equivalents: thread/task
  stacks, a sampling CPU profile window, and heap usage (tracemalloc).
- `cometbft debug dump/kill` (cmd/cometbft/commands/debug/) — collect
  status, net_info, consensus state, and profiles from a live node
  into a timestamped archive, optionally then killing the process.
"""

from __future__ import annotations

import asyncio
import io
import json
import os
import sys
import threading
import time
import traceback
import zipfile
from typing import Optional


class StuckTaskWatchdog:
    """Deadlock-detection analog for the asyncio single-writer design
    (the reference swaps in go-deadlock mutexes under the `deadlock`
    build tag, libs/sync/deadlock.go; a coroutine runtime's equivalent
    hazard is an await that never resumes).

    Samples all asyncio tasks every ``interval_s``; a task observed
    suspended at the SAME await point (same frame, same instruction)
    for more than ``stall_s`` is reported once with its stack via the
    structured logger. Also watches event-loop responsiveness: if the
    sampling task itself fires late by more than ``stall_s`` the loop
    was blocked (sync work on the loop thread) and that is reported.
    """

    def __init__(self, interval_s: float = 5.0, stall_s: float = 30.0):
        self.interval_s = interval_s
        self.stall_s = stall_s
        self._seen = {}  # id(task) -> (marker, first_seen, reported)
        self._task: Optional[asyncio.Task] = None
        self.stalled: list = []  # (name, stack) tuples, for tests

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    @staticmethod
    def _marker(task: "asyncio.Task"):
        """Identity of the task's current suspension point.

        The frame position alone cannot distinguish "stuck forever"
        from "re-suspends at the same line each iteration" (a polling
        loop), so the marker includes the identity of the innermost
        awaited object: a live loop creates a fresh Future per await,
        a stuck task keeps waiting on the same one.
        """
        import weakref

        coro = task.get_coro()
        fr = getattr(coro, "cr_frame", None)
        if fr is None:
            return None
        obj = coro
        wr = None
        for _ in range(16):
            try:
                # a weakref (not a bare id), so a recycled allocation
                # at the same address cannot masquerade as the same
                # await; keep the DEEPEST weakrefable object (e.g. the
                # inner sleep coroutine — FutureIter isn't weakrefable)
                wr = weakref.ref(obj)
            except TypeError:
                pass
            nxt = getattr(obj, "cr_await", None)
            if nxt is None:
                nxt = getattr(obj, "gi_yieldfrom", None)
            if nxt is None:
                break
            obj = nxt
        if wr is None:
            return None
        return (id(fr), fr.f_lasti, wr)

    def _sample(self) -> None:
        from .log import get_logger

        log = get_logger("watchdog")
        now = time.monotonic()
        alive = set()
        me = asyncio.current_task()
        for task in asyncio.all_tasks():
            if task is me or task.done():
                continue
            key = id(task)
            alive.add(key)
            marker = self._marker(task)
            if marker is None:  # unknown suspension point: never report
                self._seen.pop(key, None)
                continue
            prev = self._seen.get(key)
            if prev is None or prev[0] != marker:
                self._seen[key] = (marker, now, False)
                continue
            marker0, first, reported = prev
            if not reported and now - first > self.stall_s:
                stack = io.StringIO()
                task.print_stack(file=stack)
                name = task.get_name()
                self.stalled.append((name, stack.getvalue()))
                log.error(
                    "task stuck at the same await point",
                    task=name,
                    stalled_s=round(now - first, 1),
                    stack=stack.getvalue()[:2000],
                )
                self._seen[key] = (marker0, first, True)
        for key in list(self._seen):
            if key not in alive:
                del self._seen[key]

    async def _run(self) -> None:
        from .log import get_logger

        log = get_logger("watchdog")
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval_s)
            late = time.monotonic() - t0 - self.interval_s
            if late > self.stall_s:
                log.error(
                    "event loop blocked (sync work on loop thread)",
                    blocked_s=round(late, 1),
                )
            try:
                self._sample()
            except Exception:  # the watchdog must never kill the node
                pass


def all_stacks() -> str:
    """Every thread's stack + every asyncio task (the goroutine-dump
    equivalent)."""
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(
            f"--- thread {t.name} (daemon={t.daemon}, id={t.ident})\n"
        )
        fr = frames.get(t.ident)
        if fr is not None:
            traceback.print_stack(fr, file=out)
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        loop = None
    if loop is not None:
        for task in asyncio.all_tasks(loop):
            out.write(f"--- task {task.get_name()} {task!r}\n")
            for line in task.get_stack(limit=16):
                out.write(f"    {line}\n")
    return out.getvalue()


_profile_lock = threading.Lock()


def cpu_profile(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Sampling profiler over ALL threads (py-spy style): captures
    sys._current_frames() at `hz` for the window and aggregates frame
    occurrence counts. cProfile can't do this — its hook only attaches
    to the calling thread, which here would just be sleeping."""
    if not _profile_lock.acquire(blocking=False):
        return "profile already running\n"
    try:
        counts: dict = {}
        own = threading.get_ident()
        deadline = time.monotonic() + seconds
        samples = 0
        interval = 1.0 / hz
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == own:
                    continue
                stack = []
                f = frame
                depth = 0
                while f is not None and depth < 30:
                    stack.append(
                        f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno} {f.f_code.co_name}"
                    )
                    f = f.f_back
                    depth += 1
                key = " <- ".join(stack[:6])
                counts[key] = counts.get(key, 0) + 1
            samples += 1
            time.sleep(interval)
        out = io.StringIO()
        out.write(f"{samples} samples over {seconds}s at {hz}Hz\n\n")
        for key, n in sorted(
            counts.items(), key=lambda kv: -kv[1]
        )[:60]:
            out.write(f"{n:6d}  {key}\n")
        return out.getvalue()
    finally:
        _profile_lock.release()


def heap_stats(top: int = 40) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc started; call again for a snapshot\n"
    snap = tracemalloc.take_snapshot()
    out = io.StringIO()
    for stat in snap.statistics("lineno")[:top]:
        out.write(f"{stat}\n")
    cur, peak = tracemalloc.get_traced_memory()
    out.write(f"current={cur} peak={peak}\n")
    return out.getvalue()


class DebugServer:
    """The pprof-style HTTP listener (config
    instrumentation.pprof_laddr, reference node/node.go:624)."""

    def __init__(self, addr: str):
        self.addr = addr
        self._runner = None

    async def start(self) -> None:
        from aiohttp import web

        async def index(_req):
            return web.Response(
                text=(
                    "/debug/pprof/stacks   thread+task dump\n"
                    "/debug/pprof/profile?seconds=N  CPU profile\n"
                    "/debug/pprof/heap     tracemalloc top\n"
                )
            )

        async def stacks(_req):
            return web.Response(text=all_stacks())

        async def profile(req):
            secs = float(req.query.get("seconds", "5"))
            text = await asyncio.to_thread(cpu_profile, min(secs, 60.0))
            return web.Response(text=text)

        async def heap(_req):
            return web.Response(text=heap_stats())

        app = web.Application()
        app.router.add_get("/debug/pprof", index)
        app.router.add_get("/debug/pprof/", index)
        app.router.add_get("/debug/pprof/stacks", stacks)
        app.router.add_get("/debug/pprof/profile", profile)
        app.router.add_get("/debug/pprof/heap", heap)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        host, _, port = self.addr.replace("tcp://", "").rpartition(":")
        site = web.TCPSite(self._runner, host or "127.0.0.1", int(port))
        await site.start()

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()


def collect_debug_dump(
    rpc_addr: str,
    out_dir: str,
    pprof_addr: str = "",
    label: str = "dump",
) -> str:
    """`cometbft debug dump`: snapshot a live node's observable state
    into <out_dir>/<label>-<ts>.zip. Uses plain HTTP so it works
    against any running node."""
    import urllib.request

    os.makedirs(out_dir, exist_ok=True)
    ts = time.strftime("%Y%m%d-%H%M%S")
    path = os.path.join(out_dir, f"{label}-{ts}.zip")

    def fetch(base, p):
        with urllib.request.urlopen(base + p, timeout=10) as f:
            return f.read()

    rpc = rpc_addr if rpc_addr.startswith("http") else f"http://{rpc_addr}"
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        for name, p in (
            ("status.json", "/status"),
            ("net_info.json", "/net_info"),
            ("consensus_state.json", "/dump_consensus_state"),
            ("abci_info.json", "/abci_info"),
        ):
            try:
                z.writestr(name, fetch(rpc, p))
            except Exception as e:
                z.writestr(name + ".err", str(e))
        if pprof_addr:
            pp = (
                pprof_addr
                if pprof_addr.startswith("http")
                else f"http://{pprof_addr}"
            )
            for name, p in (
                ("stacks.txt", "/debug/pprof/stacks"),
                ("heap.txt", "/debug/pprof/heap"),
            ):
                try:
                    z.writestr(name, fetch(pp, p))
                except Exception as e:
                    z.writestr(name + ".err", str(e))
        z.writestr(
            "meta.json",
            json.dumps({"ts": ts, "rpc": rpc, "pprof": pprof_addr}),
        )
    return path
