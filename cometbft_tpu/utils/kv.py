"""Embedded KV store abstraction (the reference's cometbft-db seam).

Two backends: in-memory dict (tests, like memdb) and sqlite3 (durable,
transactional, ships with CPython — the role goleveldb/pebble plays for
the reference). Keys/values are bytes; batches are atomic.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple


class KV:
    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def write_batch(self, sets, deletes=()) -> None:
        """Atomic batch: sets = [(k, v)], deletes = [k]."""
        raise NotImplementedError

    def iter_prefix(self, prefix: bytes) -> Iterator[Tuple[bytes, bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemKV(KV):
    def __init__(self):
        self._d: Dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def get(self, key):
        with self._lock:
            return self._d.get(key)

    def set(self, key, value):
        with self._lock:
            self._d[bytes(key)] = bytes(value)

    def delete(self, key):
        with self._lock:
            self._d.pop(key, None)

    def write_batch(self, sets, deletes=()):
        with self._lock:
            for k, v in sets:
                self._d[bytes(k)] = bytes(v)
            for k in deletes:
                self._d.pop(k, None)

    def iter_prefix(self, prefix):
        with self._lock:
            items = sorted(
                (k, v) for k, v in self._d.items() if k.startswith(prefix)
            )
        yield from items


class SqliteKV(KV):
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)"
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()
        self._lock = threading.RLock()

    def get(self, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)
            ).fetchone()
        return row[0] if row else None

    def set(self, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", (key, value)
            )
            self._conn.commit()

    def delete(self, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()

    def write_batch(self, sets, deletes=()):
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)", list(sets)
            )
            if deletes:
                self._conn.executemany(
                    "DELETE FROM kv WHERE k = ?", [(k,) for k in deletes]
                )
            self._conn.commit()

    def iter_prefix(self, prefix):
        hi = prefix + b"\xff" * 8
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k >= ? AND k <= ? ORDER BY k",
                (prefix, hi),
            ).fetchall()
        for k, v in rows:
            if bytes(k).startswith(prefix):
                yield bytes(k), bytes(v)

    def close(self):
        self._conn.close()


def open_kv(backend: str, path: Optional[str] = None) -> KV:
    if backend == "memdb":
        return MemKV()
    if backend == "sqlite":
        assert path
        return SqliteKV(path)
    if backend == "logdb":
        # native C++ log-structured engine (the reference's pebble role)
        assert path
        from .logdb import LogDB

        return LogDB(path)
    raise ValueError(f"unknown db backend {backend}")
