"""Block, Header, Commit data model (reference types/block.go).

Hashes follow the reference scheme: Header.hash() is the merkle root of
the proto-encoded header fields in order (types/block.go:409-447);
Commit.hash() is the merkle root of the encoded CommitSigs; Data.hash()
the merkle root of raw txs (each leaf is the tx bytes, reference
types/tx.go Txs.Hash uses tx hashes as leaves — we hash tx first for
identical semantics).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle
from ..utils import proto

MAX_HEADER_BYTES = 626

# BlockIDFlag (types/block.go:605)
BLOCK_ID_FLAG_ABSENT = 1
BLOCK_ID_FLAG_COMMIT = 2
BLOCK_ID_FLAG_NIL = 3


def tx_hash(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return proto.field_varint(1, self.total) + proto.field_bytes(
            2, self.hash
        )

    def __repr__(self) -> str:
        return f"{self.total}:{self.hash.hex()[:12]}"


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        return not self.hash and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return len(self.hash) == 32 and self.part_set_header.total > 0

    def key(self) -> bytes:
        return (
            self.hash
            + self.part_set_header.total.to_bytes(4, "big")
            + self.part_set_header.hash
        )

    def encode(self) -> bytes:
        return proto.field_bytes(1, self.hash) + proto.field_message(
            2, self.part_set_header.encode()
        )

    def __repr__(self) -> str:
        if self.is_nil():
            return "BlockID<nil>"
        return f"BlockID<{self.hash.hex()[:12]}:{self.part_set_header!r}>"


NIL_BLOCK_ID = BlockID()


@dataclass(frozen=True)
class Header:
    # versioning
    version_block: int = 11
    version_app: int = 0
    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> Optional[bytes]:
        """Merkle root of the encoded fields (types/block.go:409)."""
        if not self.validators_hash:
            return None
        ver = proto.field_varint(1, self.version_block) + proto.field_varint(
            2, self.version_app
        )
        fields = [
            ver,
            self.chain_id.encode(),
            proto.varint(self.height),
            proto.timestamp(self.time_ns),
            self.last_block_id.encode(),
            self.last_commit_hash,
            self.data_hash,
            self.validators_hash,
            self.next_validators_hash,
            self.consensus_hash,
            self.app_hash,
            self.last_results_hash,
            self.evidence_hash,
            self.proposer_address,
        ]
        return merkle.hash_from_byte_slices(fields)


@dataclass(frozen=True)
class CommitSig:
    block_id_flag: int = BLOCK_ID_FLAG_ABSENT
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls()

    def for_block(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_COMMIT

    def is_absent(self) -> bool:
        return self.block_id_flag == BLOCK_ID_FLAG_ABSENT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this sig endorsed (commit's id, nil, or zero)."""
        if self.block_id_flag == BLOCK_ID_FLAG_COMMIT:
            return commit_block_id
        return NIL_BLOCK_ID

    def encode(self) -> bytes:
        return (
            proto.field_varint(1, self.block_id_flag)
            + proto.field_bytes(2, self.validator_address)
            + proto.field_message(3, proto.timestamp(self.timestamp_ns))
            + proto.field_bytes(4, self.signature)
        )

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BLOCK_ID_FLAG_ABSENT,
            BLOCK_ID_FLAG_COMMIT,
            BLOCK_ID_FLAG_NIL,
        ):
            raise ValueError(f"unknown BlockIDFlag {self.block_id_flag}")
        if self.is_absent():
            if self.validator_address or self.signature:
                raise ValueError("absent CommitSig with data")
        else:
            if len(self.validator_address) != 20:
                raise ValueError("invalid validator address size")
            if not self.signature or len(self.signature) > 96:
                raise ValueError("invalid signature size")


@dataclass(frozen=True)
class ExtendedCommitSig(CommitSig):
    """CommitSig carrying the vote extension + its signature
    (reference types/block.go ExtendedCommitSig — ABCI 2.0 vote
    extensions)."""

    extension: bytes = b""
    extension_signature: bytes = b""

    def strip(self) -> CommitSig:
        return CommitSig(
            block_id_flag=self.block_id_flag,
            validator_address=self.validator_address,
            timestamp_ns=self.timestamp_ns,
            signature=self.signature,
        )


@dataclass
class ExtendedCommit:
    """Commit whose signatures carry vote extensions (reference
    types/block.go ExtendedCommit); persisted by the block store
    (store/store.go:481 SaveBlockWithExtendedCommit) and replayed into
    the next height's PrepareProposal as ExtendedCommitInfo."""

    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    extended_signatures: List[ExtendedCommitSig] = field(
        default_factory=list
    )

    def to_commit(self) -> "Commit":
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.block_id,
            signatures=[s.strip() for s in self.extended_signatures],
        )


@dataclass
class Commit:
    height: int = 0
    round: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    signatures: List[CommitSig] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def size(self) -> int:
        return len(self.signatures)

    def hash(self) -> bytes:
        if self._hash is None:
            from ..utils import wirecodec

            nat = wirecodec.module()
            if nat is not None:
                try:  # one call: native sig encode + RFC 6962 fold
                    self._hash = nat.commit_merkle_root(self.signatures)
                    return self._hash
                except Exception:  # pragma: no cover - odd sig shapes
                    pass
            self._hash = merkle.hash_from_byte_slices(
                [cs.encode() for cs in self.signatures]
            )
        return self._hash

    def validate_basic(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round in commit")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()


@dataclass
class Data:
    txs: List[bytes] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [tx_hash(tx) for tx in self.txs]
            )
        return self._hash


@dataclass
class Block:
    header: Header
    data: Data = field(default_factory=Data)
    evidence: list = field(default_factory=list)
    last_commit: Optional[Commit] = None

    def hash(self) -> Optional[bytes]:
        return self.header.hash()

    @property
    def height(self) -> int:
        return self.header.height

    @property
    def chain_id(self) -> str:
        return self.header.chain_id

    def encode(self) -> bytes:
        """Deterministic serialization (framework wire/storage format)."""
        from ..utils import codec

        return codec.encode_block(self)

    def validate_basic(self) -> None:
        if self.header.height < 1:
            raise ValueError("block height must be >= 1")
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil LastCommit at height > 1")
            self.last_commit.validate_basic()
        if (
            self.last_commit is not None
            and self.header.last_commit_hash != self.last_commit.hash()
        ):
            raise ValueError("wrong LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong DataHash")
