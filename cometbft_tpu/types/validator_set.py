"""Validator / ValidatorSet with proposer-priority rotation.

Behavioral parity with reference types/validator_set.go: weighted
round-robin proposer selection via accumulated priorities, with
centering and scaling to bound priority spread
(PriorityWindowSizeFactor = 2), and the same update semantics
(types/validator_set.go updateWithChangeSet).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..crypto import merkle
from ..crypto.keys import PubKey
from ..utils import proto

PRIORITY_WINDOW_SIZE_FACTOR = 2
MAX_TOTAL_VOTING_POWER = (1 << 63) // 8


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    address: bytes = b""
    proposer_priority: int = 0

    def __post_init__(self):
        if not self.address:
            self.address = self.pub_key.address()

    def copy(self) -> "Validator":
        return Validator(
            self.pub_key, self.voting_power, self.address,
            self.proposer_priority,
        )

    def encode(self) -> bytes:
        """SimpleValidator proto encoding used for ValidatorsHash
        (types/validator.go Bytes: pubkey + voting power)."""
        pk = proto.field_bytes(1, self.pub_key.key_bytes)
        return proto.field_message(1, pk) + proto.field_varint(
            2, self.voting_power
        )

    def compare_proposer_priority(self, other: "Validator") -> int:
        if self.proposer_priority != other.proposer_priority:
            return -1 if self.proposer_priority > other.proposer_priority else 1
        if self.address < other.address:
            return -1
        if self.address > other.address:
            return 1
        return 0


class ValidatorSet:
    def __init__(self, validators: Sequence[Validator]):
        vals = [v.copy() for v in validators]
        vals.sort(key=lambda v: (-v.voting_power, v.address))
        self.validators: List[Validator] = vals
        self._by_address: Dict[bytes, int] = {
            v.address: i for i, v in enumerate(vals)
        }
        if len(self._by_address) != len(vals):
            raise ValueError("duplicate validator address")
        self.proposer: Optional[Validator] = None
        if vals:
            self.proposer = self._compute_max_priority_validator()

    # --- basic accessors -------------------------------------------------

    def __len__(self) -> int:
        return len(self.validators)

    def size(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        # memoized like hash(): every add_vote compares accumulated
        # power against the total, so an unmemoized sum here is O(V)
        # per vote = O(V^2) per height (the bench.py scaling leg
        # measures the slope). Powers only change through
        # update_with_change_set, which drops the memo.
        tp = getattr(self, "_total_power", None)
        if tp is None:
            tp = sum(v.voting_power for v in self.validators)  # bftlint: disable=ASY117 — memoized: this sum reruns once per membership/power change, not per message
            if tp > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power overflow")
            self._total_power = tp
        return tp

    def has_address(self, addr: bytes) -> bool:
        return addr in self._by_address

    def get_by_address(self, addr: bytes):
        i = self._by_address.get(addr)
        if i is None:
            return -1, None
        return i, self.validators[i]

    def get_by_index(self, i: int) -> Optional[Validator]:
        if 0 <= i < len(self.validators):
            return self.validators[i]
        return None

    def hash(self) -> bytes:
        # memoized: the hash covers only (pubkey, power) in canonical
        # order — NOT proposer priorities — so it survives priority
        # rotation and copies unchanged. The replay pipeline hashes
        # the (unchanging) valset twice per height without this.
        h = getattr(self, "_hash", None)
        if h is None:
            h = merkle.hash_from_byte_slices(
                [v.encode() for v in self.validators]
            )
            self._hash = h
        return h

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet.__new__(ValidatorSet)
        vs.validators = [v.copy() for v in self.validators]
        vs._by_address = dict(self._by_address)
        vs._hash = getattr(self, "_hash", None)
        vs._total_power = getattr(self, "_total_power", None)
        vs.proposer = (
            None
            if self.proposer is None
            else vs.validators[self._by_address[self.proposer.address]]
        )
        return vs

    # --- proposer rotation ----------------------------------------------

    def _compute_max_priority_validator(self) -> Validator:
        best = self.validators[0]
        for v in self.validators[1:]:
            if v.compare_proposer_priority(best) < 0:
                best = v
        return best

    def _rescale_priorities(self) -> None:
        if not self.validators:
            return
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        pmax = max(v.proposer_priority for v in self.validators)
        pmin = min(v.proposer_priority for v in self.validators)
        diff = pmax - pmin
        if diff > 0 and diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                v.proposer_priority = _int_div_round_to_zero(
                    v.proposer_priority, ratio
                )

    def _shift_by_avg_proposer_priority(self) -> None:
        if not self.validators:
            return
        avg = _int_div_round_to_zero(
            sum(v.proposer_priority for v in self.validators),
            len(self.validators),
        )
        for v in self.validators:
            v.proposer_priority -= avg

    def increment_proposer_priority(self, times: int) -> None:
        if not self.validators:
            return
        self._rescale_priorities()
        self._shift_by_avg_proposer_priority()
        proposer = self.proposer
        for _ in range(times):
            for v in self.validators:
                v.proposer_priority += v.voting_power
            proposer = self._compute_max_priority_validator()
            proposer.proposer_priority -= self.total_voting_power()
        self.proposer = proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        vs = self.copy()
        vs.increment_proposer_priority(times)
        return vs

    def get_proposer(self) -> Optional[Validator]:
        return self.proposer

    # --- updates ---------------------------------------------------------

    def update_with_change_set(self, changes: Sequence[Validator]) -> None:
        """Apply validator updates: power 0 removes, new adds, else updates
        (reference types/validator_set.go:updateWithChangeSet)."""
        if not changes:
            return
        seen = set()
        for c in changes:
            if c.address in seen:
                raise ValueError("duplicate address in changes")
            seen.add(c.address)
            if c.voting_power < 0:
                raise ValueError("negative voting power")

        removals = {c.address for c in changes if c.voting_power == 0}
        updates = [c for c in changes if c.voting_power > 0]
        for addr in removals:
            if addr not in self._by_address:
                raise ValueError("removing unknown validator")

        # index once: the per-validator `next(...)` scans here were
        # O(V x changes) — the exact nested-committee-loop shape
        # ASY118 exists to catch (a 128-validator set churning a
        # quarter of its members paid ~8k scans per update)
        upd_by_addr = {c.address: c for c in updates}

        # compute priority for new validators: -1.125 * new total power
        new_total = sum(
            c.voting_power for c in updates if c.address not in self._by_address
        )
        for v in self.validators:
            if v.address not in removals:
                upd = upd_by_addr.get(v.address)
                if upd is None:
                    new_total += v.voting_power
                else:
                    new_total += upd.voting_power
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power overflow after update")

        new_vals: List[Validator] = []
        for v in self.validators:
            if v.address in removals:
                continue
            upd = upd_by_addr.get(v.address)
            if upd is not None:
                v = v.copy()
                v.voting_power = upd.voting_power
                if isinstance(upd.pub_key, type(v.pub_key)):
                    v.pub_key = upd.pub_key
            new_vals.append(v)
        existing = {v.address for v in new_vals}
        for c in updates:
            if c.address not in existing:
                nv = c.copy()
                nv.proposer_priority = -(new_total + new_total // 8)
                new_vals.append(nv)

        if not new_vals:
            raise ValueError("validator set cannot become empty")
        new_vals.sort(key=lambda v: (-v.voting_power, v.address))
        self.validators = new_vals
        self._by_address = {v.address: i for i, v in enumerate(new_vals)}
        # membership/power changed: drop both memos
        self._hash = None
        self._total_power = None
        self._shift_by_avg_proposer_priority()
        self.proposer = self._compute_max_priority_validator()

    def validate_basic(self) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        self.total_voting_power()


def _int_div_round_to_zero(a: int, b: int) -> int:
    """Go-style integer division (truncates toward zero)."""
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return q


def random_validator_set(n: int, power: int = 100) -> tuple:
    """Test helper: returns (ValidatorSet, [Ed25519PrivKey]) sorted to
    match validator order."""
    from ..crypto.keys import Ed25519PrivKey

    privs = [Ed25519PrivKey.generate() for _ in range(n)]
    vals = [Validator(p.pub_key(), power) for p in privs]
    vs = ValidatorSet(vals)
    order = {v.address: i for i, v in enumerate(vs.validators)}
    privs.sort(key=lambda p: order[p.pub_key().address()])
    return vs, privs
