"""VoteSet: quorum tracking for one (height, round, type).

Behavioral parity with reference types/vote_set.go: one vote per
validator (conflicts tracked for evidence), weighted 2/3 majority per
BlockID, peer-claimed majorities ("maj23") tracking, commit extraction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .block import (
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    BlockID,
    Commit,
    CommitSig,
    ExtendedCommit,
    ExtendedCommitSig,
)
from .validator_set import ValidatorSet
from .vote import PRECOMMIT, Vote, is_vote_type_valid


class ErrVoteConflictingVotes(Exception):
    def __init__(self, existing: Vote, new: Vote):
        super().__init__("conflicting votes from validator")
        self.existing = existing
        self.new = new


@dataclass
class _BlockVotes:
    votes_by_index: Dict[int, Vote] = field(default_factory=dict)
    sum_power: int = 0


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        type_: int,
        val_set: ValidatorSet,
        verify_signatures: bool = True,
        sig_cache=None,
    ):
        assert is_vote_type_valid(type_)
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type_ = type_
        self.val_set = val_set
        self.verify = verify_signatures
        # shared SignatureCache: signatures pre-verified by the async
        # coalescing queue (crypto/coalesce.py) resolve as cache hits
        # here, keeping the single-writer add_vote path off the crypto
        self.sig_cache = sig_cache
        self.votes: List[Optional[Vote]] = [None] * val_set.size()
        # append-ordered log of accepted votes: the consensus
        # reactor's per-peer gossip cursors read `vote_log[i:]` so a
        # gossip tick costs O(new votes), not O(validators) — the
        # ASY117 fix. Append-only BY DESIGN; the whole VoteSet is
        # per-(height, round, type) and dropped on height advance.
        self.vote_log: List[Vote] = []  # bftlint: disable=ASY119 — append-only gossip cursor log, bounded by the validator count and dropped with the per-height VoteSet
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: Dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: Dict[str, BlockID] = {}

    def size(self) -> int:
        return self.val_set.size()

    def add_vote(self, vote: Vote) -> bool:
        """Returns True if the vote was added. Raises on conflict
        (evidence!) or invalid signature."""
        if vote is None:
            raise ValueError("nil vote")
        vote.validate_basic()
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type_ != self.type_
        ):
            raise ValueError(
                f"vote {vote.height}/{vote.round}/{vote.type_} does not "
                f"match VoteSet {self.height}/{self.round}/{self.type_}"
            )
        idx = vote.validator_index
        val = self.val_set.get_by_index(idx)
        if val is None:
            raise ValueError(f"validator index {idx} out of range")
        if val.address != vote.validator_address:
            raise ValueError("vote address does not match validator index")

        existing = self.votes[idx]
        if existing is not None:
            if existing.block_id.key() == vote.block_id.key():
                return False  # duplicate
            # conflicting vote: verify before raising as evidence
            if self.verify and not self._verify_vote(vote, val):
                raise ValueError("invalid signature on conflicting vote")
            raise ErrVoteConflictingVotes(existing, vote)

        if self.verify and not self._verify_vote(vote, val):
            raise ValueError("invalid vote signature")

        self.votes[idx] = vote
        self.vote_log.append(vote)
        self.sum += val.voting_power
        bk = vote.block_id.key()
        bv = self.votes_by_block.setdefault(bk, _BlockVotes())
        bv.votes_by_index[idx] = vote
        bv.sum_power += val.voting_power
        if (
            self.maj23 is None
            and bv.sum_power * 3 > self.val_set.total_voting_power() * 2
        ):
            self.maj23 = vote.block_id
        return True

    def _verify_vote(self, vote: Vote, val) -> bool:
        """Single-vote verify, fronted by the shared SignatureCache.

        The address-vs-index check happened in add_vote, and the cache
        key binds (sign_bytes, sig, pubkey), so a hit is exactly as
        strong as re-running the curve math (reference
        types/signature_cache.go used at types/validation.go:82-91).
        """
        if self.sig_cache is not None:
            sb = vote.sign_bytes(self.chain_id)
            if self.sig_cache.contains(
                sb, vote.signature, val.pub_key.key_bytes
            ):
                return True
            ok = vote.verify(self.chain_id, val.pub_key)
            if ok:
                self.sig_cache.add(sb, vote.signature, val.pub_key.key_bytes)
            return ok
        return vote.verify(self.chain_id, val.pub_key)

    def get_vote(self, idx: int) -> Optional[Vote]:
        return self.votes[idx]

    def get_vote_by_address(self, addr: bytes) -> Optional[Vote]:
        i, _ = self.val_set.get_by_address(addr)
        return None if i < 0 else self.votes[i]

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def two_thirds_majority(self) -> Optional[BlockID]:
        return self.maj23

    def has_two_thirds_any(self) -> bool:
        return self.sum * 3 > self.val_set.total_voting_power() * 2

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def bit_array(self) -> List[bool]:
        return [v is not None for v in self.votes]

    def bit_array_by_block_id(self, block_id: BlockID) -> List[bool]:
        bv = self.votes_by_block.get(block_id.key())
        out = [False] * self.size()
        if bv:
            for i in bv.votes_by_index:
                out[i] = True
        return out

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """Record a peer's claim that +2/3 voted for block_id
        (drives targeted vote gossip; types/vote_set.go SetPeerMaj23)."""
        prev = self.peer_maj23s.get(peer_id)
        if prev is not None and prev.key() != block_id.key():
            raise ValueError("conflicting peer maj23 claims")
        self.peer_maj23s[peer_id] = block_id

    def make_commit(self) -> Commit:
        assert self.type_ == PRECOMMIT, "commit only from precommits"
        if self.maj23 is None or self.maj23.is_nil():
            raise ValueError("no +2/3 majority for a block")
        sigs = []
        for i, vote in enumerate(self.votes):
            if vote is None:
                sigs.append(CommitSig.absent())
                continue
            if vote.block_id.key() == self.maj23.key():
                flag = BLOCK_ID_FLAG_COMMIT
            elif vote.block_id.is_nil():
                flag = BLOCK_ID_FLAG_NIL
            else:
                flag = BLOCK_ID_FLAG_NIL  # vote for other block counts nil
            sigs.append(
                CommitSig(
                    block_id_flag=flag,
                    validator_address=vote.validator_address,
                    timestamp_ns=vote.timestamp_ns,
                    signature=vote.signature,
                )
            )
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.maj23,
            signatures=sigs,
        )

    def make_extended_commit(
        self, require_extensions: bool = True
    ) -> ExtendedCommit:
        """Commit + per-vote extensions (reference
        types/vote_set.go MakeExtendedCommit): the payload the proposer
        feeds to the NEXT height's PrepareProposal.

        require_extensions (reference EnsureExtension): every
        COMMIT-flag signature must carry an extension signature —
        persisting one without it would make 'extension absent' and
        'extension stripped' indistinguishable downstream."""
        base = self.make_commit()
        ext_sigs = []
        for cs, vote in zip(base.signatures, self.votes):
            is_commit = cs.block_id_flag == BLOCK_ID_FLAG_COMMIT
            if (
                require_extensions
                and is_commit
                and not (vote and vote.extension_signature)
            ):
                raise ValueError(
                    "commit vote without extension signature "
                    f"(validator {cs.validator_address.hex()[:12]})"
                )
            # extension data only rides COMMIT-flag lanes (reference
            # ExtendedCommitSig.ValidateBasic): a vote for another
            # block is downgraded to NIL and must not leak its payload
            ext_sigs.append(
                ExtendedCommitSig(
                    block_id_flag=cs.block_id_flag,
                    validator_address=cs.validator_address,
                    timestamp_ns=cs.timestamp_ns,
                    signature=cs.signature,
                    extension=(
                        vote.extension if (vote and is_commit) else b""
                    ),
                    extension_signature=(
                        vote.extension_signature
                        if (vote and is_commit)
                        else b""
                    ),
                )
            )
        return ExtendedCommit(
            height=base.height,
            round=base.round,
            block_id=base.block_id,
            extended_signatures=ext_sigs,
        )
