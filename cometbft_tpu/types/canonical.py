"""Canonical sign-bytes encodings (reference types/canonical.go).

Sign bytes are the *security-critical* encoding: every vote/proposal
signature covers exactly these bytes, and the TPU verifier hashes them
in-kernel. Format: protobuf wire encoding of CanonicalVote /
CanonicalProposal, varint-length-delimited (libs/protoio), with
sfixed64 height/round (canonical = fixed width) and the chain id last.
"""

from __future__ import annotations

from ..utils import proto
from .block import BlockID

# SignedMsgType (proto/tendermint/types/types.proto)
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def canonical_block_id(bid: BlockID) -> bytes:
    if bid is None or bid.is_nil():
        return None
    psh = proto.field_varint(1, bid.part_set_header.total) + proto.field_bytes(
        2, bid.part_set_header.hash
    )
    return proto.field_bytes(1, bid.hash) + proto.field_message(2, psh)


def vote_sign_bytes_parts(
    chain_id: str,
    type_: int,
    height: int,
    round_: int,
    block_id: BlockID,
):
    """(prefix, suffix) of the CanonicalVote body around the timestamp
    field — everything except the timestamp is identical across the
    signatures of one commit, so verification loops encode these once
    and splice the per-signature timestamp in (150 sigs/commit on the
    replay path)."""
    prefix = proto.field_varint(1, type_)
    prefix += proto.field_sfixed64(2, height)
    prefix += proto.field_sfixed64(3, round_)
    cbid = canonical_block_id(block_id)
    if cbid is not None:
        prefix += proto.field_message(4, cbid)
    return prefix, proto.field_string(6, chain_id)


def finish_vote_sign_bytes(
    prefix: bytes, suffix: bytes, timestamp_ns: int
) -> bytes:
    return proto.delimited(
        prefix
        + proto.field_message(5, proto.timestamp(timestamp_ns))
        + suffix
    )


def vote_sign_bytes(
    chain_id: str,
    type_: int,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalVote encoding, length-delimited (types/vote.go:152)."""
    prefix, suffix = vote_sign_bytes_parts(
        chain_id, type_, height, round_, block_id
    )
    return finish_vote_sign_bytes(prefix, suffix, timestamp_ns)


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp_ns: int,
) -> bytes:
    """CanonicalProposal encoding, length-delimited (types/proposal.go)."""
    body = proto.field_varint(1, PROPOSAL_TYPE)
    body += proto.field_sfixed64(2, height)
    body += proto.field_sfixed64(3, round_)
    body += proto.field_sfixed64(4, pol_round)
    cbid = canonical_block_id(block_id)
    if cbid is not None:
        body += proto.field_message(5, cbid)
    body += proto.field_message(6, proto.timestamp(timestamp_ns))
    body += proto.field_string(7, chain_id)
    return proto.delimited(body)


def vote_extension_sign_bytes(
    chain_id: str, height: int, round_: int, extension: bytes
) -> bytes:
    """CanonicalVoteExtension (vote extensions, ABCI 2.0)."""
    body = proto.field_bytes(1, extension)
    body += proto.field_sfixed64(2, height)
    body += proto.field_sfixed64(3, round_)
    body += proto.field_string(4, chain_id)
    return proto.delimited(body)
