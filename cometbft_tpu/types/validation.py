"""Commit verification — the seam every sync path funnels through.

Parity with reference types/validation.go: VerifyCommit (:30),
VerifyCommitLight (:65), VerifyCommitLightTrusting (:148), the
``*AllSignatures`` and ``*WithCache`` variants, with an injectable batch
verifier (reference :270). Consumers: blocksync replay, adaptive
ingest, light-client bisection, evidence checks (SURVEY.md §2.3).

TPU-first departure: the reference dispatches between a sequential path
and a random-linear-combination CPU batch; here every multi-signature
verification builds one lane batch for the TPU kernel
(crypto/batch.TpuBatchVerifier), which returns per-lane verdicts — the
"light" early-exit at +2/3 is pointless on SIMD lanes, so light mode
just restricts *which* signatures are checked (the ones counted toward
the tally), identically to the reference's semantics.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Optional

from ..crypto import scheduler as crypto_sched
from ..crypto.scheduler import (  # re-exported: consumers pass these
    PRIORITY_CATCHUP,
    PRIORITY_LIGHT,
    PRIORITY_LIVE,
)
from .block import BLOCK_ID_FLAG_COMMIT, BlockID, Commit
from .canonical import (
    PRECOMMIT_TYPE,
    finish_vote_sign_bytes,
    vote_sign_bytes_parts,
)
from .signature_cache import SignatureCache
from .validator_set import ValidatorSet


class CommitVerifyError(Exception):
    pass


class ErrNotEnoughVotingPower(CommitVerifyError):
    pass


class ErrInvalidSignature(CommitVerifyError):
    pass


def _commit_sign_bytes(chain_id: str, commit: Commit, cs) -> bytes:
    """Sign bytes for one CommitSig, memoized on the commit (decoded
    commits are immutable by convention, codec.decode_commit) at two
    levels: the timestamp-independent (prefix, suffix) per block-id
    flag class, and the FINISHED bytes per (flag, timestamp) —
    proposer-aligned voting makes many signatures of one commit share
    a timestamp, so a 150-signature commit often encodes once, and
    never more than once per distinct timestamp."""
    parts = getattr(commit, "_sb_parts", None)
    if parts is None:
        parts = {}
        commit._sb_parts = parts
    flag_commit = cs.block_id_flag == BLOCK_ID_FLAG_COMMIT
    key = (chain_id, flag_commit, cs.timestamp_ns)
    sb = parts.get(key)
    if sb is None:
        pkey = (chain_id, flag_commit)
        ps = parts.get(pkey)
        if ps is None:
            ps = vote_sign_bytes_parts(
                chain_id,
                PRECOMMIT_TYPE,
                commit.height,
                commit.round,
                cs.block_id(commit.block_id),
            )
            parts[pkey] = ps
        sb = finish_vote_sign_bytes(ps[0], ps[1], cs.timestamp_ns)
        parts[key] = sb
    return sb


def _basic_checks(
    vals: ValidatorSet, commit: Commit, height: int, block_id: Optional[BlockID]
) -> None:
    if commit is None:
        raise CommitVerifyError("nil commit")
    if vals.size() != commit.size():
        raise CommitVerifyError(
            f"validator set size {vals.size()} != commit size {commit.size()}"
        )
    if height != commit.height:
        raise CommitVerifyError(
            f"height {height} != commit height {commit.height}"
        )
    if block_id is not None and block_id.key() != commit.block_id.key():
        raise CommitVerifyError("wrong BlockID in commit")


def _run_batch_async(
    items,
    cache: Optional[SignatureCache],
    priority: Optional[int] = None,
    label: str = "",
):
    """items: list of (pubkey, sign_bytes, sig). Returns a handle whose
    ``result()`` yields list[bool] — async so callers (the blocksync
    window pipeline) can overlap host work with the verification in
    flight.

    THE single choke point onto the unified verify scheduler
    (crypto/scheduler.py): cache-unskipped lanes are submitted as one
    ticket under the caller's priority class — live round > light
    session > catch-up/evidence (default) — and the scheduler takes
    the calibrated backend-routing decision from there. The handle is
    genuinely pending on every backend: device batches ride the XLA
    async dispatch, host-routed batches ride the slot-bounded chunk
    pipeline — either way the caller's decode/apply work proceeds
    while lanes verify (docs/PERF.md "Unified verify scheduler")."""
    to_verify = []
    lanes = []
    skip = [False] * len(items)
    if cache is not None:
        for i, (pk, sb, sig) in enumerate(items):
            if cache.contains(sb, sig, pk.key_bytes):
                skip[i] = True
    for i, item in enumerate(items):
        if not skip[i]:
            lanes.append(item)
            to_verify.append(i)
    pending = (
        crypto_sched.scheduler().submit(
            lanes,
            priority=PRIORITY_CATCHUP if priority is None else priority,
            label=label,
        )
        if lanes
        else None
    )
    return _BatchHandle(items, to_verify, pending, cache)


class _BatchHandle:
    """Cache-aware batch handle: ``result()`` resolves the pending
    dispatch, fills verdicts over the cache-skipped lanes, and feeds
    verified signatures back into the cache."""

    __slots__ = ("_items", "_to_verify", "_pending", "_cache")

    def __init__(self, items, to_verify, pending, cache) -> None:
        self._items = items
        self._to_verify = to_verify
        self._pending = pending
        self._cache = cache

    def result(self):
        items, cache = self._items, self._cache
        oks = [True] * len(items)
        if self._pending is not None:
            _, verdicts = self._pending.result()
            for i, ok in zip(self._to_verify, verdicts):
                oks[i] = ok
                if ok and cache is not None:
                    pk, sb, sig = items[i]
                    cache.add(sb, sig, pk.key_bytes)
        return oks


def _run_batch(
    items,
    cache: Optional[SignatureCache],
    priority: Optional[int] = None,
    label: str = "",
):
    """items: list of (pubkey, sign_bytes, sig). Returns list[bool]."""
    if not items:
        return []
    return _run_batch_async(
        items, cache, priority=priority, label=label
    ).result()


def verify_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
    cache: Optional[SignatureCache] = None,
    priority: Optional[int] = None,
) -> None:
    """Full verification: every non-absent signature must be valid
    (including nil votes), and >2/3 of power must have signed block_id.
    (reference types/validation.go:30; used by blocksync + ingest).
    ``priority`` is the verify-scheduler class (PRIORITY_LIVE for the
    consensus hot path; default catch-up)."""
    _basic_checks(vals, commit, height, block_id)
    items = []
    tally_idx = []
    for i, cs in enumerate(commit.signatures):  # bftlint: disable=ASY117 — verifying an O(V) commit payload is O(V) by construction; once per commit received, curve math batch-verified via the lane cache
        if cs.is_absent():
            continue
        val = vals.get_by_index(i)
        if val.address != cs.validator_address:
            raise CommitVerifyError(
                f"commit sig {i} address mismatch with validator set"
            )
        items.append(
            (val.pub_key, _commit_sign_bytes(chain_id, commit, cs), cs.signature)
        )
        tally_idx.append(i)
    oks = _run_batch(items, cache, priority=priority, label="commit")
    tallied = 0
    for (i, ok) in zip(tally_idx, oks):
        if not ok:
            raise ErrInvalidSignature(f"invalid signature for validator {i}")
        cs = commit.signatures[i]
        if cs.for_block():
            tallied += vals.get_by_index(i).voting_power
    if not tallied * 3 > vals.total_voting_power() * 2:
        raise ErrNotEnoughVotingPower(
            f"tallied {tallied} <= 2/3 of {vals.total_voting_power()}"
        )


def _collect_light_lanes(
    chain_id: str,
    vals: ValidatorSet,
    block_id: Optional[BlockID],
    height: int,
    commit: Commit,
    all_signatures: bool,
    items: list,
) -> list:
    """Shared lane builder for LIGHT verification — the serial path
    and the coalesced jobs path both run exactly this, so their
    verdicts cannot drift. Appends (pubkey, sign_bytes, sig) lanes to
    ``items``; returns [(lane_idx, validator_idx)]. Raises
    CommitVerifyError on structural failures."""
    _basic_checks(vals, commit, height, block_id)
    total = vals.total_voting_power()
    lanes = []
    tallied_known = 0
    for i, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        val = vals.get_by_index(i)
        if val.address != cs.validator_address:
            raise CommitVerifyError(f"commit sig {i} address mismatch")
        lanes.append((len(items), i))
        items.append(
            (val.pub_key, _commit_sign_bytes(chain_id, commit, cs), cs.signature)
        )
        tallied_known += val.voting_power
        if not all_signatures and tallied_known * 3 > total * 2:
            break  # enough power collected; verify just these lanes
    return lanes


def _fold_light_lanes(
    lanes: list, oks: list, vals: ValidatorSet, commit: Commit
) -> None:
    """Shared tally/verdict fold for LIGHT verification."""
    tallied = 0
    for lane, i in lanes:
        if not oks[lane]:
            raise ErrInvalidSignature(f"invalid signature for validator {i}")
        if commit.signatures[i].for_block():
            tallied += vals.get_by_index(i).voting_power
    total = vals.total_voting_power()
    if not tallied * 3 > total * 2:
        raise ErrNotEnoughVotingPower(
            f"tallied {tallied} <= 2/3 of {total}"
        )


def _collect_trusting_lanes(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction,
    all_signatures: bool,
    items: list,
):
    """Shared lane builder for TRUSTING verification (see
    _collect_light_lanes). Returns ([(lane_idx, voting_power)],
    total, need)."""
    if commit is None:
        raise CommitVerifyError("nil commit")
    if trust_level.numerator * 3 < trust_level.denominator or (
        trust_level.numerator > trust_level.denominator
    ):
        raise CommitVerifyError("trust level must be in [1/3, 1]")
    total = vals.total_voting_power()
    need = total * trust_level.numerator
    lanes = []
    seen = set()
    tallied_known = 0
    for cs in commit.signatures:
        if not cs.for_block():
            continue
        idx, val = vals.get_by_address(cs.validator_address)
        if idx < 0:
            continue
        if idx in seen:
            raise CommitVerifyError("double vote from same validator")
        seen.add(idx)
        lanes.append((len(items), val.voting_power))
        items.append(
            (val.pub_key, _commit_sign_bytes(chain_id, commit, cs), cs.signature)
        )
        tallied_known += val.voting_power
        if (
            not all_signatures
            and tallied_known * trust_level.denominator > need
        ):
            break
    return lanes, total, need


def _fold_trusting_lanes(
    lanes: list, oks: list, total, need, trust_level: Fraction
) -> None:
    """Shared tally/verdict fold for TRUSTING verification."""
    tallied = 0
    for lane, power in lanes:
        if not oks[lane]:
            raise ErrInvalidSignature("invalid signature in trusted commit")
        tallied += power
    if not tallied * trust_level.denominator > need:
        raise ErrNotEnoughVotingPower(
            f"trusted tally {tallied} <= {trust_level} of {total}"
        )


def verify_commit_light(
    chain_id: str,
    vals: ValidatorSet,
    block_id: BlockID,
    height: int,
    commit: Commit,
    cache: Optional[SignatureCache] = None,
    all_signatures: bool = False,
    priority: Optional[int] = None,
) -> None:
    """Light verification: only signatures for block_id are checked and
    tallied up to the 2/3 threshold (reference :65; all_signatures=True
    checks every block signature — evidence mode, reference :96)."""
    items: list = []
    lanes = _collect_light_lanes(
        chain_id, vals, block_id, height, commit, all_signatures, items
    )
    oks = _run_batch(items, cache, priority=priority, label="light")
    _fold_light_lanes(lanes, oks, vals, commit)


def verify_commits_coalesced_async(
    chain_id: str,
    jobs,
    cache: Optional[SignatureCache] = None,
    light: bool = True,
    priority: Optional[int] = None,
):
    """Async form of verify_commits_coalesced: enqueues ONE lane batch
    for every job's signatures and returns a handle whose ``result()``
    blocks for the verdicts and yields the per-job error list. The
    blocksync reactor dispatches window K+1 through this before
    applying window K's blocks, hiding the device+link latency behind
    host execution (reference blocksync/reactor.go:560-700 is strictly
    sequential per block)."""
    items = []         # global lane batch
    job_lanes = []     # per job: list of (lane_idx, val_idx)
    errors: list = [None] * len(jobs)
    for j, (vals, block_id, height, commit) in enumerate(jobs):
        lanes = []
        try:
            _basic_checks(vals, commit, height, block_id)
            total = vals.total_voting_power()
            tallied_known = 0
            for i, cs in enumerate(commit.signatures):
                want = cs.for_block() if light else not cs.is_absent()
                if not want:
                    continue
                val = vals.get_by_index(i)
                if val.address != cs.validator_address:
                    raise CommitVerifyError(
                        f"commit sig {i} address mismatch"
                    )
                lanes.append((len(items), i))
                items.append(
                    (
                        val.pub_key,
                        _commit_sign_bytes(chain_id, commit, cs),
                        cs.signature,
                    )
                )
                if light and cs.for_block():
                    tallied_known += val.voting_power
                    if tallied_known * 3 > total * 2:
                        break
        except CommitVerifyError as e:
            errors[j] = e
            lanes = []
        job_lanes.append(lanes)

    batch_handle = _run_batch_async(
        items, cache, priority=priority, label="coalesced"
    )
    return _CoalescedHandle(batch_handle, jobs, job_lanes, errors)


class _CoalescedHandle:
    """``result()`` blocks for the lane verdicts and folds them back
    into per-job errors (tally + 2/3 check per commit)."""

    __slots__ = ("_batch", "_jobs", "_job_lanes", "_errors")

    def __init__(self, batch, jobs, job_lanes, errors) -> None:
        self._batch = batch
        self._jobs = jobs
        self._job_lanes = job_lanes
        self._errors = errors

    def result(self):
        oks = self._batch.result()
        errors = self._errors
        for j, (vals, block_id, height, commit) in enumerate(
            self._jobs
        ):
            if errors[j] is not None:
                continue
            tallied = 0
            bad = None
            for lane, i in self._job_lanes[j]:
                if not oks[lane]:
                    bad = ErrInvalidSignature(
                        f"invalid signature for validator {i} "
                        f"at height {height}"
                    )
                    break
                if commit.signatures[i].for_block():
                    tallied += vals.get_by_index(i).voting_power
            if bad is not None:
                errors[j] = bad
            elif not tallied * 3 > vals.total_voting_power() * 2:
                errors[j] = ErrNotEnoughVotingPower(
                    f"height {height}: tallied {tallied} <= 2/3"
                )
        return errors


def verify_commits_coalesced(
    chain_id: str,
    jobs,
    cache: Optional[SignatureCache] = None,
    light: bool = True,
    priority: Optional[int] = None,
) -> list:
    """Verify MANY commits in one TPU dispatch (cross-height coalescing).

    jobs: list of (vals, block_id, height, commit). Returns a list of
    None (success) or CommitVerifyError per job. This is the bulk seam
    the reference cannot express: its batch verifier is per-commit
    (types/validation.go:261); here blocksync/light coalesce whole
    windows of heights into one signature-lane batch (BASELINE.json
    north star: amortize thousands of validator sigs per XLA dispatch).
    """
    return verify_commits_coalesced_async(
        chain_id, jobs, cache=cache, light=light, priority=priority
    ).result()


def verify_commit_jobs_coalesced(
    chain_id: str,
    jobs,
    cache: Optional[SignatureCache] = None,
    priority: Optional[int] = None,
) -> list:
    """Mixed-kind coalesced verification: MANY light and trusting
    commit checks land in ONE lane batch (the light-client serving
    plane's cross-client seam, light/serving.py — a bisection hop is
    one trusting + one light check, and concurrent clients' hops
    coalesce here).

    jobs: list of either
        ("light", vals, block_id, height, commit)
        ("trusting", vals, commit, trust_level)

    Returns one entry per job: None (success) or the exact
    CommitVerifyError subclass the serial path raises —
    serial-equivalence is BY CONSTRUCTION: collection and fold run
    the same _collect_*/_fold_* helpers verify_commit_light and
    verify_commit_light_trusting run, just over one shared lane
    batch (asserted end to end by tests/test_light_serving.py and
    in-bench)."""
    items: list = []
    metas: list = []
    errors: list = [None] * len(jobs)
    for j, job in enumerate(jobs):
        kind = job[0]
        try:
            if kind == "light":
                _, vals, block_id, height, commit = job
                lanes = _collect_light_lanes(
                    chain_id, vals, block_id, height, commit, False,
                    items,
                )
                metas.append(("light", lanes, vals, commit))
            elif kind == "trusting":
                _, vals, commit, trust_level = job
                lanes, total, need = _collect_trusting_lanes(
                    chain_id, vals, commit, trust_level, False, items
                )
                metas.append(
                    ("trusting", lanes, total, need, trust_level)
                )
            else:
                raise CommitVerifyError(f"unknown job kind {kind!r}")
        except CommitVerifyError as e:
            errors[j] = e
            metas.append(None)
    oks = _run_batch(items, cache, priority=priority, label="jobs")
    for j, meta in enumerate(metas):
        if meta is None:
            continue
        try:
            if meta[0] == "light":
                _, lanes, vals, commit = meta
                _fold_light_lanes(lanes, oks, vals, commit)
            else:
                _, lanes, total, need, trust_level = meta
                _fold_trusting_lanes(
                    lanes, oks, total, need, trust_level
                )
        except CommitVerifyError as e:
            errors[j] = e
    return errors


def verify_commit_light_trusting(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    trust_level: Fraction = Fraction(1, 3),
    cache: Optional[SignatureCache] = None,
    all_signatures: bool = False,
    priority: Optional[int] = None,
) -> None:
    """Trusting verification against an *old* validator set: tally power
    of trusted validators who signed; require > trust_level of trusted
    total (reference :148; used by light bisection + evidence)."""
    items: list = []
    lanes, total, need = _collect_trusting_lanes(
        chain_id, vals, commit, trust_level, all_signatures, items
    )
    oks = _run_batch(items, cache, priority=priority, label="trusting")
    _fold_trusting_lanes(lanes, oks, total, need, trust_level)


def verify_extended_commit(
    chain_id: str,
    vals: ValidatorSet,
    block_hash: bytes,
    height: int,
    ec,
    cache: Optional[SignatureCache] = None,
    priority: Optional[int] = None,
) -> None:
    """Full extended-commit verification, shared by every path that
    persists an EC received from a peer (blocksync block responses and
    the consensus catch-up gossip — the analog of the checks guarding
    reference SaveBlockWithExtendedCommit, blocksync/reactor.go:648):

      * the EC binds to this height + block hash;
      * the embedded plain commit fully verifies against ``vals``;
      * non-commit lanes carry no extension data (reference
        ExtendedCommitSig.ValidateBasic — unverifiable attacker bytes
        must never be persisted / reach the app);
      * every commit lane has an extension signature and all of them
        verify in one batch.

    Raises CommitVerifyError on any failure.
    """
    from .canonical import vote_extension_sign_bytes

    if ec.height != height or ec.block_id.hash != block_hash:
        raise CommitVerifyError("extended commit does not bind to block")
    verify_commit(
        chain_id,
        vals,
        ec.block_id,
        height,
        ec.to_commit(),
        cache=cache,
        priority=priority,
    )
    items = []
    for i, s in enumerate(ec.extended_signatures):  # bftlint: disable=ASY117 — verifying an O(V) commit payload is O(V) by construction; runs once per commit-block received and the curve math is batch-verified
        if not s.for_block():
            if s.extension or s.extension_signature:
                raise CommitVerifyError(
                    f"sig {i}: extension data on non-commit lane"
                )
            continue
        if not s.extension_signature:
            raise CommitVerifyError(
                f"commit sig {i} missing extension signature"
            )
        val = vals.get_by_index(i)
        items.append(
            (
                val.pub_key,
                vote_extension_sign_bytes(
                    chain_id, height, ec.round, s.extension
                ),
                s.extension_signature,
            )
        )
    if not all(
        _run_batch(items, cache, priority=priority, label="extension")
    ):
        raise CommitVerifyError("invalid extension signature")
