"""GenesisDoc (reference types/genesis.go): chain bootstrap document."""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto.keys import pubkey_from_type_bytes
from ..state.state_types import ConsensusParams, State
from .validator_set import Validator, ValidatorSet


@dataclass
class GenesisValidator:
    pub_key: object
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    validators: List[Validator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state_bytes: bytes = b""

    def __post_init__(self):
        if not self.genesis_time_ns:
            self.genesis_time_ns = time.time_ns()

    def validate_and_complete(self) -> None:
        if not self.chain_id:
            raise ValueError("genesis doc must include chain_id")
        if self.initial_height < 1:
            raise ValueError("initial_height must be >= 1")

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet(self.validators)

    def make_genesis_state(self) -> State:
        vs = self.validator_set()
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=0,
            last_block_time_ns=self.genesis_time_ns,
            validators=vs,
            next_validators=vs.copy(),
            last_validators=None,
            last_height_validators_changed=self.initial_height,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=self.initial_height,
            app_hash=self.app_hash,
        )

    # --- JSON round trip (genesis.json) -------------------------------

    def to_json(self) -> str:
        return json.dumps(
            {
                "chain_id": self.chain_id,
                "genesis_time_ns": self.genesis_time_ns,
                "initial_height": self.initial_height,
                "validators": [
                    {
                        "pub_key_type": v.pub_key.type_,
                        "pub_key": v.pub_key.key_bytes.hex(),
                        "power": v.voting_power,
                    }
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex(),
                "app_state": self.app_state_bytes.decode()
                if self.app_state_bytes
                else "",
                "consensus_params": self.consensus_params.to_dict(),
            },
            indent=2,
        )

    @classmethod
    def from_json(cls, raw: str) -> "GenesisDoc":
        d = json.loads(raw)
        vals = [
            Validator(
                pubkey_from_type_bytes(
                    v["pub_key_type"], bytes.fromhex(v["pub_key"])
                ),
                v["power"],
            )
            for v in d.get("validators", [])
        ]
        return cls(
            chain_id=d["chain_id"],
            genesis_time_ns=d.get("genesis_time_ns", 0),
            initial_height=d.get("initial_height", 1),
            validators=vals,
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state_bytes=d.get("app_state", "").encode(),
            consensus_params=ConsensusParams.from_dict(
                d.get("consensus_params", {})
            ),
        )

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
