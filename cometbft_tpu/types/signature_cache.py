"""LRU cache of already-verified signatures (fork feature).

Parity with reference types/signature_cache.go: key = (sign bytes,
signature, pubkey), used by light-client / statesync verification to
dedup across overlapping valsets and bisection hops
(types/validation.go:82-91, light/verifier.go:57).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

DEFAULT_CACHE_SIZE = 10_000


class SignatureCache:
    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self._od: OrderedDict[tuple, None] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(sign_bytes: bytes, sig: bytes, pubkey: bytes) -> tuple:
        """Plain tuple key: collision-free by construction (no digest
        needed — the reference hashes only to bound Go map key size),
        and cheap on the miss-then-add path because Python caches each
        bytes object's hash, so the second keying of the SAME objects
        costs almost nothing (profile_replay r5: sha256 keying was
        ~3% of replay host wall with a 0% hit rate on linear sync)."""
        return (sign_bytes, sig, pubkey)

    def contains(self, sign_bytes: bytes, sig: bytes, pubkey: bytes) -> bool:
        k = self.key(sign_bytes, sig, pubkey)
        with self._lock:
            if k in self._od:
                self._od.move_to_end(k)
                self.hits += 1
                return True
            self.misses += 1
            return False

    def add(self, sign_bytes: bytes, sig: bytes, pubkey: bytes) -> None:
        k = self.key(sign_bytes, sig, pubkey)
        with self._lock:
            self._od[k] = None
            self._od.move_to_end(k)
            while len(self._od) > self.size:
                self._od.popitem(last=False)

    def __len__(self) -> int:
        return len(self._od)
