"""LRU cache of already-verified signatures (fork feature).

Parity with reference types/signature_cache.go: key = (sign bytes,
signature, pubkey), used by light-client / statesync verification to
dedup across overlapping valsets and bisection hops
(types/validation.go:82-91, light/verifier.go:57).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

DEFAULT_CACHE_SIZE = 10_000


class SignatureCache:
    def __init__(self, size: int = DEFAULT_CACHE_SIZE):
        self.size = size
        self._od: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(sign_bytes: bytes, sig: bytes, pubkey: bytes) -> bytes:
        return hashlib.sha256(
            len(sign_bytes).to_bytes(4, "big") + sign_bytes + sig + pubkey
        ).digest()

    def contains(self, sign_bytes: bytes, sig: bytes, pubkey: bytes) -> bool:
        k = self.key(sign_bytes, sig, pubkey)
        with self._lock:
            if k in self._od:
                self._od.move_to_end(k)
                self.hits += 1
                return True
            self.misses += 1
            return False

    def add(self, sign_bytes: bytes, sig: bytes, pubkey: bytes) -> None:
        k = self.key(sign_bytes, sig, pubkey)
        with self._lock:
            self._od[k] = None
            self._od.move_to_end(k)
            while len(self._od) > self.size:
                self._od.popitem(last=False)

    def __len__(self) -> int:
        return len(self._od)
