"""Core consensus data model (the lingua franca of every layer).

Reference parity: types/ package of CometBFT — Block/Header/Commit,
Vote/Proposal, ValidatorSet, VoteSet, PartSet, commit verification with
TPU batch dispatch, signature cache.
"""

from .block import (  # noqa: F401
    BLOCK_ID_FLAG_ABSENT,
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    Block,
    BlockID,
    Commit,
    CommitSig,
    ExtendedCommit,
    ExtendedCommitSig,
    Data,
    Header,
    NIL_BLOCK_ID,
    PartSetHeader,
)
from .canonical import (  # noqa: F401
    PRECOMMIT_TYPE,
    PREVOTE_TYPE,
    PROPOSAL_TYPE,
    proposal_sign_bytes,
    vote_sign_bytes,
)
from .part_set import BLOCK_PART_SIZE, Part, PartSet  # noqa: F401
from .signature_cache import SignatureCache  # noqa: F401
from .validation import (  # noqa: F401
    PRIORITY_CATCHUP,
    PRIORITY_LIGHT,
    PRIORITY_LIVE,
    CommitVerifyError,
    ErrInvalidSignature,
    ErrNotEnoughVotingPower,
    verify_commit,
    verify_commit_jobs_coalesced,
    verify_commit_light,
    verify_commit_light_trusting,
    verify_extended_commit,
)
from .validator_set import (  # noqa: F401
    Validator,
    ValidatorSet,
    random_validator_set,
)
from .vote import PRECOMMIT, PREVOTE, Proposal, Vote  # noqa: F401
from .vote_set import ErrVoteConflictingVotes, VoteSet  # noqa: F401
