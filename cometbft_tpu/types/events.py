"""EventBus: typed pub/sub for consensus/tx events (reference types/event_bus.go).

Subscriptions are predicate-filtered asyncio queues; synchronous
fan-out mirrors the reference's evsw semantics for reactor-internal
listeners (gossip wakeups must not miss events).
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_TX = "Tx"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_NEW_ROUND = "NewRound"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_VOTE = "Vote"
EVENT_POLKA = "Polka"
EVENT_LOCK = "Lock"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"


@dataclass
class Event:
    type_: str
    data: Any
    attrs: Dict[str, str] = field(default_factory=dict)


class Subscription:
    def __init__(self, bus: "EventBus", match: Callable[[Event], bool]):
        self._bus = bus
        self._match = match
        self.queue: "asyncio.Queue[Event]" = asyncio.Queue()

    def unsubscribe(self):
        self._bus._remove(self)


class EventBus:
    """Thread-safe publish; async + sync consumption."""

    def __init__(self):
        self._subs: List[Subscription] = []
        self._sync_listeners: List[Callable[[Event], None]] = []
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    def set_loop(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop

    def subscribe(
        self, match: Optional[Callable[[Event], bool]] = None
    ) -> Subscription:
        sub = Subscription(self, match or (lambda e: True))
        with self._lock:
            self._subs.append(sub)
        return sub

    def subscribe_type(self, type_: str) -> Subscription:
        return self.subscribe(lambda e, t=type_: e.type_ == t)

    def add_sync_listener(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            self._sync_listeners.append(fn)

    def _remove(self, sub: Subscription):
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def publish(self, event: Event) -> None:
        with self._lock:
            subs = list(self._subs)
            listeners = list(self._sync_listeners)
        for fn in listeners:
            fn(event)
        for sub in subs:
            if sub._match(event):
                if self._loop is not None and not self._loop.is_closed():
                    self._loop.call_soon_threadsafe(
                        sub.queue.put_nowait, event
                    )
                else:
                    sub.queue.put_nowait(event)

    # convenience publishers (reference event_bus.go PublishEventX)
    def publish_type(self, type_: str, data: Any, **attrs) -> None:
        self.publish(Event(type_, data, {k: str(v) for k, v in attrs.items()}))
