"""EventBus: typed pub/sub for consensus/tx events (reference types/event_bus.go).

Subscriptions are predicate-filtered asyncio queues; synchronous
fan-out mirrors the reference's evsw semantics for reactor-internal
listeners (gossip wakeups must not miss events).
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..analysis.runtime import sanitized_lock
from ..obs.queues import InstrumentedQueue

# per-subscriber queue bound: a subscriber that stops draining sheds
# (events dropped + counted) instead of growing the queue without
# bound until the process dies — the outbound analog of the mempool
# ingest queue's overload policy (ROADMAP item 4; bftlint ASY109)
SUBSCRIPTION_QUEUE_SIZE = 2048

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_TX = "Tx"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_NEW_ROUND = "NewRound"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_VOTE = "Vote"
EVENT_POLKA = "Polka"
EVENT_LOCK = "Lock"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_EVIDENCE = "NewEvidence"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_TIMEOUT_WAIT = "TimeoutWait"


@dataclass
class Event:
    type_: str
    data: Any
    attrs: Dict[str, str] = field(default_factory=dict)


class Subscription:
    def __init__(
        self,
        bus: "EventBus",
        match: Callable[[Event], bool],
        queue_size: int = SUBSCRIPTION_QUEUE_SIZE,
    ):
        self._bus = bus
        self._match = match
        self.queue: InstrumentedQueue = InstrumentedQueue(
            queue_size, name="events.sub"
        )

    def _offer(self, event: "Event") -> None:
        """Non-blocking delivery with shed-and-count overflow: when a
        subscriber stops draining, NEW events are dropped (counted on
        its queue + the bus) and the backlog it already holds stays
        intact — publishers and other subscribers never block behind
        it, and a resumed drainer sees a gap-free prefix followed by
        a counted gap."""
        try:
            self.queue.put_nowait(event)
        except asyncio.QueueFull:
            self.queue.count_drop()
            self._bus.dropped += 1

    def unsubscribe(self):
        self._bus._remove(self)


class EventBus:
    """Thread-safe publish; async + sync consumption."""

    def __init__(self):
        self._subs: List[Subscription] = []
        self._sync_listeners: List[Callable[[Event], None]] = []
        self._lock = sanitized_lock(threading.Lock(), "events.bus")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.dropped = 0  # events shed across all subscribers

    def set_loop(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop

    def subscribe(
        self, match: Optional[Callable[[Event], bool]] = None
    ) -> Subscription:
        sub = Subscription(self, match or (lambda e: True))
        with self._lock:
            self._subs.append(sub)
        return sub

    def subscribe_type(self, type_: str) -> Subscription:
        return self.subscribe(lambda e, t=type_: e.type_ == t)

    def add_sync_listener(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            self._sync_listeners.append(fn)

    def remove_sync_listener(self, fn: Callable[[Event], None]) -> None:
        with self._lock:
            if fn in self._sync_listeners:
                self._sync_listeners.remove(fn)

    def _remove(self, sub: Subscription):
        with self._lock:
            if sub in self._subs:
                self._subs.remove(sub)

    def publish(self, event: Event) -> None:
        with self._lock:
            subs = list(self._subs)
            listeners = list(self._sync_listeners)
        for fn in listeners:
            fn(event)
        for sub in subs:
            if sub._match(event):
                if self._loop is not None and not self._loop.is_closed():
                    self._loop.call_soon_threadsafe(sub._offer, event)
                else:
                    sub._offer(event)

    def queue_stats(self) -> dict:
        """Aggregate subscriber-queue backpressure (obs registry):
        depth summed, watermark = worst subscriber, drops bus-wide."""
        with self._lock:
            subs = list(self._subs)
        depth = hwm = enqueued = 0
        for sub in subs:
            q = sub.queue
            depth += q.qsize()
            hwm = max(hwm, q.high_watermark)
            enqueued += q.enqueued
        # no "maxsize": this entry AGGREGATES over subscribers, and
        # the health route's full-queue check compares depth against
        # maxsize — a per-subscriber bound must not be compared with
        # a summed depth (obs/queues.py convention: aggregates and
        # soft targets use a differently-named field)
        return {
            "depth": depth,
            "high_watermark": hwm,
            "enqueued": enqueued,
            "dropped": self.dropped,
            "subscribers": len(subs),
            "subscriber_maxsize": SUBSCRIPTION_QUEUE_SIZE,
        }

    # convenience publishers (reference event_bus.go PublishEventX)
    def publish_type(self, type_: str, data: Any, **attrs) -> None:
        self.publish(Event(type_, data, {k: str(v) for k, v in attrs.items()}))
