"""Vote type + verification (reference types/vote.go).

``Vote.verify`` is the consensus-round hot path (one signature per
gossiped vote; reference types/vote.go:228-237). Bulk verification of
whole commits goes through types/validation.py onto the TPU lanes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto.keys import PubKey
from . import canonical
from .block import BlockID

PREVOTE = canonical.PREVOTE_TYPE
PRECOMMIT = canonical.PRECOMMIT_TYPE


def is_vote_type_valid(t: int) -> bool:
    return t in (PREVOTE, PRECOMMIT)


@dataclass
class Vote:
    type_: int
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_sign_bytes(
            chain_id,
            self.type_,
            self.height,
            self.round,
            self.block_id,
            self.timestamp_ns,
        )

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_extension_sign_bytes(
            chain_id, self.height, self.round, self.extension
        )

    def verify(self, chain_id: str, pub_key: PubKey) -> bool:
        """Single-signature verify (consensus hot path)."""
        if pub_key.address() != self.validator_address:
            return False
        return pub_key.verify(self.sign_bytes(chain_id), self.signature)

    def verify_with_extension(self, chain_id: str, pub_key: PubKey) -> bool:
        if not self.verify(chain_id, pub_key):
            return False
        if self.type_ == PRECOMMIT and not self.block_id.is_nil():
            if self.extension or self.extension_signature:
                return pub_key.verify(
                    self.extension_sign_bytes(chain_id),
                    self.extension_signature,
                )
        return True

    def is_nil(self) -> bool:
        return self.block_id.is_nil()

    def validate_basic(self) -> None:
        if not is_vote_type_valid(self.type_):
            raise ValueError("invalid vote type")
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if len(self.validator_address) != 20:
            raise ValueError("invalid validator address")
        if self.validator_index < 0:
            raise ValueError("negative validator index")
        if not self.signature or len(self.signature) > 96:
            raise ValueError("invalid signature size")

    def key(self):
        return (self.type_, self.height, self.round, self.block_id.key())


@dataclass
class Proposal:
    height: int
    round: int
    pol_round: int
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id,
            self.height,
            self.round,
            self.pol_round,
            self.block_id,
            self.timestamp_ns,
        )

    def verify(self, chain_id: str, pub_key: PubKey) -> bool:
        return pub_key.verify(self.sign_bytes(chain_id), self.signature)

    def validate_basic(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if self.pol_round < -1 or self.pol_round >= self.round:
            raise ValueError("invalid POLRound")
        if not self.block_id.is_complete():
            raise ValueError("proposal BlockID must be complete")
        if not self.signature or len(self.signature) > 96:
            raise ValueError("invalid signature size")
