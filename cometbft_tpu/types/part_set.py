"""PartSet: merkle-chunked block propagation unit (types/part_set.go).

Blocks gossip as fixed-size parts (64KB, reference BlockPartSizeBytes)
each carrying a merkle inclusion proof against the PartSetHeader hash,
so peers can verify chunks independently before the block is whole.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..crypto import merkle
from .block import PartSetHeader

BLOCK_PART_SIZE = 65536


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if self.index < 0:
            raise ValueError("negative part index")
        if len(self.bytes_) > BLOCK_PART_SIZE:
            raise ValueError("part too big")
        if self.proof.index != self.index:
            raise ValueError("part proof index mismatch")


class PartSet:
    """Either built full from data (proposer) or assembled from a header
    (receiver adding verified parts)."""

    def __init__(self, header: PartSetHeader):
        self.header = header
        self.parts: List[Optional[Part]] = [None] * header.total
        self.count = 0
        self.byte_size = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE):
        chunks = [
            data[i : i + part_size] for i in range(0, len(data), part_size)
        ] or [b""]
        # proposal-path leaf hashing rides the native finalize lane
        # when built (sha256(0x00 || 64KB chunk) per part with the GIL
        # released); proofs/root come out identical either way
        from ..state import native_finalize

        lh = native_finalize.part_leaf_hashes(chunks)
        if lh is not None:
            root, proofs = merkle.proofs_from_leaf_hashes(lh)
        else:
            root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, (c, pr) in enumerate(zip(chunks, proofs)):
            ps.parts[i] = Part(index=i, bytes_=c, proof=pr)
        ps.count = len(chunks)
        ps.byte_size = len(data)
        return ps

    def add_part(self, part: Part) -> bool:
        """Verify proof against the header and insert. Returns False for
        duplicates; raises on invalid proof."""
        part.validate_basic()
        if part.index >= self.header.total:
            raise ValueError("part index out of range")
        if self.parts[part.index] is not None:
            return False
        if not part.proof.verify(self.header.hash, part.bytes_):
            raise ValueError("invalid part proof")
        self.parts[part.index] = part
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def is_complete(self) -> bool:
        return self.count == self.header.total

    def get_part(self, i: int) -> Optional[Part]:
        return self.parts[i] if 0 <= i < len(self.parts) else None

    def bit_array(self) -> List[bool]:
        return [p is not None for p in self.parts]

    def assemble(self) -> bytes:
        assert self.is_complete()
        return b"".join(p.bytes_ for p in self.parts)
