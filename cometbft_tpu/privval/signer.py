"""Remote signer protocol (reference privval/signer_client.go:18,
privval/signer_listener_endpoint.go, privval/signer_server.go,
privval/msgs.go).

Topology matches the reference: the VALIDATOR NODE listens on
priv_validator_laddr; the SIGNER (HSM-holder) dials in and serves
signing requests over an authenticated-encrypted stream (the same
SecretConnection as p2p). The node-side SignerClient implements the
PrivValidator interface; each call does one request/response round
trip with a deadline. Double-sign protection lives with the KEY (the
signer's FilePV), exactly like the reference.

The endpoint runs its own background event loop thread so the
synchronous PrivValidator interface (called from inside the consensus
routine) can block on the socket with a timeout without re-entering
the node's loop."""

from __future__ import annotations

import asyncio
import struct
import threading
import traceback
from typing import Optional

from ..analysis.runtime import sanitized_lock
from ..crypto.keys import Ed25519PrivKey, Ed25519PubKey
from ..p2p.conn.secret_connection import SecretConnection
from ..types.vote import Proposal, Vote
from ..utils import codec

MSG_PUBKEY_REQUEST = 0x01
MSG_PUBKEY_RESPONSE = 0x02
MSG_SIGN_VOTE_REQUEST = 0x03
MSG_SIGNED_VOTE_RESPONSE = 0x04
MSG_SIGN_PROPOSAL_REQUEST = 0x05
MSG_SIGNED_PROPOSAL_RESPONSE = 0x06
MSG_PING_REQUEST = 0x07
MSG_PING_RESPONSE = 0x08
MSG_SIGN_VOTE_EXT_REQUEST = 0x09
MSG_SIGNED_VOTE_EXT_RESPONSE = 0x0A
MSG_ERROR_RESPONSE = 0x7F


class RemoteSignerError(Exception):
    """Definitive signer-side refusal (e.g. the double-sign guard) or
    exhausted retries — never retried."""


class SignerUnavailableError(ConnectionError):
    """No signer currently connected — transient: the signer redials
    (serve_forever) and RetrySignerClient retries through it."""


async def _send(sconn: SecretConnection, mtype: int, payload: bytes = b""):
    await sconn.write_msg(
        struct.pack(">BI", mtype, len(payload)) + payload
    )


async def _recv(sconn: SecretConnection):
    buf = await sconn.read_chunk()
    mtype, ln = struct.unpack(">BI", buf[:5])
    body = buf[5:]
    while len(body) < ln:
        body += await sconn.read_chunk()
    return mtype, body[:ln]


def _strip_scheme(addr: str) -> str:
    for pfx in ("tcp://", "unix://"):
        if addr.startswith(pfx):
            return addr[len(pfx):]
    return addr


class SignerClient:
    """Node-side PrivValidator backed by a remote signer (reference
    privval/signer_client.go). Listens for the signer to dial in."""

    # consensus offloads our (socket-blocking) sign calls to a worker
    # thread instead of blocking its event loop
    REMOTE_BLOCKING = True

    def __init__(self, laddr: str, node_priv: Optional[Ed25519PrivKey] = None,
                 timeout_s: float = 5.0):
        # node_priv authenticates the NODE end of the secret conn
        # (a throwaway key is fine; the signer's identity is what
        # matters operationally)
        self._auth_priv = node_priv or Ed25519PrivKey.generate()
        self.timeout_s = timeout_s
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )
        self._thread.start()
        self._sconn: Optional[SecretConnection] = None
        self._connected = threading.Event()
        self._lock = sanitized_lock(threading.Lock(), "privval.sign")
        self.listen_addr = ""
        fut = asyncio.run_coroutine_threadsafe(
            self._listen(laddr), self._loop
        )
        fut.result(10.0)
        self._pubkey: Optional[Ed25519PubKey] = None

    async def _listen(self, laddr: str) -> None:
        host, _, port = _strip_scheme(laddr).rpartition(":")

        async def on_accept(reader, writer):
            try:
                sconn = await SecretConnection.handshake(
                    reader, writer, self._auth_priv
                )
            except asyncio.CancelledError:
                writer.close()
                raise
            except (OSError, ValueError, asyncio.IncompleteReadError):
                # failed auth / torn conn: drop it, keep listening
                writer.close()
                return
            self._sconn = sconn
            self._connected.set()

        self._server = await asyncio.start_server(
            on_accept, host or "127.0.0.1", int(port)
        )
        h, p = self._server.sockets[0].getsockname()[:2]
        self.listen_addr = f"{h}:{p}"

    def wait_for_signer(self, timeout_s: float = 30.0) -> None:
        if not self._connected.wait(timeout_s):
            raise SignerUnavailableError("no remote signer connected")

    # --- request/response ------------------------------------------------

    def _call(self, mtype: int, payload: bytes = b""):
        import concurrent.futures

        self.wait_for_signer(self.timeout_s)
        with self._lock:
            fut = asyncio.run_coroutine_threadsafe(
                self._roundtrip(mtype, payload), self._loop
            )
            try:
                return fut.result(self.timeout_s)
            except RemoteSignerError:
                raise  # clean protocol response; stream still in sync
            except concurrent.futures.TimeoutError:
                # the orphaned round trip may still complete later and
                # leave a stale response in the stream: every request
                # after that would read the WRONG response. Drop the
                # connection so the signer redials and both ends
                # resync (the reference drops on timeout too).
                self._drop_conn()
                raise
            except Exception:
                # transport-level failure: the stream state is unknown
                self._drop_conn()
                raise

    def _drop_conn(self) -> None:
        sconn, self._sconn = self._sconn, None
        self._connected.clear()
        if sconn is not None:
            self._loop.call_soon_threadsafe(sconn.close)

    async def _roundtrip(self, mtype: int, payload: bytes):
        sconn = self._sconn
        if sconn is None:
            raise ConnectionError("remote signer disconnected")
        await _send(sconn, mtype, payload)
        rtype, body = await _recv(sconn)
        if rtype == MSG_ERROR_RESPONSE:
            raise RemoteSignerError(body.decode() or "remote signer error")
        return rtype, body

    # --- PrivValidator interface ----------------------------------------

    def pub_key(self) -> Ed25519PubKey:
        if self._pubkey is None:
            rtype, body = self._call(MSG_PUBKEY_REQUEST)
            if rtype != MSG_PUBKEY_RESPONSE or len(body) != 32:
                raise RemoteSignerError("bad pubkey response")
            self._pubkey = Ed25519PubKey(body)
        return self._pubkey

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        payload = (
            struct.pack(">H", len(chain_id))
            + chain_id.encode()
            + codec.encode_vote(vote)
        )
        rtype, body = self._call(MSG_SIGN_VOTE_REQUEST, payload)
        if rtype != MSG_SIGNED_VOTE_RESPONSE:
            raise RemoteSignerError("bad sign-vote response")
        signed = codec.decode_vote(body)
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns
        # the server extension-signs in the same round trip whenever
        # the request vote carries an extension
        vote.extension_signature = signed.extension_signature

    def sign_vote_extension(self, chain_id: str, vote: Vote) -> None:
        """Dedicated round trip: the server extension-signs even an
        EMPTY extension (matching FilePV — peers at extension-enabled
        heights require the signature regardless of payload). The
        fast path: a non-empty extension was already co-signed during
        SIGN_VOTE."""
        if vote.extension_signature:
            return
        payload = (
            struct.pack(">H", len(chain_id))
            + chain_id.encode()
            + codec.encode_vote(vote)
        )
        rtype, body = self._call(MSG_SIGN_VOTE_EXT_REQUEST, payload)
        if rtype != MSG_SIGNED_VOTE_EXT_RESPONSE:
            raise RemoteSignerError("bad sign-vote-extension response")
        vote.extension_signature = body
        if not vote.extension_signature:
            raise RemoteSignerError(
                "signer did not produce an extension signature"
            )

    def sign_proposal(self, chain_id: str, prop: Proposal) -> None:
        payload = (
            struct.pack(">H", len(chain_id))
            + chain_id.encode()
            + codec.encode_proposal(prop)
        )
        rtype, body = self._call(MSG_SIGN_PROPOSAL_REQUEST, payload)
        if rtype != MSG_SIGNED_PROPOSAL_RESPONSE:
            raise RemoteSignerError("bad sign-proposal response")
        signed = codec.decode_proposal(body)
        prop.signature = signed.signature
        prop.timestamp_ns = signed.timestamp_ns

    def close(self) -> None:
        def _shut():
            if self._sconn:
                self._sconn.close()
            self._server.close()

        self._loop.call_soon_threadsafe(_shut)
        self._loop.call_soon_threadsafe(self._loop.stop)


class RetrySignerClient:
    """Retrying PrivValidator wrapper around SignerClient (reference
    privval/retry_signer_client.go): a transient signer hiccup — a
    dropped connection, a slow redial, a request timeout — must cost a
    bounded delay, not a missed vote or proposal.

    retries=0 retries forever (the reference's semantics for 0).
    DEFINITIVE signer refusals (the signer answered with an error
    payload, e.g. the double-sign guard) are NOT retried: re-asking an
    HSM to double-sign is never correct and only delays the round —
    the one deliberate deviation from the reference, which retries
    every error class."""

    REMOTE_BLOCKING = True

    def __init__(
        self,
        client: SignerClient,
        retries: int = 5,
        interval_s: float = 0.2,
    ):
        self.client = client
        self.retries = retries
        self.interval_s = interval_s

    def _retry(self, what: str, fn, *args):
        import concurrent.futures
        import time as _t

        n = 0
        last: Optional[Exception] = None
        while self.retries == 0 or n < self.retries:
            try:
                return fn(*args)
            except RemoteSignerError:
                raise  # definitive refusal (e.g. double-sign guard)
            except (
                concurrent.futures.TimeoutError,
                TimeoutError,
                ConnectionError,
                OSError,
                asyncio.IncompleteReadError,
            ) as e:
                last = e
            n += 1
            _t.sleep(self.interval_s)
        raise RemoteSignerError(
            f"{what}: exhausted {self.retries} retries "
            f"(last: {last!r})"
        )

    # --- PrivValidator interface (all retried) -------------------------

    def pub_key(self) -> Ed25519PubKey:
        return self._retry("pub_key", self.client.pub_key)

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        self._retry("sign_vote", self.client.sign_vote, chain_id, vote)

    def sign_vote_extension(self, chain_id: str, vote: Vote) -> None:
        self._retry(
            "sign_vote_extension",
            self.client.sign_vote_extension,
            chain_id,
            vote,
        )

    def sign_proposal(self, chain_id: str, prop: Proposal) -> None:
        self._retry(
            "sign_proposal", self.client.sign_proposal, chain_id, prop
        )

    def wait_for_signer(self, timeout_s: float = 30.0) -> None:
        self.client.wait_for_signer(timeout_s)

    @property
    def listen_addr(self) -> str:
        return self.client.listen_addr

    def close(self) -> None:
        self.client.close()


class SignerServer:
    """Signer-side daemon: dials the validator node and serves signing
    requests from a FilePV (reference privval/signer_server.go +
    signer_dialer_endpoint.go). Run via `await serve()`."""

    def __init__(self, file_pv, addr: str,
                 auth_priv: Optional[Ed25519PrivKey] = None):
        self.pv = file_pv
        self.addr = addr
        self._auth_priv = auth_priv or self.pv.priv_key
        self._stopped = False

    async def serve_forever(self, redial_interval_s: float = 0.2) -> None:
        """serve() with redial: when the connection to the node drops
        (or the node is not up yet), dial again after a short pause —
        the reference's SignerDialerEndpoint retry behavior
        (privval/signer_dialer_endpoint.go). Pairs with the node-side
        RetrySignerClient so a transient drop heals from both ends."""
        while not self._stopped:
            try:
                await self.serve()
            except (
                ConnectionError,
                OSError,
                # IncompleteReadError (an EOFError, NOT an OSError):
                # node closed the socket mid-handshake, e.g. a restart
                EOFError,
                asyncio.TimeoutError,
            ):
                pass
            if not self._stopped:
                await asyncio.sleep(redial_interval_s)

    async def serve(self) -> None:
        host, _, port = _strip_scheme(self.addr).rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        sconn = await SecretConnection.handshake(
            reader, writer, self._auth_priv
        )
        while not self._stopped:
            try:
                mtype, body = await _recv(sconn)
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            try:
                await self._handle(sconn, mtype, body)
            except asyncio.CancelledError:
                raise  # server stop cancels the serve loop
            except Exception as e:
                traceback.print_exc()
                await _send(
                    sconn, MSG_ERROR_RESPONSE, str(e).encode()
                )

    async def _handle(self, sconn, mtype: int, body: bytes) -> None:
        if mtype == MSG_PUBKEY_REQUEST:
            await _send(
                sconn,
                MSG_PUBKEY_RESPONSE,
                bytes(self.pv.pub_key().key_bytes),
            )
        elif mtype == MSG_PING_REQUEST:
            await _send(sconn, MSG_PING_RESPONSE)
        elif mtype == MSG_SIGN_VOTE_EXT_REQUEST:
            (ln,) = struct.unpack(">H", body[:2])
            chain_id = body[2 : 2 + ln].decode()
            vote = codec.decode_vote(body[2 + ln:])
            self.pv.sign_vote_extension(chain_id, vote)
            await _send(
                sconn,
                MSG_SIGNED_VOTE_EXT_RESPONSE,
                vote.extension_signature,
            )
        elif mtype in (MSG_SIGN_VOTE_REQUEST, MSG_SIGN_PROPOSAL_REQUEST):
            (ln,) = struct.unpack(">H", body[:2])
            chain_id = body[2 : 2 + ln].decode()
            rest = body[2 + ln:]
            if mtype == MSG_SIGN_VOTE_REQUEST:
                vote = codec.decode_vote(rest)
                self.pv.sign_vote(chain_id, vote)  # double-sign guard HERE
                if vote.extension:
                    # ABCI vote extensions: sign in the same round trip
                    self.pv.sign_vote_extension(chain_id, vote)
                await _send(
                    sconn,
                    MSG_SIGNED_VOTE_RESPONSE,
                    codec.encode_vote(vote),
                )
            else:
                prop = codec.decode_proposal(rest)
                self.pv.sign_proposal(chain_id, prop)
                await _send(
                    sconn,
                    MSG_SIGNED_PROPOSAL_RESPONSE,
                    codec.encode_proposal(prop),
                )
        else:
            raise RemoteSignerError(f"unknown request type {mtype}")

    def stop(self) -> None:
        self._stopped = True
