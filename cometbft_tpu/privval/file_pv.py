"""File-backed private validator with double-sign protection.

Parity with reference privval/file.go: key file (persistent identity)
plus a state file persisted BEFORE every signature recording
(height/round/step + signature + sign bytes), the CheckHRS regression
rule (privval/file.go:100), and same-HRS re-signing only for identical
or timestamp-only-differing sign bytes (privval/file.go:307-410).
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass
from typing import Optional, Tuple

from ..crypto.keys import Ed25519PrivKey, Ed25519PubKey
from ..types import canonical
from ..types.vote import PRECOMMIT, PREVOTE, Proposal, Vote
from ..utils import proto

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {PREVOTE: STEP_PREVOTE, PRECOMMIT: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


@dataclass
class _LastSign:
    height: int = 0
    round: int = 0
    step: int = 0
    signature: str = ""
    sign_bytes: str = ""


class FilePV:
    def __init__(self, priv_key: Ed25519PrivKey, key_path: str, state_path: str):
        self.priv_key = priv_key
        self.key_path = key_path
        self.state_path = state_path
        self.last = _LastSign()

    # --- construction -------------------------------------------------

    @classmethod
    def generate(cls, key_path: str, state_path: str) -> "FilePV":
        pv = cls(Ed25519PrivKey.generate(), key_path, state_path)
        pv.save_key()
        pv.save_state()
        return pv

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        with open(key_path) as f:
            kd = json.load(f)
        pv = cls(
            Ed25519PrivKey.from_seed(bytes.fromhex(kd["priv_key"])),
            key_path,
            state_path,
        )
        if os.path.exists(state_path):
            with open(state_path) as f:
                sd = json.load(f)
            pv.last = _LastSign(**sd)
        return pv

    @classmethod
    def load_or_generate(cls, key_path: str, state_path: str) -> "FilePV":
        if os.path.exists(key_path):
            return cls.load(key_path, state_path)
        return cls.generate(key_path, state_path)

    def save_key(self) -> None:
        pub = self.priv_key.pub_key()
        _atomic_write(
            self.key_path,
            json.dumps(
                {
                    "address": pub.address().hex(),
                    "pub_key": pub.key_bytes.hex(),
                    "priv_key": self.priv_key.seed.hex(),
                }
            ).encode(),
        )

    def save_state(self) -> None:
        _atomic_write(
            self.state_path, json.dumps(self.last.__dict__).encode()
        )

    # --- PrivValidator interface --------------------------------------

    def pub_key(self) -> Ed25519PubKey:
        return self.priv_key.pub_key()

    def _check_hrs(
        self, height: int, round_: int, step: int
    ) -> bool:
        """Returns True if HRS was seen before (same-HRS re-sign path);
        raises on regression (reference privval/file.go:100-131)."""
        last = self.last
        if last.height > height:
            raise DoubleSignError("height regression")
        if last.height == height:
            if last.round > round_:
                raise DoubleSignError("round regression")
            if last.round == round_:
                if last.step > step:
                    raise DoubleSignError("step regression")
                if last.step == step:
                    if not last.sign_bytes:
                        raise DoubleSignError("no sign bytes for same HRS")
                    return True
        return False

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        step = _VOTE_STEP[vote.type_]
        sign_bytes = vote.sign_bytes(chain_id)
        same = self._check_hrs(vote.height, vote.round, step)
        if same:
            prev = bytes.fromhex(self.last.sign_bytes)
            if prev == sign_bytes:
                vote.signature = bytes.fromhex(self.last.signature)
                return
            if _votes_differ_only_by_timestamp(prev, sign_bytes):
                # re-sign with the ORIGINAL timestamp (reference behavior)
                vote.timestamp_ns = _vote_timestamp(prev)
                vote.signature = bytes.fromhex(self.last.signature)
                return
            raise DoubleSignError(
                f"conflicting vote at {vote.height}/{vote.round}/{step}"
            )
        sig = self.priv_key.sign(sign_bytes)
        self.last = _LastSign(
            height=vote.height,
            round=vote.round,
            step=step,
            signature=sig.hex(),
            sign_bytes=sign_bytes.hex(),
        )
        self.save_state()  # persist BEFORE returning the signature
        vote.signature = sig

    def sign_vote_extension(self, chain_id: str, vote: Vote) -> None:
        if vote.type_ == PRECOMMIT and not vote.block_id.is_nil():
            ext_sb = vote.extension_sign_bytes(chain_id)
            vote.extension_signature = self.priv_key.sign(ext_sb)

    def sign_proposal(self, chain_id: str, prop: Proposal) -> None:
        sign_bytes = prop.sign_bytes(chain_id)
        same = self._check_hrs(prop.height, prop.round, STEP_PROPOSE)
        if same:
            prev = bytes.fromhex(self.last.sign_bytes)
            if prev == sign_bytes:
                prop.signature = bytes.fromhex(self.last.signature)
                return
            if _proposals_differ_only_by_timestamp(prev, sign_bytes):
                prop.timestamp_ns = _proposal_timestamp(prev)
                prop.signature = bytes.fromhex(self.last.signature)
                return
            raise DoubleSignError(
                f"conflicting proposal at {prop.height}/{prop.round}"
            )
        sig = self.priv_key.sign(sign_bytes)
        self.last = _LastSign(
            height=prop.height,
            round=prop.round,
            step=STEP_PROPOSE,
            signature=sig.hex(),
            sign_bytes=sign_bytes.hex(),
        )
        self.save_state()
        prop.signature = sig


# --- timestamp-only comparison helpers ---------------------------------


def _strip_ts(delimited: bytes, ts_field: int) -> Tuple[bytes, int]:
    """Remove the timestamp field from canonical sign bytes; return
    (stripped, timestamp_ns)."""
    payload, _ = proto.read_delimited(delimited)
    m = proto.parse(payload)
    ts = proto.parse_timestamp(proto.get1(m, ts_field, b""))
    # re-encode without the ts field, preserving field order
    out = b""
    for f in sorted(m):
        if f == ts_field:
            continue
        for v in m[f]:
            if isinstance(v, bytes):
                out += proto.field_bytes(f, v)
            else:
                out += proto.field_sfixed64(f, v) if f in (2, 3, 4) else (
                    proto.field_varint(f, v)
                )
    return out, ts


def _votes_differ_only_by_timestamp(a: bytes, b: bytes) -> bool:
    sa, _ = _strip_ts(a, 5)
    sb, _ = _strip_ts(b, 5)
    return sa == sb


def _vote_timestamp(sign_bytes: bytes) -> int:
    _, ts = _strip_ts(sign_bytes, 5)
    return ts


def _proposals_differ_only_by_timestamp(a: bytes, b: bytes) -> bool:
    sa, _ = _strip_ts(a, 6)
    sb, _ = _strip_ts(b, 6)
    return sa == sb


def _proposal_timestamp(sign_bytes: bytes) -> int:
    _, ts = _strip_ts(sign_bytes, 6)
    return ts
