from .file_pv import FilePV, DoubleSignError  # noqa: F401
