"""ASCII armor for key material (reference crypto/armor/armor.go:11 —
EncodeArmor/DecodeArmor over the OpenPGP armor format, RFC 4880 §6):

    -----BEGIN <block type>-----
    Header-Key: value

    <base64 body, wrapped>
    =<base64 CRC-24>
    -----END <block type>-----

Used by key-export tooling (the reference's cosmos-sdk consumers armor
privkeys with block type "TENDERMINT PRIVATE KEY" and a kdf/salt
header, encrypting with xsalsa20symmetric — see privval/armor helpers).
"""

from __future__ import annotations

import base64
import binascii
from typing import Dict, Tuple

_LINE = 64
_CRC24_INIT = 0xB704CE
_CRC24_POLY = 0x1864CFB


def _crc24(data: bytes) -> int:
    """OpenPGP radix-64 checksum (RFC 4880 §6.1)."""
    crc = _CRC24_INIT
    for b in data:
        crc ^= b << 16
        for _ in range(8):
            crc <<= 1
            if crc & 0x1000000:
                crc ^= _CRC24_POLY
    return crc & 0xFFFFFF


def encode_armor(
    block_type: str, headers: Dict[str, str], data: bytes
) -> str:
    lines = [f"-----BEGIN {block_type}-----"]
    for k in sorted(headers):
        lines.append(f"{k}: {headers[k]}")
    lines.append("")
    b64 = base64.b64encode(data).decode()
    for i in range(0, len(b64), _LINE):
        lines.append(b64[i : i + _LINE])
    crc = base64.b64encode(_crc24(data).to_bytes(3, "big")).decode()
    lines.append(f"={crc}")
    lines.append(f"-----END {block_type}-----")
    return "\n".join(lines) + "\n"


def decode_armor(armor_str: str) -> Tuple[str, Dict[str, str], bytes]:
    """Returns (block_type, headers, data); raises ValueError on any
    malformed framing, base64, or checksum mismatch."""
    lines = [l.rstrip("\r") for l in armor_str.strip().split("\n")]
    if not lines or not lines[0].startswith("-----BEGIN "):
        raise ValueError("missing armor BEGIN line")
    if not lines[0].endswith("-----"):
        raise ValueError("malformed BEGIN line")
    block_type = lines[0][len("-----BEGIN ") : -len("-----")]
    if lines[-1] != f"-----END {block_type}-----":
        raise ValueError("missing/mismatched armor END line")
    headers: Dict[str, str] = {}
    i = 1
    while i < len(lines) - 1 and lines[i].strip():
        if ":" not in lines[i]:
            break  # body starts without the customary blank line
        k, v = lines[i].split(":", 1)
        headers[k.strip()] = v.strip()
        i += 1
    if i < len(lines) - 1 and not lines[i].strip():
        i += 1  # blank separator
    body_lines = []
    crc_line = None
    for l in lines[i:-1]:
        if l.startswith("="):
            crc_line = l[1:]
        elif l.strip():
            body_lines.append(l.strip())
    try:
        data = base64.b64decode("".join(body_lines), validate=True)
    except (binascii.Error, ValueError) as e:
        raise ValueError(f"bad armor body: {e}") from None
    if crc_line is not None:
        try:
            want = int.from_bytes(
                base64.b64decode(crc_line, validate=True), "big"
            )
        except (binascii.Error, ValueError) as e:
            raise ValueError(f"bad armor checksum: {e}") from None
        if want != _crc24(data):
            raise ValueError("armor checksum mismatch")
    return block_type, headers, data
