"""Loader for the native chunk verifier (native/batchverify.cpp).

Follows the logdb/wirecodec pattern: built on demand with g++ into
~/.cache/cometbft_tpu (override with BATCHVERIFY_SO_DIR), loaded as a
CPython extension. ``verify_chunk(items)`` returns per-lane verdicts
or None when the extension is unavailable — callers (the parallel
verify engine's worker body) keep the pure pk.verify() loop as both
the fallback and the semantic source of truth.

Why it exists (docs/PERF.md "Host verification plane"): the per-lane
Python path pays ~6 short ctypes transitions per signature with the
GIL reacquired between them, so pool threads convoy on the GIL and
stop scaling; the extension verifies a whole chunk per call with the
GIL released for the entire C loop.

Verdict semantics are EXACTLY crypto/keys.Ed25519PubKey.verify:
OpenSSL (RFC 8032, the strict subset of ZIP-215) accepts → True;
OpenSSL rejects → re-run the liberal pure-python ZIP-215 check on
that lane. Non-ed25519 lanes and malformed inputs take the per-lane
Python path unchanged. GRAFT_NATIVE_VERIFY=0 disables.
"""

from __future__ import annotations

import os
import struct
import subprocess
import sysconfig
import threading
from typing import List, Optional

from .keys import Ed25519PubKey

_SRC = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native",
    "batchverify.cpp",
)
_SO = os.path.join(
    os.environ.get(
        "BATCHVERIFY_SO_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "cometbft_tpu"),
    ),
    "_batchverify.so",
)

_mod = None
_tried = False
_lock = threading.Lock()


def module():
    """The extension module, or None (no compiler / no libcrypto /
    disabled)."""
    global _mod, _tried
    if _tried:
        return _mod
    with _lock:
        if _tried:  # pragma: no cover - race
            return _mod
        _tried = True
        if os.environ.get("GRAFT_NATIVE_VERIFY") == "0":
            return None
        try:
            if (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    [
                        "g++",
                        "-O2",
                        "-std=c++17",
                        "-shared",
                        "-fPIC",
                        "-I",
                        sysconfig.get_paths()["include"],
                        _SRC,
                        "-ldl",
                        "-o",
                        _SO,
                    ],
                    check=True,
                    capture_output=True,
                )
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_batchverify", _SO
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            if mod.available():
                _mod = mod
        except Exception:
            _mod = None
        return _mod


def verify_chunk(items) -> Optional[List[bool]]:
    """Verdicts for [(pk, msg, sig)] via ONE GIL-releasing native
    call, or None when the extension is unavailable (caller falls
    back to the per-lane Python loop).

    Only well-formed ed25519 lanes enter the native call; every other
    lane — and every native-rejected lane — runs the exact per-lane
    ``pk.verify`` path, so verdicts are bit-identical to the serial
    backend on every input (incl. the liberal ZIP-215 edge cases
    OpenSSL rejects)."""
    mod = module()
    if mod is None:
        return None
    n = len(items)
    ed_idx: List[int] = []
    pubs = bytearray()
    sigs = bytearray()
    msgs = bytearray()
    lens: List[int] = []
    for i, (pk, msg, sig) in enumerate(items):
        if (
            isinstance(pk, Ed25519PubKey)
            and len(pk.key_bytes) == 32
            and len(sig) == 64
        ):
            ed_idx.append(i)
            pubs += pk.key_bytes
            sigs += sig
            msgs += msg
            lens.append(len(msg))
    oks = [False] * n
    if ed_idx:
        verdicts = mod.verify_ed25519(
            bytes(pubs),
            bytes(sigs),
            bytes(msgs),
            struct.pack(f"={len(lens)}I", *lens),
            len(ed_idx),
        )
        for j, i in enumerate(ed_idx):
            if verdicts[j]:
                oks[i] = True
            else:
                # OpenSSL's RFC 8032 check is the strict subset of
                # ZIP-215: a rejection here still goes through the
                # full (liberal) per-lane path, exactly like
                # keys.Ed25519PubKey.verify
                pk, msg, sig = items[i]
                oks[i] = pk.verify(msg, sig)
    covered = set(ed_idx)
    for i in range(n):
        if i not in covered:
            pk, msg, sig = items[i]
            oks[i] = pk.verify(msg, sig)
    return oks
