"""Batch signature verification dispatch: the framework's hottest seam.

Mirrors the reference's injectable ``crypto.BatchVerifier``
(crypto/crypto.go + crypto/batch/batch.go:10): callers accumulate
(pubkey, msg, sig) triples and call ``verify()``. Two backends:

- ``CpuBatchVerifier`` — sequential ZIP-215 on host (correctness
  baseline + small-batch latency path, like the reference's per-vote
  single verify).
- ``TpuBatchVerifier`` — one XLA dispatch over signature lanes
  (ops/ed25519). Returns per-signature verdicts, so unlike the
  reference's random-linear-combination batch there is no second
  fall-back pass on failure.

Mixed-curve sets (north-star config #5): ed25519 items go to the TPU
lanes, anything else verifies on host; verdicts are re-interleaved.
The reference instead abandons batching entirely when key types are
mixed (types/validation.go shouldBatchVerify).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .keys import Ed25519PubKey, PubKey

# Below this many signatures the host path wins: one XLA dispatch has
# fixed latency (and a first-call compile), while host ed25519 verify is
# ~60us/sig. Consensus-round commits (tens of sigs) stay on host; bulk
# paths (blocksync replay, light bisection, 150-val commits) go to TPU.
_MIN_TPU_BATCH = 64


def set_min_tpu_batch(n: int) -> None:
    global _MIN_TPU_BATCH
    _MIN_TPU_BATCH = n


class BatchVerifier:
    """Accumulate signatures, verify all at once.

    add() order is preserved; verify() returns (all_ok, per_item_ok).
    """

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> Tuple[bool, List[bool]]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class CpuBatchVerifier(BatchVerifier):
    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        oks = [pk.verify(msg, sig) for pk, msg, sig in self.items]
        return all(oks) and bool(oks), oks

    def __len__(self) -> int:
        return len(self.items)


class TpuBatchVerifier(BatchVerifier):
    """Routes ed25519 lanes to the TPU kernel, everything else to host."""

    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def __len__(self) -> int:
        return len(self.items)

    def verify(self) -> Tuple[bool, List[bool]]:
        ed_idx, ed_items, other_idx = [], [], []
        for i, (pk, msg, sig) in enumerate(self.items):
            if isinstance(pk, Ed25519PubKey):
                ed_idx.append(i)
                ed_items.append((msg, pk.key_bytes, sig))
            else:
                other_idx.append(i)
        oks = [False] * len(self.items)
        if len(ed_items) >= _MIN_TPU_BATCH:
            from ..ops import ed25519 as _ed

            verdicts = _ed.verify_batch(ed_items)
            for i, v in zip(ed_idx, verdicts):
                oks[i] = bool(v)
        else:
            for i in ed_idx:
                pk, msg, sig = self.items[i]
                oks[i] = pk.verify(msg, sig)
        for i in other_idx:
            pk, msg, sig = self.items[i]
            oks[i] = pk.verify(msg, sig)
        return all(oks) and bool(oks), oks


_default_backend = "tpu"
_lock = threading.Lock()


def set_default_backend(name: str) -> None:
    """'tpu' or 'cpu' (process-wide; mirrors config knobs)."""
    global _default_backend
    assert name in ("tpu", "cpu")
    with _lock:
        _default_backend = name


def create_batch_verifier(
    pks: Optional[Sequence[PubKey]] = None,
) -> BatchVerifier:
    """Factory mirroring crypto/batch.CreateBatchVerifier: returns the
    configured backend (TPU by default)."""
    if _default_backend == "cpu":
        return CpuBatchVerifier()
    return TpuBatchVerifier()


def supports_batch_verification(pk: PubKey) -> bool:
    """Mirrors crypto/batch.SupportsBatchVerifier — but note the TPU
    verifier also absorbs mixed sets by splitting (see module doc)."""
    return isinstance(pk, Ed25519PubKey)
