"""Batch signature verification dispatch: the framework's hottest seam.

Mirrors the reference's injectable ``crypto.BatchVerifier``
(crypto/crypto.go + crypto/batch/batch.go:10): callers accumulate
(pubkey, msg, sig) triples and call ``verify()``. Two backends:

- ``CpuBatchVerifier`` — sequential ZIP-215 on host (correctness
  baseline + small-batch latency path, like the reference's per-vote
  single verify).
- ``TpuBatchVerifier`` — one XLA dispatch over signature lanes
  (ops/ed25519). Returns per-signature verdicts, so unlike the
  reference's random-linear-combination batch there is no second
  fall-back pass on failure.

- ``CpuParallelBatchVerifier`` — the multi-core host plane
  (crypto/parallel_verify): verification lanes fan out in calibrated
  chunks over a persistent worker pool, verdicts merge in input
  order. Bit-identical to CpuBatchVerifier; it IS the host path worth
  benchmarking against the device.

Backends live in a registry (``register_backend``) so config knobs,
the bench ablation and tests select by name; the TPU verifier's
host-routed lanes also ride the parallel plane, so every coalesced
caller (types/validation windows, blocksync replay, light client,
consensus vote sets) gets multi-core host verification for free.

Mixed-curve sets (north-star config #5): ed25519 items go to the TPU
lanes, anything else verifies on host; verdicts are re-interleaved.
The reference instead abandons batching entirely when key types are
mixed (types/validation.go shouldBatchVerify).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .keys import Ed25519PubKey, PubKey

# Floor below which the device is never considered. The REAL cutoff is
# measured at runtime (_Calibration below): one XLA dispatch has a
# fixed latency that varies by two orders of magnitude between a local
# chip (~2-5ms) and a tunneled one (~90ms on the axon link), so a
# static constant is wrong somewhere (VERDICT r2 weak #3: the r2 value
# routed 150-sig commits to a 98ms dispatch that costs 12ms on host).
# Setting it to <= 1 (set_min_tpu_batch(1)) FORCES the device path,
# bypassing calibration — tests and the driver dryrun rely on that.
_MIN_TPU_BATCH = 64


def set_min_tpu_batch(n: int) -> None:
    global _MIN_TPU_BATCH
    _MIN_TPU_BATCH = n


class _Calibration:
    """Measured host-vs-device crossover (the reference's dual path —
    per-vote single verify vs batch, types/validation.go:15-21 — made
    measurement-driven).

    Model: device_wall(n) = flat + n*lane_s; host_wall(n) = n*host_s.
    All three parameters are EWMAs of observed walls. Samples that are
    clearly compiles (wall > _COMPILE_CUTOFF_S) never enter the EWMA.
    Seeds are optimistic for the device (local-chip figures) so bulk
    paths try it; two dispatches are enough to learn a tunnel's real
    flat cost and stop sending small batches there.
    """

    _COMPILE_CUTOFF_S = 10.0
    _ALPHA = 0.4
    EXPLORE_EVERY = 256
    # Samples below this floor are enqueue-time artifacts, not real
    # dispatch walls: block_until_ready does not block through the
    # axon tunnel (ADVICE r5 medium), so a non-blocking wait records
    # a near-zero wall that would pull flat_s optimistic and keep
    # misrouting small commits to a ~120 ms link. No genuine
    # dispatch+fetch completes under 200us even on a local chip.
    _WALL_FLOOR_S = 2e-4

    def __init__(self) -> None:
        self.host_s = 80e-6     # ~80us/sig OpenSSL (measured r2)
        self.lane_s = 3.5e-6    # bulk kernel ~3.5us/lane (BENCH_r02)
        self.flat_s = 5e-3      # optimistic local-chip dispatch seed
        self.device_samples = 0
        self._host_streak = 0
        self._lock = threading.Lock()

    def observe_host(self, n: int, wall: float) -> None:
        if n <= 0 or wall <= 0:
            return
        with self._lock:
            self.host_s += self._ALPHA * (wall / n - self.host_s)

    def observe_device(self, n: int, wall: float) -> None:
        if n <= 0 or not (
            self._WALL_FLOOR_S <= wall < self._COMPILE_CUTOFF_S
        ):
            return
        with self._lock:
            # The FIRST sample for a process often includes an XLA
            # compile; a 0.1-10s compile wall entering the EWMA would
            # inflate flat_s so far that the device path is never
            # chosen again (and never observed again = frozen). Accept
            # a first sample only when it clearly isn't a compile.
            if self.device_samples == 0 and wall >= 1.0:
                return
            flat_obs = max(wall - n * self.lane_s, 1e-5)
            self.flat_s += self._ALPHA * (flat_obs - self.flat_s)
            self.device_samples += 1

    def device_wins(self, n: int) -> bool:
        with self._lock:
            return self.flat_s + n * self.lane_s < n * self.host_s

    def should_explore(self) -> bool:
        """Recovery path for a poisoned flat_s: a 1-10s recompile or
        tunnel stall that slips past the compile filter inflates the
        EWMA, every batch then routes to host, and without device
        traffic the estimate could never heal. Every EXPLORE_EVERY
        host-routed eligible batches, one is sent to the device anyway;
        its (filtered) wall pulls flat_s back toward reality."""
        with self._lock:
            self._host_streak += 1
            if self._host_streak >= self.EXPLORE_EVERY:
                self._host_streak = 0
                return True
            return False

    def note_device_used(self) -> None:
        with self._lock:
            self._host_streak = 0

    def crossover(self) -> int:
        """Smallest batch the device is predicted to win."""
        with self._lock:
            margin = self.host_s - self.lane_s
            if margin <= 0:
                return 1 << 30
            return max(1, int(self.flat_s / margin) + 1)


calibration = _Calibration()

_BACKEND_IS_CPU = None


def _jax_backend_is_cpu() -> bool:
    """True when the process's jax backend is the CPU platform: the
    unforced device route is then pointless (it would XLA-compile the
    kernel for the host, which OpenSSL beats) and is skipped. Forced
    routing (set_min_tpu_batch(1) — the dryrun/tests) is unaffected:
    the virtual-mesh validation deliberately runs the kernel on CPU."""
    global _BACKEND_IS_CPU
    if _BACKEND_IS_CPU is None:
        try:
            import jax

            _BACKEND_IS_CPU = jax.default_backend() == "cpu"
        except Exception:  # pragma: no cover - uninitializable backend
            _BACKEND_IS_CPU = True
    return _BACKEND_IS_CPU

# Last routing decision (observability: bench configs + tests report
# which path the calibrated dispatch actually chose).
LAST_ROUTE = {"path": None, "n": 0, "crossover": None}


class ResolvedVerdicts:
    """Already-computed verdicts behind the async-handle interface."""

    def __init__(self, all_ok: bool, oks: List[bool]) -> None:
        self._res = (all_ok, oks)

    def result(self) -> Tuple[bool, List[bool]]:
        return self._res


class _PendingVerdicts:
    """In-flight device dispatch: host lanes already resolved in
    ``oks``; ``result()`` fills the ed25519 lanes from the device
    handle. Plain fields (not a closure) so the handle object holds
    exactly what it needs.

    The device wall for the calibration EWMA is observed by a
    watcher thread blocking on device readiness (see verify_async),
    NOT at result() time: a caller that overlaps long host work
    before resolving would otherwise inflate the observed wall and
    poison flat_s (the replay pipeline resolves a window's handle
    ~1 s of apply-work after dispatch)."""

    __slots__ = ("_handle", "_ed_idx", "_oks")

    def __init__(self, handle, ed_idx, oks) -> None:
        self._handle = handle
        self._ed_idx = ed_idx
        self._oks = oks

    def result(self) -> Tuple[bool, List[bool]]:
        oks = self._oks
        for i, v in zip(self._ed_idx, self._handle.result()):
            oks[i] = bool(v)
        return all(oks) and bool(oks), oks


class _PendingHostVerdicts:
    """Host-routed async batch: ed25519 lanes in flight on the
    parallel plane, other lanes already resolved in ``oks``. The
    pool-completion wall (recorded by the handle's done callback, NOT
    at result() time) feeds the host-cost EWMA, so a caller that
    overlaps long host work before resolving cannot inflate the
    observed host cost — the mirror of the device watcher's concern
    (_PendingVerdicts below)."""

    __slots__ = ("_handle", "_ed_idx", "_oks")

    def __init__(self, handle, ed_idx, oks) -> None:
        self._handle = handle
        self._ed_idx = ed_idx
        self._oks = oks

    def result(self) -> Tuple[bool, List[bool]]:
        oks = self._oks
        for i, v in zip(self._ed_idx, self._handle.result()):
            oks[i] = v
        wall = self._handle.wall()
        if wall:
            calibration.observe_host(len(self._ed_idx), wall)
        return all(oks) and bool(oks), oks


class BatchVerifier:
    """Accumulate signatures, verify all at once.

    add() order is preserved; verify() returns (all_ok, per_item_ok).
    verify_async() enqueues the work and returns a handle whose
    ``result()`` blocks for the verdicts — on the TPU backend the XLA
    dispatch is genuinely asynchronous, so callers can overlap host
    work (block decode/apply) with device verification (the blocksync
    window pipeline; docs/PERF.md "overlapped replay dispatch").
    """

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        raise NotImplementedError

    def verify(self) -> Tuple[bool, List[bool]]:
        raise NotImplementedError

    def verify_async(self):
        """Default: compute now, hand back a resolved handle (host
        backends have no async dispatch to overlap)."""
        return ResolvedVerdicts(*self.verify())

    def __len__(self) -> int:
        raise NotImplementedError


class CpuBatchVerifier(BatchVerifier):
    """Sequential host verification — the correctness baseline and the
    serial leg of the bench ablation (docs/PERF.md host plane)."""

    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        oks = [pk.verify(msg, sig) for pk, msg, sig in self.items]
        return all(oks) and bool(oks), oks

    def __len__(self) -> int:
        return len(self.items)


class _PendingParallelVerdicts:
    """In-flight parallel-plane batch behind the async-handle
    interface (``result()`` blocks for the pool and merges)."""

    __slots__ = ("_handle",)

    def __init__(self, handle) -> None:
        self._handle = handle

    def result(self) -> Tuple[bool, List[bool]]:
        oks = self._handle.result()
        return all(oks) and bool(oks), oks


class CpuParallelBatchVerifier(BatchVerifier):
    """Multi-core host plane: fans lanes over the persistent worker
    pool (crypto/parallel_verify.engine()); verdicts are bit-identical
    to CpuBatchVerifier and order-stable. verify_async() genuinely
    enqueues — the blocksync window pipeline overlaps window K's host
    apply with window K+1's verification even with no device."""

    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        from .parallel_verify import engine

        oks = engine().verify(self.items)
        return all(oks) and bool(oks), oks

    def verify_async(self):
        from .parallel_verify import engine

        return _PendingParallelVerdicts(
            engine().verify_async(self.items)
        )

    def __len__(self) -> int:
        return len(self.items)


class TpuBatchVerifier(BatchVerifier):
    """Routes ed25519 lanes to the TPU kernel, everything else to host."""

    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def __len__(self) -> int:
        return len(self.items)

    def _route(self):
        """Split items by curve and take the calibrated routing
        decision (shared by verify / verify_async)."""
        ed_idx, ed_items, other_idx = [], [], []
        for i, (pk, msg, sig) in enumerate(self.items):
            if isinstance(pk, Ed25519PubKey):
                ed_idx.append(i)
                ed_items.append((msg, pk.key_bytes, sig))
            else:
                other_idx.append(i)
        n_ed = len(ed_items)
        forced = _MIN_TPU_BATCH <= 1
        # calibration first: the backend probe imports jax and
        # initializes the platform, so it must only run when the
        # device route is otherwise about to be taken
        use_device = n_ed >= _MIN_TPU_BATCH and (
            forced
            or (
                (
                    calibration.device_wins(n_ed)
                    or calibration.should_explore()
                )
                and not _jax_backend_is_cpu()
            )
        )
        if use_device and not forced:
            calibration.note_device_used()
        LAST_ROUTE.update(
            path="device" if use_device else "host",
            n=n_ed,
            crossover=None if forced else calibration.crossover(),
        )
        return ed_idx, ed_items, other_idx, use_device

    def _host_lanes(self, oks, ed_idx, other_idx, ed_on_host: bool):
        """Host-routed lanes ride the multi-core plane: ed25519 lanes
        fan out over the persistent pool (crypto/parallel_verify); the
        rare non-ed lanes verify inline. observe_host feeds the
        PARALLEL wall — routing must compare the device against the
        host path's real (multi-core) cost, not one core's."""
        if ed_on_host and ed_idx:
            from .parallel_verify import engine

            t0 = time.perf_counter()
            verdicts = engine().verify(
                [self.items[i] for i in ed_idx]
            )
            wall = time.perf_counter() - t0
            for i, v in zip(ed_idx, verdicts):
                oks[i] = v
            calibration.observe_host(len(ed_idx), wall)
        for i in other_idx:
            pk, msg, sig = self.items[i]
            oks[i] = pk.verify(msg, sig)

    def verify(self) -> Tuple[bool, List[bool]]:
        ed_idx, ed_items, other_idx, use_device = self._route()
        oks = [False] * len(self.items)
        if use_device:
            from ..ops import ed25519 as _ed

            t0 = time.perf_counter()
            verdicts = _ed.verify_batch(ed_items)
            calibration.observe_device(
                len(ed_items), time.perf_counter() - t0
            )
            for i, v in zip(ed_idx, verdicts):
                oks[i] = bool(v)
        self._host_lanes(oks, ed_idx, other_idx, not use_device)
        return all(oks) and bool(oks), oks

    def verify_async(self):
        """Enqueue the device dispatch WITHOUT blocking on verdicts.
        Host-routed lanes (small batches, non-ed25519 curves) are
        verified eagerly — there is nothing to overlap for them.

        A daemon watcher thread blocks on device READINESS and feeds
        the true dispatch wall into the calibration EWMA. Without
        this, the async seam — the one verify_commit_light actually
        takes (types/validation.py) — never corrects the optimistic
        flat-cost seed and small commits route to a ~120 ms tunnel
        forever (BENCH_r05 first run: commit150 auto=device at 10x
        the host wall). Observing at result() time instead would
        over-state walls for callers that overlap host work (the
        replay pipeline) and poison the estimate the other way."""
        ed_idx, ed_items, other_idx, use_device = self._route()
        oks = [False] * len(self.items)
        if not use_device:
            # host route: enqueue ed lanes on the parallel plane and
            # hand back a PENDING handle — the caller's host work
            # (window decode/apply) overlaps pool verification even
            # with no device in the picture
            for i in other_idx:
                pk, msg, sig = self.items[i]
                oks[i] = pk.verify(msg, sig)
            if not ed_idx:
                return ResolvedVerdicts(all(oks) and bool(oks), oks)
            from .parallel_verify import engine

            return _PendingHostVerdicts(
                engine().verify_async(
                    [self.items[i] for i in ed_idx]
                ),
                ed_idx,
                oks,
            )
        from ..ops import ed25519 as _ed

        t0 = time.perf_counter()
        handle = _ed.verify_batch_async(ed_items)
        n_ed = len(ed_items)

        def _observe_ready():
            try:
                # wait_fetch, not wait(): block_until_ready does not
                # block through the axon tunnel (ADVICE r5 medium —
                # exactly the environment the BENCH_r05 misrouting
                # occurred in), so readiness is observed via a minimal
                # 1-element result fetch that must genuinely
                # round-trip. observe_device's wall floor rejects any
                # residual non-blocking sample. (getattr: tolerate
                # injected handles that only model the old surface)
                getattr(handle, "wait_fetch", handle.wait)()
            except Exception:
                return
            calibration.observe_device(
                n_ed, time.perf_counter() - t0
            )

        threading.Thread(target=_observe_ready, daemon=True).start()
        self._host_lanes(oks, ed_idx, other_idx, False)
        return _PendingVerdicts(handle, ed_idx, oks)


_default_backend = "tpu"
_lock = threading.Lock()


def _mesh_factory():
    """Lazy factory for the multi-chip mesh backend — the import
    touches jax device enumeration, which must not happen just
    because the registry dict was built."""
    from .mesh_backend import MeshBatchVerifier

    return MeshBatchVerifier()


# Backend registry: every coalesced caller goes through
# create_batch_verifier(), so registering a backend here hands it to
# all of them (types/validation windows, blocksync replay, light
# client, consensus vote sets) at once. Names mirror the config knob
# (config.CryptoConfig.batch_backend).
_BACKENDS = {
    "tpu": TpuBatchVerifier,
    "cpu": CpuBatchVerifier,
    "cpu-parallel": CpuParallelBatchVerifier,
    "mesh": _mesh_factory,
}


def register_backend(name: str, factory) -> None:
    """Add/replace a named verifier backend (factory: () -> BatchVerifier)."""
    with _lock:
        _BACKENDS[name] = factory


def backends() -> Tuple[str, ...]:
    return tuple(_BACKENDS)


def default_backend() -> str:
    """Name of the backend create_batch_verifier() would return — the
    verify scheduler (crypto/scheduler.py) routes by it."""
    with _lock:
        return _default_backend


def set_default_backend(name: str) -> None:
    """Any registered backend name — 'tpu', 'cpu', 'cpu-parallel', ...
    (process-wide; mirrors config knobs)."""
    global _default_backend
    assert name in _BACKENDS, (name, tuple(_BACKENDS))
    with _lock:
        _default_backend = name


def create_batch_verifier(
    pks: Optional[Sequence[PubKey]] = None,
) -> BatchVerifier:
    """Factory mirroring crypto/batch.CreateBatchVerifier: returns the
    configured backend (TPU by default)."""
    return _BACKENDS[_default_backend]()


def supports_batch_verification(pk: PubKey) -> bool:
    """Mirrors crypto/batch.SupportsBatchVerifier — but note the TPU
    verifier also absorbs mixed sets by splitting (see module doc)."""
    return isinstance(pk, Ed25519PubKey)
