"""Async coalescing signature-verification queue (the consensus-round
hot-path batcher).

The reference verifies live votes one at a time on the CPU
(types/vote.go:237 via consensus/state.go:2175 addVote) — fine for a
CPU whose single verify costs ~60us. A TPU dispatch has fixed latency,
so the win only appears when a round's vote WAVE (one vote per
validator, arriving in a burst) is verified as one lane batch. This
queue is that seam: requests arriving within ``window_s`` (or until
``max_pending``) are verified in ONE batch dispatch through the
injectable crypto/batch backend, each submitter getting its own
future. Verified signatures land in the shared SignatureCache
(reference types/signature_cache.go) so the consensus state machine's
inline re-verify is a cache hit, preserving its single-writer design.

BASELINE.json north star: "a host-side async queue coalesces
signatures across heights/blocks"; SURVEY.md §7 stage 1.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

from . import scheduler as crypto_sched
from .scheduler import PRIORITY_LIVE
from ..utils.log import get_logger

_log = get_logger("coalesce")


def _host_verify_one(pk, sign_bytes: bytes, sig: bytes) -> bool:
    """Per-item host verification (OpenSSL/ref path via PubKey.verify);
    the dispatch-failure fallback. Never raises."""
    try:
        return bool(pk.verify(sign_bytes, sig))
    except Exception:
        return False

# window long enough to collect a gossip burst, short enough to add no
# visible latency to a round (consensus timeouts are 100ms+)
DEFAULT_WINDOW_S = 0.002
DEFAULT_MAX_PENDING = 8192


class CoalescingVerifier:
    """Window-batched async verifier with per-request futures."""

    def __init__(
        self,
        cache=None,
        window_s: float = DEFAULT_WINDOW_S,
        max_pending: int = DEFAULT_MAX_PENDING,
        priority: int = PRIORITY_LIVE,
    ):
        self.cache = cache
        self.window_s = window_s
        self.max_pending = max_pending
        # verify-scheduler class for dispatched windows: the consensus
        # vote wave IS the live round, so LIVE by default
        self.priority = priority
        self._pending: List[Tuple] = []
        self._timer: Optional[asyncio.Task] = None
        self._inflight: set = set()
        # stats (asserted by tests; exported by node metrics)
        self.submitted = 0
        self.dispatches = 0
        self.cache_hits = 0

    def submit(self, pub_key, sign_bytes: bytes, sig: bytes) -> asyncio.Future:
        """Queue one (pubkey, sign_bytes, sig) for verification.

        Returns a future resolving to the bool verdict. Must be called
        on the event loop thread.
        """
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.submitted += 1
        if self.cache is not None and self.cache.contains(
            sign_bytes, sig, pub_key.key_bytes
        ):
            self.cache_hits += 1
            fut.set_result(True)
            return fut
        self._pending.append((pub_key, sign_bytes, sig, fut))
        if len(self._pending) >= self.max_pending:
            self._flush_now()
        elif self._timer is None:
            self._timer = loop.create_task(self._window())
        return fut

    def _flush_now(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        t = asyncio.ensure_future(self._dispatch())
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)

    def flush(self) -> None:
        """Dispatch whatever is pending right now (no-op when empty).

        Callers that know the natural batch boundary — the consensus
        receive loop draining its inbox, a reactor finishing a read
        burst — flush explicitly instead of waiting out the window
        timer: on a busy loop the timer callback can starve for tens
        of milliseconds behind queued work, turning the micro-batch
        window into real quorum latency. The timer stays as the
        backstop for callers without such a boundary."""
        if self._pending:
            self._flush_now()

    async def _window(self) -> None:
        try:
            await asyncio.sleep(self.window_s)
        except asyncio.CancelledError:
            return
        self._timer = None
        await self._dispatch()

    async def _dispatch(self) -> None:
        items, self._pending = self._pending, []
        if not items:
            return
        self.dispatches += 1
        try:
            # one LIVE-class ticket through the unified scheduler; the
            # blocking resolve rides a worker thread so the loop stays
            # free (the scheduler's dispatch may device-compile or
            # grind host crypto — both release the GIL)
            ticket = crypto_sched.scheduler().submit(
                [(pk, sb, sig) for pk, sb, sig, _fut in items],
                priority=self.priority,
                label="vote-wave",
            )
            _, oks = await asyncio.to_thread(ticket.result)
        except asyncio.CancelledError:
            raise  # engine stop cancels the dispatch task
        except Exception as e:
            # A transient backend/device failure must not discard a
            # whole wave of valid votes (the reactor already announced
            # has_vote for them, so they would never be re-gossiped and
            # round liveness degrades). Resolve each lane by per-item
            # host verification instead — correctness is identical, the
            # batch was only ever an optimization.
            _log.error(
                "batch verify dispatch failed; falling back to per-item "
                "host verification",
                n=len(items),
                err=repr(e),
            )

            def _host_verify_all():
                return [
                    _host_verify_one(pk, sb, sig)
                    for pk, sb, sig, _fut in items
                ]

            oks = await asyncio.to_thread(_host_verify_all)
        for (pk, sb, sig, fut), ok in zip(items, oks):
            if ok and self.cache is not None:
                self.cache.add(sb, sig, pk.key_bytes)
            if not fut.done():
                fut.set_result(bool(ok))

    async def drain(self) -> None:
        """Flush pending work and wait for in-flight dispatches
        (tests/shutdown)."""
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        await self._dispatch()
        while self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)
