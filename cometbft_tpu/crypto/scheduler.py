"""Unified verify scheduler: ONE dispatch queue for every signature
verification consumer (docs/PERF.md "Unified verify scheduler").

Before this seam each consumer reached the crypto engine through its
own path — types/validation built a per-call BatchVerifier, the
consensus vote coalescer and the light serving plane each window-
batched on their own, blocksync pipelined through the same unordered
pool — so a live round's precommit wave could queue behind a
500-block catch-up window sharing the host pool. The scheduler is the
single choke point those seams now submit to:

- **Priority classes**: live round (0) > light session (1) >
  catch-up/evidence (2). Dispatch granularity is one calibrated chunk
  (~4 ms of host work, crypto/parallel_verify.chunk_size), so a live
  batch arriving mid-storm preempts at the next chunk boundary — a
  bounded wait of roughly workers x chunk-wall, never the storm's
  full residue.
- **Starvation guard**: a queued ticket older than ``promote_after_s``
  is served ahead of higher classes once every ``promote_every``
  picks — catch-up keeps a bounded 1/promote_every share of dispatch
  slots under ANY sustained live load (tests/test_verify_scheduler).
- **Per-backend lanes + calibrated routing**: the routing decision is
  the exact decision crypto/batch.TpuBatchVerifier._route takes —
  same _MIN_TPU_BATCH floor, same measured host-vs-device crossover
  EWMA (crypto/batch.calibration), same explore/recovery schedule —
  so migrating a consumer onto the scheduler cannot change WHERE its
  lanes verify, only when. Device dispatches ride the async XLA seam
  with the same readiness-watcher calibration feed; the ``mesh``
  backend (crypto/mesh_backend) shards lanes over every local device
  and degrades to host chunks when no mesh materializes.

Verdicts are serial-equivalent BY CONSTRUCTION: every lane runs the
same ``pk.verify``/kernel math the direct backends run, merged back
in submission order (differential-tested in
tests/test_verify_scheduler.py and gated in-bench by the
``verify-sched`` leg).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

from ..trace import global_tracer
from ..utils.log import get_logger
from . import batch as crypto_batch
from .keys import Ed25519PubKey

_log = get_logger("crypto.sched")

# Priority classes, lower value = served first.
PRIORITY_LIVE = 0
PRIORITY_LIGHT = 1
PRIORITY_CATCHUP = 2

CLASS_NAMES = ("live", "light", "catchup")

# Starvation guard defaults: a ticket queued longer than this is
# "aged"; one aged chunk is served per PROMOTE_EVERY picks while any
# aged ticket exists, so lower classes keep a bounded share of
# dispatch slots under sustained higher-class load.
DEFAULT_PROMOTE_AFTER_S = 0.25
DEFAULT_PROMOTE_EVERY = 4


def _clamp_priority(priority) -> int:
    try:
        p = int(priority)
    except (TypeError, ValueError):
        return PRIORITY_CATCHUP
    return min(max(p, PRIORITY_LIVE), PRIORITY_CATCHUP)


class VerifyTicket:
    """One submitted batch: ``result()`` blocks for the merged
    verdicts, returning ``(all_ok, oks)`` exactly like the
    BatchVerifier async handles (crypto/batch.ResolvedVerdicts), so
    the validation seam plumbs it through unchanged."""

    __slots__ = (
        "items", "priority", "label", "t_submit", "t_done", "oks",
        "backend", "_chunks", "_units_left", "_event", "_routed",
    )

    def __init__(self, items, priority: int, label: str) -> None:
        self.items = items
        self.priority = priority
        self.label = label
        self.t_submit = time.perf_counter()
        self.t_done: Optional[float] = None
        self.oks: List[bool] = [False] * len(items)
        self.backend: Optional[str] = None
        self._chunks: deque = deque()
        self._units_left = 0
        self._event = threading.Event()
        self._routed = False

    def result(self, timeout: Optional[float] = None) -> Tuple[bool, List[bool]]:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"verify ticket ({len(self.items)} lanes, "
                f"class={CLASS_NAMES[self.priority]}) not resolved "
                f"within {timeout}s"
            )
        oks = self.oks
        return all(oks) and bool(oks), oks

    def done(self) -> bool:
        return self._event.is_set()

    def wall(self) -> Optional[float]:
        """Submit→resolve wall (queue wait INCLUDED — the latency the
        priority classes exist to bound), or None while pending."""
        done = self.t_done
        return None if done is None else done - self.t_submit


class VerifyScheduler:
    """Single dispatch queue with priority classes and per-backend
    lanes. Thread-safe; one daemon dispatcher thread started lazily on
    first submit."""

    def __init__(
        self,
        promote_after_s: float = DEFAULT_PROMOTE_AFTER_S,
        promote_every: int = DEFAULT_PROMOTE_EVERY,
    ) -> None:
        self.promote_after_s = promote_after_s
        self.promote_every = max(1, promote_every)
        self._cv = threading.Condition()
        self._queues: Tuple[deque, ...] = (deque(), deque(), deque())
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._promo_credit = 0
        # host-pool backpressure: chunks in flight on the shared pool,
        # bounded to the worker count so a late-arriving live ticket
        # waits at most one chunk-wall per worker
        self._inflight = 0
        self._max_slots: Optional[int] = None
        # stats (obs registry + tests + bench)
        self.enqueued_lanes = 0
        self.done_lanes = 0
        self.enqueued_by_class = [0, 0, 0]
        self.done_by_class = [0, 0, 0]
        self.depth_hwm = 0
        self.promoted = 0
        self.device_dispatches = 0
        self.host_chunks = 0
        self.degraded = 0
        self.tickets = 0

    # --- submission ----------------------------------------------------

    def submit(
        self,
        items: Sequence,
        priority: int = PRIORITY_CATCHUP,
        label: str = "",
    ) -> VerifyTicket:
        """Queue (pubkey, msg, sig) lanes for verification under a
        priority class; returns immediately with a VerifyTicket."""
        priority = _clamp_priority(priority)
        ticket = VerifyTicket(list(items), priority, label)
        if not ticket.items:
            # empty batch resolves to (False, []) like BatchVerifier
            ticket.t_done = ticket.t_submit
            ticket._event.set()
            return ticket
        with self._cv:
            if self._closed:
                raise RuntimeError("verify scheduler closed")
            self.tickets += 1
            n = len(ticket.items)
            self.enqueued_lanes += n
            self.enqueued_by_class[priority] += n
            self._queues[priority].append(ticket)
            depth = self.enqueued_lanes - self.done_lanes
            if depth > self.depth_hwm:
                self.depth_hwm = depth
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop,
                    name="verify-sched",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()
        return ticket

    # --- dispatcher ----------------------------------------------------

    def _slots(self) -> int:
        if self._max_slots is None:
            from .parallel_verify import engine

            self._max_slots = max(1, engine().workers)
        return self._max_slots

    def _pick_locked(self) -> Optional[VerifyTicket]:
        """Highest-priority non-empty class, with the bounded aging
        promotion (starvation guard). Caller holds the lock."""
        best_cls = None
        for cls in (PRIORITY_LIVE, PRIORITY_LIGHT, PRIORITY_CATCHUP):
            if self._queues[cls]:
                best_cls = cls
                break
        if best_cls is None:
            return None
        now = time.perf_counter()
        aged = None
        for cls in range(best_cls + 1, len(self._queues)):
            q = self._queues[cls]
            if q and now - q[0].t_submit > self.promote_after_s:
                if aged is None or q[0].t_submit < aged.t_submit:
                    aged = q[0]
        if aged is not None:
            self._promo_credit += 1
            if self._promo_credit >= self.promote_every:
                self._promo_credit = 0
                self.promoted += 1
                return aged
        return self._queues[best_cls][0]

    def _loop(self) -> None:
        while True:
            with self._cv:
                ticket = None
                while True:
                    if self._inflight < self._slots():
                        ticket = self._pick_locked()
                    if ticket is not None or self._closed:
                        break
                    # bounded wait: aging promotions must be
                    # re-evaluated even with no new submissions
                    self._cv.wait(0.05)
                if ticket is None and self._closed:
                    return
                if ticket is None:
                    continue
                if ticket._routed:
                    chunk = ticket._chunks.popleft()
                    if not ticket._chunks:
                        self._queues[ticket.priority].remove(ticket)
                else:
                    chunk = None
                    self._queues[ticket.priority].remove(ticket)
            try:
                if chunk is None:
                    self._route(ticket)
                else:
                    self._run_chunk(ticket, chunk)
            except Exception as e:  # pragma: no cover - last resort
                # verdicts must never be lost: resolve the affected
                # lanes by per-item host verification
                _log.error(
                    "verify dispatch failed; per-item host fallback",
                    err=repr(e),
                    lanes=len(ticket.items),
                )
                self._fallback_serial(ticket, chunk)

    # --- routing -------------------------------------------------------

    def _route(self, ticket: VerifyTicket) -> None:
        """First pop: split lanes by curve, take the calibrated
        backend-routing decision (the same decision
        crypto/batch.TpuBatchVerifier._route takes), dispatch the
        device part async, queue the host part as calibrated chunks."""
        items = ticket.items
        ed_idx: List[int] = []
        ed_items = []
        other_idx: List[int] = []
        for i, (pk, msg, sig) in enumerate(items):
            if isinstance(pk, Ed25519PubKey):
                ed_idx.append(i)
                ed_items.append((msg, pk.key_bytes, sig))
            else:
                other_idx.append(i)
        backend = crypto_batch.default_backend()
        ticket.backend = backend
        if backend not in ("tpu", "cpu", "cpu-parallel", "mesh"):
            # custom registered backend (register_backend): preserve
            # its semantics verbatim — build it and resolve on the
            # dispatcher thread (priority ordering still applied at
            # pick time; preemption granularity is the whole ticket)
            verifier = crypto_batch.create_batch_verifier()
            for pk, msg, sig in items:
                verifier.add(pk, msg, sig)
            _, oks = verifier.verify()
            ticket.oks[:] = oks
            ticket._routed = True
            self._finish(ticket, len(items))
            return
        n_ed = len(ed_items)
        forced = crypto_batch._MIN_TPU_BATCH <= 1
        cal = crypto_batch.calibration
        use_device = False
        if backend == "tpu":
            use_device = n_ed >= crypto_batch._MIN_TPU_BATCH and (
                forced
                or (
                    (cal.device_wins(n_ed) or cal.should_explore())
                    and not crypto_batch._jax_backend_is_cpu()
                )
            )
            if use_device and not forced:
                cal.note_device_used()
        elif backend == "mesh":
            # explicit operator choice: shard whenever a mesh exists
            # (no calibration gate — the mesh IS the configured
            # plane); honor the batch floor so tiny commits stay on
            # host, and degrade to host chunks with no mesh
            from .mesh_backend import mesh_devices

            if mesh_devices() > 1:
                use_device = n_ed > 0 and (
                    forced or n_ed >= crypto_batch._MIN_TPU_BATCH
                )
            else:
                ticket.backend = "mesh-degraded"
                self.degraded += 1
        crypto_batch.LAST_ROUTE.update(
            path="device" if use_device else "host",
            n=n_ed,
            crossover=None if forced else cal.crossover(),
        )
        # non-ed lanes: verified inline at route time (rare curves,
        # exactly TpuBatchVerifier._host_lanes' treatment)
        for i in other_idx:
            pk, msg, sig = items[i]
            ticket.oks[i] = pk.verify(msg, sig)
        ticket._routed = True
        if use_device and ed_idx:
            if self._dispatch_device(ticket, ed_idx, ed_items, backend):
                return
            # device dispatch failed: re-route the lanes to host
            ticket.backend = f"{backend}-degraded"
            self.degraded += 1
        self._queue_host_chunks(ticket, ed_idx)

    def _dispatch_device(
        self, ticket: VerifyTicket, ed_idx, ed_items, backend: str
    ) -> bool:
        """Async device dispatch for the ed25519 lanes; a daemon
        watcher feeds the calibration EWMA from true readiness
        (wait_fetch — block_until_ready does not block through the
        axon tunnel, crypto/batch.verify_async) and resolves the
        ticket. Returns False when the dispatch itself fails."""
        try:
            from ..ops import ed25519 as _ed

            t0 = time.perf_counter()
            handle = _ed.verify_batch_async(ed_items)
        except Exception as e:
            _log.error(
                "device dispatch failed; host chunks",
                backend=backend,
                err=repr(e),
                lanes=len(ed_items),
            )
            return False
        self.device_dispatches += 1
        ticket._units_left += 1
        n_ed = len(ed_items)
        cal = crypto_batch.calibration

        def _watch():
            try:
                getattr(handle, "wait_fetch", handle.wait)()
                cal.observe_device(n_ed, time.perf_counter() - t0)
                verdicts = handle.result()
            except Exception as e:
                _log.error(
                    "device resolve failed; per-item host fallback",
                    err=repr(e),
                    lanes=n_ed,
                )
                verdicts = [
                    _host_verify_one(ticket.items[i]) for i in ed_idx
                ]
            for i, v in zip(ed_idx, verdicts):
                ticket.oks[i] = bool(v)
            self._unit_done(ticket, n_ed)

        threading.Thread(
            target=_watch, name="verify-sched-dev", daemon=True
        ).start()
        return True

    def _queue_host_chunks(self, ticket: VerifyTicket, ed_idx) -> None:
        """Chunk the host-routed ed25519 lanes (calibrated ~4 ms of
        serial work each — the preemption granularity) and requeue the
        ticket at the FRONT of its class so its chunks drain before
        later same-class arrivals."""
        if not ed_idx:
            if ticket._units_left == 0:
                self._finish(ticket, 0)
            return
        from .parallel_verify import engine

        eng = engine()
        chunk = eng.chunk_size(len(ed_idx))
        chunks = [
            ed_idx[s : s + chunk] for s in range(0, len(ed_idx), chunk)
        ]
        with self._cv:
            ticket._chunks.extend(chunks)
            ticket._units_left += len(chunks)
            self._queues[ticket.priority].appendleft(ticket)
            self._cv.notify_all()

    # --- host execution ------------------------------------------------

    def _run_chunk(self, ticket: VerifyTicket, idx_chunk) -> None:
        """One host chunk: on the shared pool when it pays (slot-
        bounded so priorities hold at chunk granularity), inline on
        the dispatcher thread otherwise (serial tier / tiny work)."""
        from .parallel_verify import _verify_chunk, engine

        eng = engine()
        chunk_items = [ticket.items[i] for i in idx_chunk]
        self.host_chunks += 1
        pool = None
        if ticket.backend != "cpu" and len(ticket.items) >= eng.min_parallel:
            pool = eng._ensure_pool()
        if pool is None:
            oks, wall = _verify_chunk(chunk_items, eng.tier)
            self._chunk_resolved(ticket, idx_chunk, oks, wall, eng)
            return
        if eng.tier == "process":
            chunk_items = [
                (pk, bytes(m), bytes(s)) for pk, m, s in chunk_items
            ]
        with self._cv:
            self._inflight += 1
        try:
            fut = pool.submit(_verify_chunk, chunk_items, eng.tier)
        except RuntimeError:
            # pool shut down underneath us (teardown): inline
            with self._cv:
                self._inflight -= 1
            oks, wall = _verify_chunk(chunk_items, eng.tier)
            self._chunk_resolved(ticket, idx_chunk, oks, wall, eng)
            return
        eng._chunk_submitted()

        def _done(f):
            eng._chunk_done()
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()
            try:
                oks, wall = f.result()
            except Exception:  # pragma: no cover - worker died
                oks = [
                    _host_verify_one(ticket.items[i]) for i in idx_chunk
                ]
                wall = 0.0
            self._chunk_resolved(ticket, idx_chunk, oks, wall, eng)

        fut.add_done_callback(_done)

    def _chunk_resolved(self, ticket, idx_chunk, oks, wall, eng) -> None:
        for i, ok in zip(idx_chunk, oks):
            ticket.oks[i] = bool(ok)
        n = len(idx_chunk)
        if wall:
            eng._observe_chunk(n, wall)
            if ticket.backend == "tpu":
                # host-vs-device routing EWMA: fed only on the backend
                # whose routing consults it (TpuBatchVerifier parity —
                # the cpu backends never calibrated)
                crypto_batch.calibration.observe_host(n, wall)
        self._unit_done(ticket, n)

    def _fallback_serial(self, ticket, idx_chunk) -> None:
        idx = idx_chunk if idx_chunk is not None else range(len(ticket.items))
        for i in idx:
            ticket.oks[i] = _host_verify_one(ticket.items[i])
        if idx_chunk is None:
            # routing never completed: the whole ticket is resolved
            ticket._routed = True
            self._finish(ticket, len(ticket.items))
        else:
            self._unit_done(ticket, len(idx_chunk))

    # --- completion ----------------------------------------------------

    def _unit_done(self, ticket: VerifyTicket, lanes: int) -> None:
        with self._cv:
            ticket._units_left -= 1
            last = ticket._units_left <= 0 and not ticket._chunks
        if last:
            self._finish(ticket, len(ticket.items))

    def _finish(self, ticket: VerifyTicket, lanes: int) -> None:
        ticket.t_done = time.perf_counter()
        with self._cv:
            n = len(ticket.items)
            self.done_lanes += n
            self.done_by_class[ticket.priority] += n
            self._cv.notify_all()
        tr = global_tracer()
        if tr.enabled:
            tr.complete(
                "crypto.sched.dispatch",
                time.monotonic_ns()
                - int((ticket.t_done - ticket.t_submit) * 1e9),
                int((ticket.t_done - ticket.t_submit) * 1e9),
                tid="crypto.sched",
                cls=CLASS_NAMES[ticket.priority],
                backend=ticket.backend or "?",
                lanes=len(ticket.items),
            )
        ticket._event.set()

    # --- observability / lifecycle -------------------------------------

    def queue_stats(self) -> dict:
        """Backpressure snapshot (obs/queues.py registry): pending
        lane depth overall + per class. Queued-but-unrouted tickets
        count every lane; routed tickets count their unfinished
        chunks' share. No ``maxsize`` — the queue is unbounded by
        design, depth is load, not overload."""
        with self._cv:
            depth = self.enqueued_lanes - self.done_lanes
            per = {}
            for cls, name in enumerate(CLASS_NAMES):
                per[f"{name}_depth"] = (
                    self.enqueued_by_class[cls] - self.done_by_class[cls]
                )
            out = {
                "depth": max(depth, 0),
                "high_watermark": self.depth_hwm,
                "enqueued": self.enqueued_lanes,
                "dropped": 0,
                "inflight_chunks": self._inflight,
                "promoted": self.promoted,
                "device_dispatches": self.device_dispatches,
                "host_chunks": self.host_chunks,
                "degraded": self.degraded,
            }
            out.update(per)
            return out

    def stats(self) -> dict:
        with self._cv:
            return {
                "tickets": self.tickets,
                "lanes": self.enqueued_lanes,
                "by_class": {
                    name: self.enqueued_by_class[cls]
                    for cls, name in enumerate(CLASS_NAMES)
                },
                "promoted": self.promoted,
                "device_dispatches": self.device_dispatches,
                "host_chunks": self.host_chunks,
                "degraded": self.degraded,
            }

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted lane resolved (tests/bench)."""
        deadline = time.perf_counter() + timeout
        with self._cv:
            while self.done_lanes < self.enqueued_lanes:
                left = deadline - time.perf_counter()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.1))
        return True

    def close(self) -> None:
        """Stop the dispatcher after the queue drains (shutdown)."""
        self.drain(timeout=5.0)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)


def _host_verify_one(item) -> bool:
    """Per-item host verification — the never-raises fallback lane."""
    pk, msg, sig = item
    try:
        return bool(pk.verify(msg, sig))
    except Exception:
        return False


# --- process-wide default scheduler --------------------------------------

_SCHED: Optional[VerifyScheduler] = None
_SCHED_LOCK = threading.Lock()


def scheduler() -> VerifyScheduler:
    """The shared scheduler every verify consumer submits through
    (types/validation, the consensus vote coalescer, light serving,
    blocksync, statesync, evidence). Created lazily on first use."""
    global _SCHED
    with _SCHED_LOCK:
        if _SCHED is None:
            _SCHED = VerifyScheduler()
        return _SCHED


def set_scheduler(s: Optional[VerifyScheduler]) -> None:
    """Swap the process-wide scheduler (tests / operator reconfig)."""
    global _SCHED
    with _SCHED_LOCK:
        old, _SCHED = _SCHED, s
    if old is not None and old is not s:
        old.close()


def sched_stats_if_running() -> Optional[dict]:
    """Queue-depth gauges for the obs registry, or None when no
    scheduler was ever built — the registry entry must never CREATE
    the scheduler (dispatcher spin-up) just to report an idle plane."""
    with _SCHED_LOCK:
        s = _SCHED
    return None if s is None else s.queue_stats()
