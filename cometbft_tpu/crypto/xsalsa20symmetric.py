"""NaCl secretbox symmetric encryption (reference
crypto/xsalsa20symmetric/symmetric.go): XSalsa20 stream cipher +
Poly1305 one-time MAC, wire format ``nonce(24) || tag(16) || ct``.

Used for passphrase-encrypting armored private keys (secret = 32 bytes,
"something like Sha256(Bcrypt(passphrase))" per the reference). Pure
Python: payloads are key-sized, so throughput is irrelevant; what
matters is exact NaCl compatibility (HSalsa20 subkey derivation, Salsa20
counter stream with the first 32 bytes reserved for the Poly1305 key).
"""

from __future__ import annotations

import hmac
import os
import struct

# shared 32-bit word primitives + "expand 32-byte k" constants: the
# Salsa and ChaCha families use the same sigma and rotate
from .xchacha20poly1305 import _MASK, _SIGMA, _rotl

NONCE_LEN = 24
SECRET_LEN = 32
OVERHEAD = 16  # poly1305 tag


def _salsa_doubleround(x):
    # columnround
    x[4] ^= _rotl((x[0] + x[12]) & _MASK, 7)
    x[8] ^= _rotl((x[4] + x[0]) & _MASK, 9)
    x[12] ^= _rotl((x[8] + x[4]) & _MASK, 13)
    x[0] ^= _rotl((x[12] + x[8]) & _MASK, 18)
    x[9] ^= _rotl((x[5] + x[1]) & _MASK, 7)
    x[13] ^= _rotl((x[9] + x[5]) & _MASK, 9)
    x[1] ^= _rotl((x[13] + x[9]) & _MASK, 13)
    x[5] ^= _rotl((x[1] + x[13]) & _MASK, 18)
    x[14] ^= _rotl((x[10] + x[6]) & _MASK, 7)
    x[2] ^= _rotl((x[14] + x[10]) & _MASK, 9)
    x[6] ^= _rotl((x[2] + x[14]) & _MASK, 13)
    x[10] ^= _rotl((x[6] + x[2]) & _MASK, 18)
    x[3] ^= _rotl((x[15] + x[11]) & _MASK, 7)
    x[7] ^= _rotl((x[3] + x[15]) & _MASK, 9)
    x[11] ^= _rotl((x[7] + x[3]) & _MASK, 13)
    x[15] ^= _rotl((x[11] + x[7]) & _MASK, 18)
    # rowround
    x[1] ^= _rotl((x[0] + x[3]) & _MASK, 7)
    x[2] ^= _rotl((x[1] + x[0]) & _MASK, 9)
    x[3] ^= _rotl((x[2] + x[1]) & _MASK, 13)
    x[0] ^= _rotl((x[3] + x[2]) & _MASK, 18)
    x[6] ^= _rotl((x[5] + x[4]) & _MASK, 7)
    x[7] ^= _rotl((x[6] + x[5]) & _MASK, 9)
    x[4] ^= _rotl((x[7] + x[6]) & _MASK, 13)
    x[5] ^= _rotl((x[4] + x[7]) & _MASK, 18)
    x[11] ^= _rotl((x[10] + x[9]) & _MASK, 7)
    x[8] ^= _rotl((x[11] + x[10]) & _MASK, 9)
    x[9] ^= _rotl((x[8] + x[11]) & _MASK, 13)
    x[10] ^= _rotl((x[9] + x[8]) & _MASK, 18)
    x[12] ^= _rotl((x[15] + x[14]) & _MASK, 7)
    x[13] ^= _rotl((x[12] + x[15]) & _MASK, 9)
    x[14] ^= _rotl((x[13] + x[12]) & _MASK, 13)
    x[15] ^= _rotl((x[14] + x[13]) & _MASK, 18)


def _salsa20_block(key: bytes, block16: bytes) -> bytes:
    """Salsa20 core with the final state addition (the stream block)."""
    k = struct.unpack("<8L", key)
    n = struct.unpack("<4L", block16)
    init = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    x = list(init)
    for _ in range(10):
        _salsa_doubleround(x)
    return struct.pack(
        "<16L", *(((a + b) & _MASK) for a, b in zip(x, init))
    )


def hsalsa20(key: bytes, nonce16: bytes) -> bytes:
    """HSalsa20 KDF (no final addition; words 0,5,10,15,6,7,8,9)."""
    k = struct.unpack("<8L", key)
    n = struct.unpack("<4L", nonce16)
    x = [
        _SIGMA[0], k[0], k[1], k[2],
        k[3], _SIGMA[1], n[0], n[1],
        n[2], n[3], _SIGMA[2], k[4],
        k[5], k[6], k[7], _SIGMA[3],
    ]
    for _ in range(10):
        _salsa_doubleround(x)
    return struct.pack(
        "<8L", *(x[i] for i in (0, 5, 10, 15, 6, 7, 8, 9))
    )


def _xsalsa20_stream(key: bytes, nonce24: bytes, length: int) -> bytes:
    sub = hsalsa20(key, nonce24[:16])
    out = bytearray()
    counter = 0
    while len(out) < length:
        block16 = nonce24[16:24] + struct.pack("<Q", counter)
        out += _salsa20_block(sub, block16)
        counter += 1
    return bytes(out[:length])


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i : i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def seal(plaintext: bytes, nonce: bytes, secret: bytes) -> bytes:
    """secretbox.Seal: returns tag(16) || ct (no nonce prefix)."""
    if len(secret) != SECRET_LEN:
        raise ValueError("secret must be 32 bytes")
    if len(nonce) != NONCE_LEN:
        raise ValueError("nonce must be 24 bytes")
    stream = _xsalsa20_stream(secret, nonce, 32 + len(plaintext))
    poly_key, pad = stream[:32], stream[32:]
    ct = bytes(a ^ b for a, b in zip(plaintext, pad))
    return _poly1305(poly_key, ct) + ct


def open_box(boxed: bytes, nonce: bytes, secret: bytes) -> bytes:
    """secretbox.Open; raises ValueError on authentication failure."""
    if len(secret) != SECRET_LEN:
        raise ValueError("secret must be 32 bytes")
    if len(boxed) < OVERHEAD:
        raise ValueError("ciphertext too short")
    tag, ct = boxed[:16], boxed[16:]
    stream = _xsalsa20_stream(secret, nonce, 32 + len(ct))
    poly_key, pad = stream[:32], stream[32:]
    if not hmac.compare_digest(tag, _poly1305(poly_key, ct)):
        raise ValueError("ciphertext decryption failed")
    return bytes(a ^ b for a, b in zip(ct, pad))


def encrypt_symmetric(plaintext: bytes, secret: bytes) -> bytes:
    """Reference EncryptSymmetric: nonce(24) || secretbox.Seal(...).
    Ciphertext is nonce+overhead = 40 bytes longer than the plaintext."""
    nonce = os.urandom(NONCE_LEN)
    return nonce + seal(plaintext, nonce, secret)


def decrypt_symmetric(ciphertext: bytes, secret: bytes) -> bytes:
    """Reference DecryptSymmetric; raises ValueError on bad input/MAC."""
    if len(ciphertext) <= NONCE_LEN + OVERHEAD:
        raise ValueError("ciphertext is too short")
    nonce = ciphertext[:NONCE_LEN]
    return open_box(ciphertext[NONCE_LEN:], nonce, secret)
