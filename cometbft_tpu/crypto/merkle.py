"""RFC 6962 merkle trees (host path) + inclusion proofs.

Behavioral parity with the reference's crypto/merkle (tree.go, proof.go):
leaf hash = SHA-256(0x00 || leaf), inner = SHA-256(0x01 || left || right),
split point = largest power of two < n. Empty tree hashes to
SHA-256(""). The TPU bulk path for hashing thousands of leaves lives in
ops/ (device SHA-256); this module is the canonical host implementation
used for block/header hashing.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Root hash. Iterative binary-carry reduction: the RFC 6962
    left-heavy split (k = largest power of two < n) is exactly the
    binary decomposition of n, so pushing leaf hashes and merging
    equal-sized subtrees (then folding the remainder right-to-left)
    yields the identical tree — without the recursive version's
    O(n log n) list slicing. ~2.5x faster on 150-leaf valset hashes
    (the replay pipeline hashes several per height)."""
    n = len(items)
    if n == 0:
        return _sha256(b"")
    sha = hashlib.sha256
    stack: List = []  # (subtree hash, subtree size)
    for it in items:
        h = sha(LEAF_PREFIX + it).digest()
        s = 1
        while stack and stack[-1][1] == s:
            ph, _ = stack.pop()
            h = sha(INNER_PREFIX + ph + h).digest()
            s *= 2
        stack.append((h, s))
    h, _ = stack.pop()
    while stack:
        ph, _ = stack.pop()
        h = sha(INNER_PREFIX + ph + h).digest()
    return h


@dataclass
class Proof:
    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = _compute_root(
            self.total, self.index, self.leaf_hash, self.aunts
        )
        return computed == root


def _compute_root(
    total: int, index: int, lh: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if total == 0:
        return None
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_root(k, index, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_root(total - k, index - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]):
    """Returns (root, [Proof per item])."""
    trails, root_node = _trails_from_byte_slices(list(items))
    root = root_node.hash if root_node else _sha256(b"")
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            Proof(
                total=len(items),
                index=i,
                leaf_hash=trail.hash,
                aunts=trail.flatten_aunts(),
            )
        )
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None  # sibling pointers while building the trail
        self.right = None

    def flatten_aunts(self) -> List[bytes]:
        out = []
        node = self
        while node is not None:
            if node.left is not None:
                out.append(node.left.hash)
            elif node.right is not None:
                out.append(node.right.hash)
            node = node.parent
        return out


def _trails_from_byte_slices(items: List[bytes]):
    n = len(items)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root
