"""RFC 6962 merkle trees (host path) + inclusion proofs.

Behavioral parity with the reference's crypto/merkle (tree.go, proof.go):
leaf hash = SHA-256(0x00 || leaf), inner = SHA-256(0x01 || left || right),
split point = largest power of two < n. Empty tree hashes to
SHA-256(""). The TPU bulk path for hashing thousands of leaves lives in
ops/ (device SHA-256); this module is the canonical host implementation
used for block/header hashing.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    """Root hash. Iterative binary-carry reduction: the RFC 6962
    left-heavy split (k = largest power of two < n) is exactly the
    binary decomposition of n, so pushing leaf hashes and merging
    equal-sized subtrees (then folding the remainder right-to-left)
    yields the identical tree — without the recursive version's
    O(n log n) list slicing. ~2.5x faster on 150-leaf valset hashes
    (the replay pipeline hashes several per height); the native tree
    (native/wirecodec.cpp merkle_root, differential-tested against
    this implementation) takes the larger lists."""
    n = len(items)
    if n == 0:
        return _sha256(b"")
    if n >= 4:
        from ..utils import wirecodec

        nat = wirecodec.module()
        if nat is not None:
            try:
                return nat.merkle_root(items)
            except Exception:  # pragma: no cover - non-bytes leaves
                pass
    sha = hashlib.sha256
    stack: List = []  # (subtree hash, subtree size)
    for it in items:
        h = sha(LEAF_PREFIX + it).digest()
        s = 1
        while stack and stack[-1][1] == s:
            ph, _ = stack.pop()
            h = sha(INNER_PREFIX + ph + h).digest()
            s *= 2
        stack.append((h, s))
    h, _ = stack.pop()
    while stack:
        ph, _ = stack.pop()
        h = sha(INNER_PREFIX + ph + h).digest()
    return h


@dataclass
class Proof:
    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes] = field(default_factory=list)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0 or self.index >= self.total:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = _compute_root(
            self.total, self.index, self.leaf_hash, self.aunts
        )
        return computed == root


def _compute_root(
    total: int, index: int, lh: bytes, aunts: List[bytes]
) -> Optional[bytes]:
    if total == 0:
        return None
    if total == 1:
        if aunts:
            return None
        return lh
    if not aunts:
        return None
    k = _split_point(total)
    if index < k:
        left = _compute_root(k, index, lh, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_root(total - k, index - k, lh, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]):
    """Returns (root, [Proof per item])."""
    return proofs_from_leaf_hashes([leaf_hash(it) for it in items])


def proofs_from_leaf_hashes(leaf_hashes: Sequence[bytes]):
    """Returns (root, [Proof per leaf]) from PRECOMPUTED leaf hashes
    (sha256(0x00 || item) each) — the seam that lets the proposal
    path hash block-part chunks natively with the GIL released
    (state/native_finalize.part_leaf_hashes) while the trail/aunt
    construction stays here; identical output to
    ``proofs_from_byte_slices`` on the same items."""
    trails, root_node = _trails_from_leaf_hashes(list(leaf_hashes))
    root = root_node.hash if root_node else _sha256(b"")
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(
            Proof(
                total=len(leaf_hashes),
                index=i,
                leaf_hash=trail.hash,
                aunts=trail.flatten_aunts(),
            )
        )
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent = None
        self.left = None  # sibling pointers while building the trail
        self.right = None

    def flatten_aunts(self) -> List[bytes]:
        out = []
        node = self
        while node is not None:
            if node.left is not None:
                out.append(node.left.hash)
            elif node.right is not None:
                out.append(node.right.hash)
            node = node.parent
        return out


def _trails_from_leaf_hashes(leaf_hashes: List[bytes]):
    n = len(leaf_hashes)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(leaf_hashes[0])
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_leaf_hashes(leaf_hashes[:k])
    rights, right_root = _trails_from_leaf_hashes(leaf_hashes[k:])
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# --- proof operators ----------------------------------------------------
#
# Chainable proof steps for light-client verification of ABCI query
# responses (the role of the reference's crypto/merkle ProofRuntime +
# ProofOperators, light/rpc/client.go:126-187): each op maps the value
# produced by the previous op to the next root, and the final output
# must equal the light-verified AppHash. Three op types cover the
# provable kvstore (models/kvstore.py prove mode):
#
#   kv:v  — value inclusion: a Proof for the sorted-KV leaf
#           len-prefix(key) || len-prefix(value); recomputing the leaf
#           from the QUERIED key and RETURNED value binds both.
#   kv:a  — absence: the would-be neighbors in sorted-key order (their
#           own inclusion proofs + adjacency/ordering checks) show no
#           leaf for the key can exist.
#   kv:h  — app-hash binding: app_hash = SHA-256(height_8B || kv_root).
#
# The design is an original sorted-array range proof (simpler than
# iavl's tree-path absence proofs but with the same guarantees for a
# flat store); op payloads use the repo's deterministic proto writer.


class ProofError(Exception):
    """A proof op failed to verify / decode."""


OP_KV_VALUE = "kv:v"
OP_KV_ABSENCE = "kv:a"
OP_APP_HASH = "kv:h"


@dataclass
class ProofOp:
    type: str
    key: bytes
    data: bytes

    def encode(self) -> bytes:
        from ..utils import proto

        return (
            proto.field_string(1, self.type)
            + proto.field_bytes(2, self.key)
            + proto.field_bytes(3, self.data)
        )

    @classmethod
    def decode(cls, b: bytes) -> "ProofOp":
        from ..utils import proto

        m = proto.parse(b)
        return cls(
            type=proto.get1(m, 1, b"").decode(),
            key=proto.get1(m, 2, b""),
            data=proto.get1(m, 3, b""),
        )


def encode_proof_ops(ops: List[ProofOp]) -> bytes:
    from ..utils import proto

    return b"".join(proto.field_message(1, op.encode()) for op in ops)


def decode_proof_ops(b: bytes) -> List[ProofOp]:
    from ..utils import proto

    m = proto.parse(b)
    return [ProofOp.decode(x) for x in m.get(1, [])]


def encode_proof(p: Proof) -> bytes:
    from ..utils import proto

    return (
        proto.field_varint(1, p.total)
        + proto.field_varint(2, p.index)
        + proto.field_bytes(3, p.leaf_hash)
        + b"".join(proto.field_bytes(4, a) for a in p.aunts)
    )


def decode_proof(b: bytes) -> Proof:
    from ..utils import proto

    m = proto.parse(b)
    return Proof(
        total=proto.get1(m, 1, 0),
        index=proto.get1(m, 2, 0),
        leaf_hash=proto.get1(m, 3, b""),
        aunts=list(m.get(4, [])),
    )


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Canonical sorted-KV leaf encoding (length-prefixed k then v)."""
    from ..utils import proto

    return proto.field_bytes(1, key) + proto.field_bytes(2, value)


def _leaf_root(proof: Proof, leaf: bytes):
    # Bounds must be enforced here, not just in Proof.verify: the
    # absence-op adjacency/ordering checks (index+1, index==0/total-1)
    # assume index integrity that _compute_root alone does not give —
    # the extreme leaves' proofs also verify under inflated/negative
    # indices.
    if proof.total <= 0:
        raise ProofError("inclusion proof with non-positive tree size")
    if not (0 <= proof.index < proof.total):
        raise ProofError("inclusion proof index out of bounds")
    lh = leaf_hash(leaf)
    root = _compute_root(proof.total, proof.index, lh, proof.aunts)
    if root is None:
        raise ProofError("malformed inclusion proof")
    return root


def _run_value_op(op: ProofOp, key: bytes, value: bytes) -> bytes:
    if op.key != key:
        raise ProofError("value op bound to a different key")
    proof = decode_proof(op.data)
    return _leaf_root(proof, kv_leaf(key, value))


def _run_absence_op(op: ProofOp, key: bytes) -> bytes:
    from ..utils import proto

    if op.key != key:
        raise ProofError("absence op bound to a different key")
    m = proto.parse(op.data)
    neighbors = []
    for nb in m.get(1, []):
        nm = proto.parse(nb)
        neighbors.append(
            (
                decode_proof(proto.get1(nm, 1, b"")),
                proto.get1(nm, 2, b""),   # neighbor key
                proto.get1(nm, 3, b""),   # neighbor value
            )
        )
    if not neighbors:
        # empty store: its root is the empty-tree hash
        return _sha256(b"")
    roots = [
        _leaf_root(p, kv_leaf(nk, nv)) for p, nk, nv in neighbors
    ]
    if any(r != roots[0] for r in roots[1:]):
        raise ProofError("absence neighbors prove different roots")
    total = neighbors[0][0].total
    if any(p.total != total for p, _, _ in neighbors):
        raise ProofError("absence neighbors disagree on tree size")
    if len(neighbors) == 2:
        (p1, k1, _), (p2, k2, _) = neighbors
        if p2.index != p1.index + 1:
            raise ProofError("absence neighbors are not adjacent")
        if not (k1 < key < k2):
            raise ProofError("key does not fall between the neighbors")
    elif len(neighbors) == 1:
        p1, k1, _ = neighbors[0]
        if p1.index == 0 and key < k1:
            pass  # before the first key
        elif p1.index == total - 1 and key > k1:
            pass  # after the last key
        else:
            raise ProofError(
                "single absence neighbor neither first-above nor "
                "last-below the key"
            )
    else:
        raise ProofError("absence proof needs 1 or 2 neighbors")
    return roots[0]


def _run_app_hash_op(op: ProofOp, root: bytes) -> bytes:
    from ..utils import proto

    m = proto.parse(op.data)
    height = proto.get1(m, 1, 0)
    if height < 0:
        raise ProofError("negative height in app-hash op")
    return _sha256(height.to_bytes(8, "big") + root)


class ProofRuntime:
    """Verify a proof-op chain against a light-verified AppHash
    (reference merkle.ProofRuntime as used by light/rpc/client.go)."""

    def verify_value(
        self, ops: List[ProofOp], app_hash: bytes, key: bytes,
        value: bytes,
    ) -> None:
        """value may be EMPTY — a committed empty value is a real
        entry (kv_leaf is injective either way); presence vs absence
        is the caller's routing decision (response code), never
        inferred from value truthiness."""
        self._verify(ops, app_hash, key, value)

    def verify_absence(
        self, ops: List[ProofOp], app_hash: bytes, key: bytes
    ) -> None:
        self._verify(ops, app_hash, key, None)

    def _verify(self, ops, app_hash, key, value) -> None:
        if len(ops) != 2:
            raise ProofError(f"expected 2 proof ops, got {len(ops)}")
        first, second = ops
        if value is not None:
            if first.type != OP_KV_VALUE:
                raise ProofError(f"unexpected first op {first.type!r}")
            root = _run_value_op(first, key, value)
        else:
            if first.type != OP_KV_ABSENCE:
                raise ProofError(f"unexpected first op {first.type!r}")
            root = _run_absence_op(first, key)
        if second.type != OP_APP_HASH:
            raise ProofError(f"unexpected final op {second.type!r}")
        computed = _run_app_hash_op(second, root)
        if computed != app_hash:
            raise ProofError(
                "proof chain does not land on the verified app hash"
            )
