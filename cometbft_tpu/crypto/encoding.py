"""Public-key <-> proto conversions (reference crypto/encoding/
codec.go:45-130: PubKeyToProto / PubKeyFromProto /
PubKeyFromTypeAndBytes, with the typed length/unsupported errors).

The wire form is the tmproto.PublicKey oneof — field 1 = ed25519
bytes, field 2 = secp256k1 bytes, field 3 = bls12381 bytes — exactly
what utils/codec.encode_pubkey emits; this module is the *typed* API
layer over it with the reference's error taxonomy.
"""

from __future__ import annotations

from ..utils import codec as _codec
from .keys import (
    BLS12381_KEY_TYPE,
    ED25519_KEY_TYPE,
    SECP256K1_KEY_TYPE,
    PubKey,
    pubkey_from_type_bytes,
)

_KEY_LENS = {
    ED25519_KEY_TYPE: 32,
    SECP256K1_KEY_TYPE: 33,
    BLS12381_KEY_TYPE: 48,
}


class ErrUnsupportedKey(ValueError):
    def __init__(self, key_type: str):
        self.key_type = key_type
        super().__init__(f"unsupported key type: {key_type!r}")


class ErrInvalidKeyLen(ValueError):
    def __init__(self, key_type: str, got: int, want: int):
        self.key_type, self.got, self.want = key_type, got, want
        super().__init__(
            f"invalid {key_type} key length: got {got}, want {want}"
        )


def pubkey_to_proto(pk: PubKey) -> bytes:
    """PubKeyToProto: typed key -> tmproto.PublicKey bytes."""
    try:
        return _codec.encode_pubkey(pk)
    except ValueError:
        raise ErrUnsupportedKey(
            getattr(pk, "type_", str(type(pk)))
        ) from None


def pubkey_from_proto(b: bytes) -> PubKey:
    """PubKeyFromProto: tmproto.PublicKey bytes -> typed key."""
    try:
        return _codec.decode_pubkey(b)
    except ValueError:
        raise ErrUnsupportedKey("<unknown oneof>") from None


def pubkey_from_type_and_bytes(key_type: str, raw: bytes) -> PubKey:
    """PubKeyFromTypeAndBytes with the reference's error taxonomy."""
    want = _KEY_LENS.get(key_type)
    if want is None:
        raise ErrUnsupportedKey(key_type)
    if len(raw) != want:
        raise ErrInvalidKeyLen(key_type, len(raw), want)
    return pubkey_from_type_bytes(key_type, raw)
