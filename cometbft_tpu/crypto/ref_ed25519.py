"""Pure-Python reference ed25519 (RFC 8032 + ZIP-215 semantics).

This is the *correctness oracle* for the TPU kernel in
``cometbft_tpu.ops.ed25519`` — slow big-int arithmetic, bit-for-bit
well-defined.  The reference framework's production verifier
(curve25519-voi, see reference crypto/ed25519/ed25519.go:10-31) uses
ZIP-215 verification semantics:

  * non-canonical point encodings (y >= p) are ACCEPTED (y reduced mod p),
  * small-order / mixed-order points are accepted,
  * x = 0 with sign bit 1 is accepted (x := -0 = 0),
  * S must be canonical (S < L),
  * the *cofactored* equation  [8][S]B = [8]R + [8][h]A  is checked.

Signing follows RFC 8032 exactly (deterministic nonce).
"""

from __future__ import annotations

import hashlib
import os

__all__ = [
    "P", "L", "D", "BASE",
    "sign", "verify_zip215", "public_from_seed", "point_decompress",
    "point_compress", "point_add", "point_mul", "point_equal", "sc_reduce",
]

# Field prime and group order.
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493

def _inv(x: int) -> int:
    return pow(x, P - 2, P)

# Twisted Edwards curve: -x^2 + y^2 = 1 + d x^2 y^2
D = (-121665 * _inv(121666)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# Points are extended homogeneous coordinates (X, Y, Z, T), x=X/Z y=Y/Z xy=T/Z.
IDENTITY = (0, 1, 1, 0)


def point_add(p, q):
    # add-2008-hwcd-3; complete for a = -1, d non-square.
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = (B - A) % P, (Dd - C) % P, (Dd + C) % P, (B + A) % P
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p):
    return point_add(p, p)


def point_neg(p):
    X, Y, Z, T = p
    return ((-X) % P, Y, Z, (-T) % P)


def point_mul(s: int, p):
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_add(p, p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def _recover_x(y: int, sign: int):
    """dalek-style decompression x from y; None if not on curve."""
    x2 = (y * y - 1) * _inv(D * y * y + 1) % P
    if x2 == 0:
        # x = 0; sign bit is ignored (-0 == 0), matching curve25519-dalek /
        # ZIP-215 semantics (RFC 8032 strict mode would reject sign=1 here).
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


# Base point: y = 4/5.
_by = 4 * _inv(5) % P
_bx = _recover_x(_by, 0)
BASE = (_bx, _by, 1, _bx * _by % P)


def point_decompress(s: bytes, zip215: bool = True):
    """Decompress a 32-byte point encoding. Returns extended coords or None."""
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= P:
        if not zip215:
            return None
        y %= P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def point_compress(p) -> bytes:
    X, Y, Z, _ = p
    zinv = _inv(Z)
    x, y = X * zinv % P, Y * zinv % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def sc_reduce(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def _hash(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def _clamp(a: int) -> int:
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def public_from_seed(seed: bytes) -> bytes:
    assert len(seed) == 32
    a = _clamp(int.from_bytes(hashlib.sha512(seed).digest()[:32], "little"))
    return point_compress(point_mul(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 deterministic signature; returns 64 bytes R || S."""
    h = hashlib.sha512(seed).digest()
    a = _clamp(int.from_bytes(h[:32], "little"))
    prefix = h[32:]
    A = point_compress(point_mul(a, BASE))
    r = _hash(prefix, msg) % L
    R = point_compress(point_mul(r, BASE))
    k = _hash(R, A, msg) % L
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify_zip215(public: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 verification: cofactored equation, liberal point decoding."""
    if len(public) != 32 or len(sig) != 64:
        return False
    A = point_decompress(public, zip215=True)
    if A is None:
        return False
    R = point_decompress(sig[:32], zip215=True)
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # S must be canonical
        return False
    k = _hash(sig[:32], public, msg) % L
    # [8]([S]B - [h]A - R) == identity
    sB = point_mul(s, BASE)
    kA = point_mul(k, A)
    diff = point_add(point_add(sB, point_neg(kA)), point_neg(R))
    eight = point_mul(8, diff)
    return point_equal(eight, IDENTITY)


def generate_seed() -> bytes:
    return os.urandom(32)
