"""XChaCha20-Poly1305 AEAD (reference crypto/xchacha20poly1305/
xchachapoly.go): the 24-byte-nonce extension of ChaCha20-Poly1305.

Construction (draft-irtf-cfrg-xchacha): derive a subkey with HChaCha20
over the first 16 nonce bytes, then run standard ChaCha20-Poly1305
(RFC 8439, via OpenSSL) with a 12-byte nonce of 4 zero bytes + the
remaining 8 nonce bytes. Only HChaCha20 runs in Python — it is a
fixed-cost KDF per seal/open, not a per-byte cost.
"""

from __future__ import annotations

import struct

from .chacha20poly1305 import ChaCha20Poly1305, InvalidTag

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_MASK = 0xFFFFFFFF


def _rotl(v: int, n: int) -> int:
    return ((v << n) & _MASK) | (v >> (32 - n))


def _quarter(s, a, b, c, d):
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = (s[a] + s[b]) & _MASK
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = (s[c] + s[d]) & _MASK
    s[b] = _rotl(s[b] ^ s[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """HChaCha20 KDF: 32-byte subkey from key + 16-byte nonce prefix
    (reference xchachapoly.go:131 hChaCha20Generic; differential
    vectors in the reference's vector_test.go)."""
    if len(key) != 32 or len(nonce16) != 16:
        raise ValueError("hchacha20: need 32-byte key, 16-byte nonce")
    s = list(_SIGMA) + list(struct.unpack("<8L", key)) + list(
        struct.unpack("<4L", nonce16)
    )
    for _ in range(10):
        _quarter(s, 0, 4, 8, 12)
        _quarter(s, 1, 5, 9, 13)
        _quarter(s, 2, 6, 10, 14)
        _quarter(s, 3, 7, 11, 15)
        _quarter(s, 0, 5, 10, 15)
        _quarter(s, 1, 6, 11, 12)
        _quarter(s, 2, 7, 8, 13)
        _quarter(s, 3, 4, 9, 14)
    return struct.pack("<8L", *(s[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


class XChaCha20Poly1305:
    """AEAD with 24-byte nonces (reference New/Seal/Open)."""

    def __init__(self, key: bytes):
        if len(key) != KEY_SIZE:
            raise ValueError("xchacha20poly1305: bad key length")
        self._key = bytes(key)

    @property
    def nonce_size(self) -> int:
        return NONCE_SIZE

    @property
    def overhead(self) -> int:
        return TAG_SIZE

    def _inner(self, nonce: bytes):
        if len(nonce) != NONCE_SIZE:
            raise ValueError("xchacha20poly1305: bad nonce length")
        sub = hchacha20(self._key, nonce[:16])
        return ChaCha20Poly1305(sub), b"\x00" * 4 + nonce[16:]

    def seal(self, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
        aead, n12 = self._inner(nonce)
        return aead.encrypt(n12, plaintext, aad or None)

    def open(self, nonce: bytes, ciphertext: bytes, aad: bytes = b"") -> bytes:
        """Raises ValueError on authentication failure."""
        aead, n12 = self._inner(nonce)
        try:
            return aead.decrypt(n12, ciphertext, aad or None)
        except InvalidTag:
            raise ValueError("xchacha20poly1305: message authentication failed")
