"""BLS12-381 signatures (feature-gated, pure Python).

Reference analog: crypto/bls12381 — real implementation behind the
`bls12381` build tag via the blst C library
(crypto/bls12381/key_bls12381.go:1), stub otherwise
(crypto/bls12381/key.go:1-30). Here the gate is the
COMETBFT_TPU_BLS12381 env var / `enable()` call: the key type
registers with the crypto registry only when enabled, so default
builds behave exactly like the reference's stub build.

Scheme: minimal-pubkey-size BLS (pubkeys in G1, signatures in G2),
matching the reference's choice. Hash-to-curve uses deterministic
try-and-increment (NOT the RFC 9380 SSWU map): this framework defines
its own wire/sign formats throughout, so self-consistency — not blst
byte-compatibility — is the requirement; the map is constant-free and
easy to audit. Verification: e(pk, H(m)) == e(G1, sig).

Pure-Python field towers (Fq, Fq2, Fq6, Fq12), Miller loop, final
exponentiation. Performance is irrelevant behind the gate (the
reference's default build has no BLS at all); validator-set BLS keys
are exercised by tests, not hot paths.
"""

from __future__ import annotations

import hashlib
import os
import secrets
from typing import List, Optional, Sequence, Tuple

# --- parameters ---------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
X_PARAM = -0xD201000000010000  # BLS parameter (negative)

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
G2_X0 = 0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8
G2_X1 = 0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E
G2_Y0 = 0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801
G2_Y1 = 0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE

KEY_TYPE = "bls12381"
PUBKEY_SIZE = 48  # compressed G1
SIG_SIZE = 96  # compressed G2


def enabled() -> bool:
    return os.environ.get("COMETBFT_TPU_BLS12381", "") not in ("", "0")


# --- Fq -----------------------------------------------------------------


def _inv(a: int, m: int = P) -> int:
    return pow(a, m - 2, m)


# Fq2 = Fq[u]/(u^2+1); elements (a, b) = a + b*u


def f2_add(x, y):
    return ((x[0] + y[0]) % P, (x[1] + y[1]) % P)


def f2_sub(x, y):
    return ((x[0] - y[0]) % P, (x[1] - y[1]) % P)


def f2_neg(x):
    return (-x[0] % P, -x[1] % P)


def f2_mul(x, y):
    a, b = x
    c, d = y
    ac = a * c
    bd = b * d
    return ((ac - bd) % P, ((a + b) * (c + d) - ac - bd) % P)


def f2_sqr(x):
    a, b = x
    return ((a + b) * (a - b) % P, 2 * a * b % P)


def f2_muls(x, s: int):
    return (x[0] * s % P, x[1] * s % P)


def f2_inv(x):
    a, b = x
    t = _inv((a * a + b * b) % P)
    return (a * t % P, -b * t % P)


def f2_conj(x):
    return (x[0], -x[1] % P)


F2_ONE = (1, 0)
F2_ZERO = (0, 0)


def f2_pow(x, e: int):
    out = F2_ONE
    base = x
    while e:
        if e & 1:
            out = f2_mul(out, base)
        base = f2_sqr(base)
        e >>= 1
    return out


def f2_sqrt(x):
    """Square root in Fq2 (p % 4 == 3 inside; standard complex method).
    Returns None if x is not a QR."""
    if x == F2_ZERO:
        return F2_ZERO
    a, b = x
    if b == 0:
        # sqrt in Fq if possible, else sqrt(-a)*u since u^2 = -1
        s = pow(a, (P + 1) // 4, P)
        if s * s % P == a:
            return (s, 0)
        s = pow(-a % P, (P + 1) // 4, P)
        if s * s % P == (-a) % P:
            return (0, s)
        return None
    # norm = a^2 + b^2; alpha = sqrt(norm)
    norm = (a * a + b * b) % P
    alpha = pow(norm, (P + 1) // 4, P)
    if alpha * alpha % P != norm:
        return None
    # x0^2 = (a + alpha)/2  (or (a - alpha)/2)
    inv2 = _inv(2)
    for al in (alpha, -alpha % P):
        x0sq = (a + al) * inv2 % P
        x0 = pow(x0sq, (P + 1) // 4, P)
        if x0 * x0 % P == x0sq and x0 != 0:
            x1 = b * _inv(2 * x0 % P) % P
            cand = (x0, x1)
            if f2_sqr(cand) == x:
                return cand
    return None


# Fq6 = Fq2[v]/(v^3 - xi), xi = 1 + u. Elements: (c0, c1, c2) of Fq2.

XI = (1, 1)


def _mul_xi(x):
    a, b = x
    return ((a - b) % P, (a + b) % P)


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(x, y):
    return tuple(f2_add(a, b) for a, b in zip(x, y))


def f6_sub(x, y):
    return tuple(f2_sub(a, b) for a, b in zip(x, y))


def f6_neg(x):
    return tuple(f2_neg(a) for a in x)


def f6_mul(x, y):
    a0, a1, a2 = x
    b0, b1, b2 = y
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(
        t0,
        _mul_xi(
            f2_sub(
                f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2)
            )
        ),
    )
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        _mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)),
        t1,
    )
    return (c0, c1, c2)


def f6_sqr(x):
    return f6_mul(x, x)


def f6_mul_by_v(x):
    a0, a1, a2 = x
    return (_mul_xi(a2), a0, a1)


def f6_inv(x):
    a0, a1, a2 = x
    c0 = f2_sub(f2_sqr(a0), _mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_inv(
        f2_add(
            f2_add(f2_mul(a0, c0), _mul_xi(f2_mul(a2, c1))),
            _mul_xi(f2_mul(a1, c2)),
        )
    )
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


# Fq12 = Fq6[w]/(w^2 - v). Elements: (c0, c1) of Fq6.

F12_ONE = (F6_ONE, F6_ZERO)


def f12_mul(x, y):
    a0, a1 = x
    b0, b1 = y
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_by_v(t1))
    c1 = f6_sub(
        f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1)
    )
    return (c0, c1)


def f12_sqr(x):
    return f12_mul(x, x)


def f12_inv(x):
    a0, a1 = x
    t = f6_inv(f6_sub(f6_sqr(a0), f6_mul_by_v(f6_sqr(a1))))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_conj(x):
    return (x[0], f6_neg(x[1]))


def f12_pow(x, e: int):
    if e < 0:
        return f12_pow(f12_inv(x), -e)
    out = F12_ONE
    base = x
    while e:
        if e & 1:
            out = f12_mul(out, base)
        base = f12_sqr(base)
        e >>= 1
    return out


# Frobenius on Fq2 coefficients of Fq12: gamma constants computed once.
# frob(c) for Fq2 is conjugation; multiply by xi^((p-1)k/6) powers.
def _frob_coeffs():
    # xi^((p-1)/6) in Fq2
    g = f2_pow(XI, (P - 1) // 6)
    gammas = [F2_ONE]
    for _ in range(5):
        gammas.append(f2_mul(gammas[-1], g))
    return gammas


_GAMMA = _frob_coeffs()


def f12_frobenius(x):
    """x -> x^p."""
    (a0, a1, a2), (b0, b1, b2) = x
    a0 = f2_conj(a0)
    a1 = f2_mul(f2_conj(a1), _GAMMA[2])
    a2 = f2_mul(f2_conj(a2), _GAMMA[4])
    b0 = f2_mul(f2_conj(b0), _GAMMA[1])
    b1 = f2_mul(f2_conj(b1), _GAMMA[3])
    b2 = f2_mul(f2_conj(b2), _GAMMA[5])
    return ((a0, a1, a2), (b0, b1, b2))


# --- curves -------------------------------------------------------------
# Jacobian-free affine arithmetic with None = infinity (performance is
# not a goal behind the gate; clarity is).


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * _inv(2 * y1 % P) % P
    else:
        lam = (y2 - y1) * _inv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def g1_neg(p):
    return None if p is None else (p[0], -p[1] % P)


def g1_mul(p, k: int):
    if k < 0:
        return g1_mul(g1_neg(p), -k)
    out = None
    while k:
        if k & 1:
            out = g1_add(out, p)
        p = g1_add(p, p)
        k >>= 1
    return out


def g1_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - (x * x * x + 4)) % P == 0


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(
            f2_muls(f2_sqr(x1), 3), f2_inv(f2_muls(y1, 2))
        )
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sub(f2_sqr(lam), x1), x2)
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def g2_neg(p):
    return None if p is None else (p[0], f2_neg(p[1]))


def g2_mul(p, k: int):
    if k < 0:
        return g2_mul(g2_neg(p), -k)
    out = None
    while k:
        if k & 1:
            out = g2_add(out, p)
        p = g2_add(p, p)
        k >>= 1
    return out


B2 = (4, 4)  # 4(1+u)


def g2_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == F2_ZERO


G1 = (G1_X, G1_Y)
G2 = ((G2_X0, G2_X1), (G2_Y0, G2_Y1))


# --- pairing ------------------------------------------------------------
# Strategy: embed G2 into E(Fq12) via the untwist map once, then run a
# textbook affine Miller loop entirely in Fq12. Slower than optimized
# line functions but free of twist-scaling subtleties (which matter
# here: aggregate verification compares products with different line
# counts, so lines must not be scaled by non-subfield constants).


def _f2_to_f12(a):
    return ((a, F2_ZERO, F2_ZERO), F6_ZERO)


def _fq_to_f12(a: int):
    return (((a % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


F12_W = (F6_ZERO, F6_ONE)  # w
_W_INV2 = f12_inv(f12_mul(F12_W, F12_W))  # w^-2
_W_INV3 = f12_inv(f12_mul(f12_mul(F12_W, F12_W), F12_W))  # w^-3


def _untwist(q):
    """E'(Fq2) -> E(Fq12): (x', y') -> (x' w^-2, y' w^-3)."""
    x, y = q
    return (
        f12_mul(_f2_to_f12(x), _W_INV2),
        f12_mul(_f2_to_f12(y), _W_INV3),
    )


def _f12_sub(x, y):
    return (f6_sub(x[0], y[0]), f6_sub(x[1], y[1]))


def _f12_eq(x, y):
    return _f12_sub(x, y) == (F6_ZERO, F6_ZERO)


def _line_f12(t, q, p12):
    """Line through t and q (E(Fq12) affine points) evaluated at p12 =
    (xp, yp) in Fq12; t == q means tangent. Returns Fq12."""
    (xt, yt), (xq, yq) = t, q
    xp, yp = p12
    if _f12_eq(xt, xq) and _f12_eq(yt, yq):
        num = f12_mul(_fq_to_f12(3), f12_mul(xt, xt))
        den = f12_mul(_fq_to_f12(2), yt)
    elif _f12_eq(xt, xq):
        return _f12_sub(xp, xt)  # vertical
    else:
        num = _f12_sub(yq, yt)
        den = _f12_sub(xq, xt)
    lam = f12_mul(num, f12_inv(den))
    return _f12_sub(_f12_sub(yp, yt), f12_mul(lam, _f12_sub(xp, xt)))


def _ec12_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    (x1, y1), (x2, y2) = p1, p2
    if _f12_eq(x1, x2):
        if _f12_eq(f12_mul(_fq_to_f12(-1), y1), y2) or _f12_eq(
            y1, f12_mul(_fq_to_f12(-1), y2)
        ):
            if not _f12_eq(y1, y2):
                return None
        if _f12_eq(y1, y2):
            lam = f12_mul(
                f12_mul(_fq_to_f12(3), f12_mul(x1, x1)),
                f12_inv(f12_mul(_fq_to_f12(2), y1)),
            )
        else:
            return None
    else:
        lam = f12_mul(_f12_sub(y2, y1), f12_inv(_f12_sub(x2, x1)))
    x3 = _f12_sub(_f12_sub(f12_mul(lam, lam), x1), x2)
    y3 = _f12_sub(f12_mul(lam, _f12_sub(x1, x3)), y1)
    return (x3, y3)


def miller_loop(q, p):
    """f_{|x|,Q}(P) with ate-pairing conventions; q in E'(Fq2) affine,
    p in E(Fq) affine. Conjugate at the end for the negative BLS
    parameter."""
    if q is None or p is None:
        return F12_ONE
    qq = _untwist(q)
    p12 = (_fq_to_f12(p[0]), _fq_to_f12(p[1]))
    t = qq
    f = F12_ONE
    for b in bin(abs(X_PARAM))[3:]:
        f = f12_mul(f12_sqr(f), _line_f12(t, t, p12))
        t = _ec12_add(t, t)
        if b == "1":
            f = f12_mul(f, _line_f12(t, qq, p12))
            t = _ec12_add(t, qq)
    if X_PARAM < 0:
        f = f12_conj(f)
    return f


def final_exponentiation(f):
    """f^((p^12-1)/r) — easy part explicit, hard part by direct
    exponentiation (slow but obviously correct)."""
    f1 = f12_mul(f12_conj(f), f12_inv(f))  # f^(p^6-1)
    f2 = f12_mul(f12_frobenius(f12_frobenius(f1)), f1)  # ^(p^2+1)
    e = (P**4 - P**2 + 1) // R
    return f12_pow(f2, e)


def pairing(q, p):
    """e(p in G1, q in E'(Fq2) r-torsion) -> Fq12."""
    return final_exponentiation(miller_loop(q, p))


# --- hashing + serialization -------------------------------------------


def hash_to_g2(msg: bytes, dst: bytes = b"COMETBFT-TPU-BLS-SIG-V1") -> Tuple:
    """Deterministic try-and-increment map to the r-torsion of G2 (not
    RFC 9380; see module docstring). Cofactor-cleared by scalar mul."""
    # G2 cofactor: h2 = (x^8 - 4x^7 + 5x^6 - 4x^4 + 6x^3 - 4x^2 - 4x + 13)/9
    x = X_PARAM
    h2 = (x**8 - 4 * x**7 + 5 * x**6 - 4 * x**4 + 6 * x**3 - 4 * x**2 - 4 * x + 13) // 9
    ctr = 0
    while True:
        seed = hashlib.sha256(dst + b"|" + ctr.to_bytes(4, "big") + b"|" + msg).digest()
        seed2 = hashlib.sha256(b"u1|" + seed).digest()
        x0 = int.from_bytes(seed + hashlib.sha256(b"x0" + seed).digest(), "big") % P
        x1 = int.from_bytes(seed2 + hashlib.sha256(b"x1" + seed2).digest(), "big") % P
        xc = (x0, x1)
        rhs = f2_add(f2_mul(f2_sqr(xc), xc), B2)
        y = f2_sqrt(rhs)
        if y is not None:
            # canonical sign: pick lexicographically smaller y
            if (y[1], y[0]) > (f2_neg(y)[1], f2_neg(y)[0]):
                y = f2_neg(y)
            pt = (xc, y)
            pt = g2_mul(pt, h2)  # clear cofactor into r-torsion
            if pt is not None:
                return pt
        ctr += 1


def g1_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 47)
    x, y = p
    flag = 0x80 | (0x20 if y > (P - 1) // 2 else 0)
    b = bytearray(x.to_bytes(48, "big"))
    b[0] |= flag
    return bytes(b)


def g1_decompress(b: bytes):
    if len(b) != 48:
        raise ValueError("bad G1 encoding length")
    if b[0] & 0x40:
        if b != bytes([0xC0] + [0] * 47):
            raise ValueError("bad G1 infinity encoding")
        return None
    if not b[0] & 0x80:
        raise ValueError("uncompressed G1 not supported")
    sign = bool(b[0] & 0x20)
    x = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (x * x * x + 4) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("G1 x not on curve")
    if (y > (P - 1) // 2) != sign:
        y = -y % P
    pt = (x, y)
    if g1_mul(pt, R) is not None:
        raise ValueError("G1 point not in r-torsion")
    return pt


def g2_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0] + [0] * 95)
    (x0, x1), (y0, y1) = p
    flag = 0x80 | (0x20 if (y1, y0) > ((-y1) % P, (-y0) % P) else 0)
    b = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    b[0] |= flag
    return bytes(b)


def g2_decompress(b: bytes):
    if len(b) != 96:
        raise ValueError("bad G2 encoding length")
    if b[0] & 0x40:
        if b != bytes([0xC0] + [0] * 95):
            raise ValueError("bad G2 infinity encoding")
        return None
    if not b[0] & 0x80:
        raise ValueError("uncompressed G2 not supported")
    sign = bool(b[0] & 0x20)
    x1 = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:48], "big")
    x0 = int.from_bytes(b[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    xc = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sqr(xc), xc), B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    yneg = f2_neg(y)
    if ((y[1], y[0]) > (yneg[1], yneg[0])) != sign:
        y = yneg
    pt = (xc, y)
    if g2_mul(pt, R) is not None:
        raise ValueError("G2 point not in r-torsion")
    return pt


# --- scheme -------------------------------------------------------------


def keygen(seed: Optional[bytes] = None) -> Tuple[int, bytes]:
    """Returns (secret scalar, compressed pubkey)."""
    if seed is None:
        seed = secrets.token_bytes(32)
    sk = (
        int.from_bytes(
            hashlib.sha512(b"bls-keygen|" + seed).digest(), "big"
        )
        % (R - 1)
        + 1
    )
    return sk, g1_compress(g1_mul(G1, sk))


def sign(sk: int, msg: bytes) -> bytes:
    return g2_compress(g2_mul(hash_to_g2(msg), sk))


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        pk = g1_decompress(pubkey)
        s = g2_decompress(sig)
    except ValueError:
        return False
    if pk is None or s is None:
        return False
    h = hash_to_g2(msg)
    # e(pk, H(m)) == e(G1, sig)
    return pairing(h, pk) == pairing(s, G1)


def aggregate(sigs: Sequence[bytes]) -> bytes:
    acc = None
    for s in sigs:
        acc = g2_add(acc, g2_decompress(s))
    return g2_compress(acc)


def verify_aggregate(
    pubkeys: Sequence[bytes], msgs: Sequence[bytes], agg_sig: bytes
) -> bool:
    """Distinct-message aggregate verification:
    prod e(pk_i, H(m_i)) == e(G1, sig)."""
    if len(pubkeys) != len(msgs) or not pubkeys:
        return False
    try:
        s = g2_decompress(agg_sig)
        lhs = F12_ONE
        for pkb, m in zip(pubkeys, msgs):
            pk = g1_decompress(pkb)
            if pk is None:
                return False
            lhs = f12_mul(lhs, miller_loop(hash_to_g2(m), pk))
    except ValueError:
        return False
    return final_exponentiation(lhs) == pairing(s, G1)
