"""Multi-core host verification plane (docs/PERF.md §"Host verification
plane").

With no reachable accelerator the *host* pipeline is the hardware, and
the round-5 profile puts serial OpenSSL ed25519 verify at ~2/3 of the
replay wall (16.5 s of 25.8 s per 1500 blocks) on ONE core while the
rest idle. Signature verification dominating committee-based consensus
wall-clock is exactly the finding of "Performance of EdDSA and BLS
Signatures in Committee-Based Consensus" (arXiv 2302.00418); this
module is the host-side analog of that paper's dedicated verification
engine: verification lanes fan out in chunks over a persistent worker
pool, per-lane verdicts merge back in input order.

Tier selection follows the crypto dependency gate (crypto/_ossl.py):

- **thread tier** — when ed25519 verification reaches OpenSSL (the
  ``cryptography`` wheel or the ctypes ``_ossl`` bindings): both
  release the GIL for the duration of each EVP call, so plain threads
  scale with cores and the items never need pickling.
- **process tier** — when only the pure-Python reference
  implementation is available (it holds the GIL throughout): chunks
  are shipped to a process pool instead. Items are plain picklable
  tuples of frozen-dataclass keys and bytes.
- **serial tier** — pool creation failed (restricted container) or
  one usable core: verify on the calling thread, bit-identically.

Chunk size is auto-calibrated like crypto/batch.py's dispatch
calibration: a small benchmark at pool init measures the serial
per-item cost, chunk walls observed from real batches keep an EWMA of
it, and chunks are sized so each one amortizes the submit/merge
overhead (~target_ms of work) while still giving every worker a share
of mid-size batches.

Env knobs (all optional):
  GRAFT_VERIFY_WORKERS         worker count (default: os.cpu_count(), capped)
  GRAFT_VERIFY_TIER            thread | process | serial (force a tier)
  GRAFT_VERIFY_CHUNK_TARGET_MS per-chunk wall target (default 4.0)
  GRAFT_VERIFY_MIN_PARALLEL    batch size below which verify is serial
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

from ..trace import global_tracer

_MAX_WORKERS_CAP = 16
_MIN_CHUNK = 8
_DEFAULT_MIN_PARALLEL = 24
_DEFAULT_CHUNK_TARGET_S = 4e-3
_EWMA_ALPHA = 0.3


def _ed25519_releases_gil() -> bool:
    """True when ed25519 verification reaches OpenSSL (wheel or ctypes
    bindings) — both release the GIL during the EVP call, so the
    thread tier scales on cores. Pure-Python fallback holds the GIL
    throughout; the process tier is the only way to spread it."""
    from . import keys

    return bool(keys._HAVE_OSSL or keys._HAVE_CTYPES_OSSL)


def _disable_worker_tracing() -> None:
    """Process-pool child initializer: a fork-started worker inherits
    the parent's enabled process tracer, but its ring can never be
    read (it lives in the child) — keep the chunk path no-op there."""
    from ..trace import enable_global

    enable_global(False)


def _verify_chunk(items, tier: str = "?") -> Tuple[List[bool], float]:
    """Worker body (top-level so the process tier can pickle it):
    verify one chunk, returning (verdicts, serial wall) — the wall
    feeds the per-item EWMA that sizes future chunks.

    Fast path: the native extension (crypto/native_verify) verifies
    the whole chunk in ONE GIL-releasing call — the per-lane ctypes
    transitions otherwise convoy worker threads on the GIL and cap
    thread-tier scaling well below the core count. Fallback (no
    compiler / disabled): the bit-identical per-lane Python loop.

    Traced onto the process-wide ring (trace/global_tracer) with
    worker id + lane count + tier: worker subprocesses never enable
    the global tracer, so the process tier's children stay no-op and
    only the thread tier (shared ring) records chunk spans."""
    tr = global_tracer()
    sp = (
        tr.span(
            "crypto.verify_chunk",
            tid=threading.current_thread().name,
            lanes=len(items),
            tier=tier,
        )
        if tr.enabled
        else None
    )
    t0 = time.perf_counter()
    try:
        from . import native_verify

        oks = native_verify.verify_chunk(items)
    except Exception:  # pragma: no cover - defensive: never lose lanes
        oks = None
    if oks is None:
        oks = [pk.verify(msg, sig) for pk, msg, sig in items]
    wall = time.perf_counter() - t0
    if sp is not None:
        sp.end()
    return oks, wall


class PendingLanes:
    """In-flight parallel verify: per-lane verdicts behind a blocking
    ``result()``, merged back in input order. ``wall()`` reports the
    dispatch→completion wall recorded by the LAST chunk's done
    callback — immune to how long the caller overlaps host work
    before resolving (the same poisoning concern as the device
    calibration watcher, crypto/batch.py)."""

    __slots__ = (
        "_futures", "_engine", "_n", "_t0", "_done_t", "_left", "_lock",
    )

    def __init__(self, futures, engine, n: int) -> None:
        self._futures = futures  # [(start, future)]
        self._engine = engine
        self._n = n
        self._t0 = time.perf_counter()
        self._done_t: Optional[float] = None
        self._left = len(futures)
        self._lock = threading.Lock()
        for _, fut in futures:
            fut.add_done_callback(self._one_done)

    def _one_done(self, _fut) -> None:
        self._engine._chunk_done()
        with self._lock:
            self._left -= 1
            if self._left == 0:
                self._done_t = time.perf_counter()

    def wall(self) -> Optional[float]:
        """Dispatch→last-chunk-completion wall, or None while pending."""
        with self._lock:
            done = self._done_t
        return None if done is None else done - self._t0

    def result(self) -> List[bool]:
        oks: List[bool] = [False] * self._n
        for start, fut in self._futures:
            chunk_oks, chunk_wall = fut.result()
            oks[start : start + len(chunk_oks)] = chunk_oks
            self._engine._observe_chunk(len(chunk_oks), chunk_wall)
        with self._lock:
            if self._done_t is None:
                # futures notify waiters BEFORE running done
                # callbacks, so result() can unblock a beat before
                # the last _one_done fires; all work is done at this
                # point, so stamping now keeps wall() available to
                # the host-cost EWMA instead of dropping the sample
                self._done_t = time.perf_counter()
        return oks


class _ResolvedLanes:
    """Already-computed verdicts behind the PendingLanes interface
    (serial path / empty batch)."""

    __slots__ = ("_oks", "_wall")

    def __init__(self, oks: List[bool], wall: float) -> None:
        self._oks = oks
        self._wall = wall

    def wall(self) -> float:
        return self._wall

    def result(self) -> List[bool]:
        return self._oks


class ParallelVerifyEngine:
    """Persistent worker pool for (pubkey, msg, sig) verification.

    verify() is bit-identical to the serial per-item loop: every lane
    runs the exact same ``pk.verify(msg, sig)`` the serial backend
    runs, only distributed; verdict order always matches input order
    regardless of chunk size or worker count (differential-tested in
    tests/test_parallel_verify.py)."""

    def __init__(
        self,
        workers: Optional[int] = None,
        tier: Optional[str] = None,
        chunk_target_s: Optional[float] = None,
        min_parallel: Optional[int] = None,
    ) -> None:
        env = os.environ
        if workers is None:
            w = env.get("GRAFT_VERIFY_WORKERS")
            workers = int(w) if w else min(
                os.cpu_count() or 1, _MAX_WORKERS_CAP
            )
        self.workers = max(1, workers)
        if tier is None:
            tier = env.get("GRAFT_VERIFY_TIER")
        if tier is None:
            tier = "thread" if _ed25519_releases_gil() else "process"
        if self.workers <= 1:
            tier = "serial"
        assert tier in ("thread", "process", "serial"), tier
        self.tier = tier
        if chunk_target_s is None:
            chunk_target_s = (
                float(env.get("GRAFT_VERIFY_CHUNK_TARGET_MS", "4.0"))
                / 1e3
            )
        self._chunk_target_s = chunk_target_s
        if min_parallel is None:
            mp = env.get("GRAFT_VERIFY_MIN_PARALLEL")
            min_parallel = int(mp) if mp else _DEFAULT_MIN_PARALLEL
        self.min_parallel = min_parallel
        # serial per-item cost EWMA; seeded by the init benchmark on
        # first pool use (the ~80us/sig OpenSSL figure from
        # crypto/batch.py's calibration is the prior)
        self._per_item_s = 80e-6
        self._calibrated = False
        self._pool = None
        self._lock = threading.Lock()
        # dispatch backpressure telemetry (obs/queues.py registry):
        # chunks submitted but not yet completed, worst case since
        # start, and total chunks dispatched
        self.inflight_chunks = 0
        self.inflight_hwm = 0
        self.chunks_dispatched = 0

    # --- pool / calibration ------------------------------------------

    def _calibrate(self) -> None:
        """Init-time benchmark (like crypto/batch.py's dispatch
        calibration): measure the serial per-item verify cost with a
        synthetic keypair so the FIRST real batch already gets a
        sensible chunk size. Pure-Python tiers are slow per verify, so
        the sample is small; the EWMA keeps refining from real chunk
        walls either way."""
        try:
            from .keys import Ed25519PrivKey

            priv = Ed25519PrivKey.from_seed(b"\x5a" * 32)
            pk = priv.pub_key()
            msg = b"parallel-verify-calibration"
            sig = priv.sign(msg)
            reps = 6 if _ed25519_releases_gil() else 2
            best = None
            for _ in range(reps):
                t0 = time.perf_counter()
                if not pk.verify(msg, sig):  # pragma: no cover
                    return
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            if best and best > 0:
                self._per_item_s = best
        except Exception:  # pragma: no cover - calibration is advisory
            pass
        self._calibrated = True

    def _ensure_pool(self):
        with self._lock:
            if self.tier == "serial":
                return None
            if self._pool is None:
                if not self._calibrated:
                    self._calibrate()
                try:
                    if self.tier == "thread":
                        from concurrent.futures import ThreadPoolExecutor

                        self._pool = ThreadPoolExecutor(
                            max_workers=self.workers,
                            thread_name_prefix="pverify",
                        )
                    else:
                        from concurrent.futures import (
                            ProcessPoolExecutor,
                        )

                        # fork-started children inherit the parent's
                        # enabled global tracer; their rings are
                        # unreadable (and COW-duplicated), so the
                        # traced path must stay no-op there
                        self._pool = ProcessPoolExecutor(
                            max_workers=self.workers,
                            initializer=_disable_worker_tracing,
                        )
                except (OSError, ImportError, RuntimeError):
                    # restricted container (no fork / thread limit):
                    # degrade to bit-identical serial verification
                    self.tier = "serial"
                    self._pool = None
            return self._pool

    def _chunk_submitted(self, n: int = 1) -> None:
        with self._lock:
            self.chunks_dispatched += n
            self.inflight_chunks += n
            if self.inflight_chunks > self.inflight_hwm:
                self.inflight_hwm = self.inflight_chunks

    def _chunk_done(self) -> None:
        with self._lock:
            if self.inflight_chunks > 0:
                self.inflight_chunks -= 1

    def queue_stats(self) -> dict:
        """Dispatch-queue backpressure (obs/queues.py registry).
        inflight > workers just means chunks are queued on the pool —
        normal under load — so the worker count is NOT reported as
        "maxsize" (the health route treats depth >= maxsize as a
        degraded full queue)."""
        with self._lock:
            return {
                "depth": self.inflight_chunks,
                "high_watermark": self.inflight_hwm,
                "enqueued": self.chunks_dispatched,
                "dropped": 0,
                "workers": self.workers,
            }

    def _observe_chunk(self, n: int, wall: float) -> None:
        if n <= 0 or wall <= 0:
            return
        with self._lock:
            self._per_item_s += _EWMA_ALPHA * (
                wall / n - self._per_item_s
            )

    def chunk_size(self, n: int) -> int:
        """Chunk lanes so each chunk amortizes submit/merge overhead
        (~chunk_target_s of serial work), while mid-size batches still
        spread over every worker."""
        with self._lock:
            per = max(self._per_item_s, 1e-7)
        c = max(_MIN_CHUNK, int(self._chunk_target_s / per))
        # a batch that fits in < workers time-sized chunks still fans
        # out: never leave workers idle to honor the time target
        c = min(c, max(_MIN_CHUNK, -(-n // self.workers)))
        return c

    def stats(self) -> dict:
        with self._lock:
            per = self._per_item_s
        return {
            "tier": self.tier,
            "workers": self.workers,
            "per_item_us": round(per * 1e6, 1),
            "min_parallel": self.min_parallel,
        }

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # --- verification -------------------------------------------------

    def _serial(self, items) -> _ResolvedLanes:
        oks, wall = _verify_chunk(items, self.tier)
        self._observe_chunk(len(items), wall)
        return _ResolvedLanes(oks, wall)

    def verify_async(self, items: Sequence) -> "PendingLanes":
        """Enqueue the batch on the pool WITHOUT blocking on verdicts;
        the returned handle's ``result()`` blocks and merges. Small
        batches resolve eagerly (nothing to amortize)."""
        n = len(items)
        pool = self._ensure_pool() if n >= self.min_parallel else None
        if pool is None:
            return self._serial(items)
        if self.tier == "process":
            # chunks cross a pickle boundary: normalize to plain tuples
            items = [(pk, bytes(m), bytes(s)) for pk, m, s in items]
        chunk = self.chunk_size(n)
        tr = global_tracer()
        if tr.enabled:
            tr.instant(
                "crypto.batch.dispatch",
                tid="crypto",
                lanes=n,
                chunk=chunk,
                tier=self.tier,
                workers=self.workers,
            )
        futures = []
        try:
            for start in range(0, n, chunk):
                fut = pool.submit(
                    _verify_chunk, items[start : start + chunk],
                    self.tier,
                )
                self._chunk_submitted()
                futures.append((start, fut))
        except RuntimeError:
            # pool shut down underneath us (interpreter teardown):
            # fall back serially for the lanes not yet submitted —
            # verdicts must never be lost
            done = futures[-1][0] + chunk if futures else 0
            tail = self._serial(items[done:])
            pending = PendingLanes(futures, self, done)
            return _ResolvedLanes(
                pending.result() + tail.result(), tail.wall() or 0.0
            )
        return PendingLanes(futures, self, n)

    def verify(self, items: Sequence) -> List[bool]:
        """Order-stable parallel verify; blocking."""
        return self.verify_async(items).result()


# --- process-wide default engine ----------------------------------------

_ENGINE: Optional[ParallelVerifyEngine] = None
_ENGINE_LOCK = threading.Lock()


def engine() -> ParallelVerifyEngine:
    """The shared engine every host verification seam rides (the
    cpu-parallel batch backend and the TPU backend's host-routed
    lanes). Created lazily on first use."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = ParallelVerifyEngine()
        return _ENGINE


def dispatch_stats_if_running():
    """The shared engine's dispatch-queue telemetry, or None when no
    engine was ever built — the obs registry entry must never CREATE
    the engine (pool spin-up) just to report an idle plane."""
    with _ENGINE_LOCK:
        e = _ENGINE
    return None if e is None else e.queue_stats()


def set_engine(e: Optional[ParallelVerifyEngine]) -> None:
    """Swap the process-wide engine (tests / operator reconfig); the
    old pool keeps draining already-submitted chunks."""
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = e
