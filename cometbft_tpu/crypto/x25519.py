"""X25519 Diffie-Hellman (RFC 7748) with a three-tier dependency gate.

Same shape as chacha20poly1305.py: the ``cryptography`` wheel when
installed, else the system libcrypto via ctypes (crypto/_ossl.py),
else a pure-Python Montgomery ladder. Keys are raw 32-byte strings on
every backend so callers never touch backend object types. The ladder
is handshake-only cost (~1ms per exchange in pure Python) — bulk
traffic never goes through here.
"""

from __future__ import annotations

import os

try:  # pragma: no cover - exercised only where OpenSSL exists
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    HAVE_OPENSSL = True
except ImportError:
    HAVE_OPENSSL = False

_P = 2**255 - 19
_A24 = 121665
_BASE_U = 9


def _decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def _decode_u(u: bytes) -> int:
    b = bytearray(u)
    b[31] &= 127  # RFC 7748: mask the unused high bit
    return int.from_bytes(b, "little")


def _ladder(k: int, u: int) -> int:
    x1 = u % _P
    x2, z2 = 1, 0
    x3, z3 = x1, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * (z3 * z3 % _P) % _P
        x2 = aa * bb % _P
        z2 = e * ((aa + _A24 * e) % _P) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P - 2, _P) % _P


def scalar_mult(scalar: bytes, u: bytes) -> bytes:
    """Raw RFC 7748 X25519(k, u) -> 32 bytes."""
    if len(scalar) != 32 or len(u) != 32:
        raise ValueError("x25519: need 32-byte scalar and u-coordinate")
    return _ladder(_decode_scalar(scalar), _decode_u(u)).to_bytes(
        32, "little"
    )


def generate_private() -> bytes:
    """Fresh 32-byte private scalar (clamping happens at use)."""
    return os.urandom(32)


from . import _ossl as _ctossl

_HAVE_CTYPES_OSSL = (not HAVE_OPENSSL) and _ctossl.available()


def public(priv: bytes) -> bytes:
    """Public u-coordinate for a raw private scalar."""
    if HAVE_OPENSSL:
        return (
            X25519PrivateKey.from_private_bytes(priv)
            .public_key()
            .public_bytes(Encoding.Raw, PublicFormat.Raw)
        )
    if _HAVE_CTYPES_OSSL:
        return _ctossl.x25519_public(priv)
    return scalar_mult(priv, _BASE_U.to_bytes(32, "little"))


def shared(priv: bytes, peer_pub: bytes) -> bytes:
    """ECDH shared secret. Raises ValueError on an all-zero result
    (low-order peer point), matching the OpenSSL backend."""
    if HAVE_OPENSSL:
        return X25519PrivateKey.from_private_bytes(priv).exchange(
            X25519PublicKey.from_public_bytes(peer_pub)
        )
    if _HAVE_CTYPES_OSSL:
        return _ctossl.x25519_shared(priv, peer_pub)
    out = scalar_mult(priv, peer_pub)
    if out == b"\x00" * 32:
        raise ValueError("x25519: low-order point, zero shared secret")
    return out
