"""ChaCha20-Poly1305 AEAD (RFC 8439) with a three-tier dependency gate.

``ChaCha20Poly1305`` resolves to the best available backend:

1. the ``cryptography`` wheel's class, when that package is installed;
2. the system libcrypto through ctypes (crypto/_ossl.py) — same
   OpenSSL code, no wheel required (~30us per 1KB frame);
3. ``PureChaCha20Poly1305`` — numpy-vectorized ChaCha20 (uint32 lanes
   wrap mod 2**32 natively; four quarter-rounds per dispatch) plus
   big-int Poly1305, with a sequential-nonce keystream precompute
   cache tuned for SecretConnection's counter nonces (~80us per 1KB
   frame warm, ~1ms cold).

Differential tests pin the tiers against each other and against RFC
vectors (tests/test_crypto_fallback.py); the core permutation is
additionally cross-checked against the vector-tested HChaCha20 in
xchacha20poly1305.py. Only the AEAD surface this repo uses is
provided: 32-byte key, 12-byte nonce, optional AAD, 16-byte tag
appended to the ciphertext.
"""

from __future__ import annotations

import hmac
import struct

try:  # pragma: no cover - exercised only where OpenSSL exists
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )

    HAVE_OPENSSL = True
except ImportError:
    HAVE_OPENSSL = False

    class InvalidTag(Exception):
        """Authentication failure (API-compatible with
        cryptography.exceptions.InvalidTag)."""


KEY_SIZE = 32
NONCE_SIZE = 12
TAG_SIZE = 16

_POLY_P = (1 << 130) - 5
_POLY_R_MASK = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def _permute(init):
    """20-round ChaCha permutation + feed-forward over a (16, n)
    uint32 column-per-block state.

    The four quarter-rounds of each half-round are independent, so
    they run as ONE set of elementwise ops on (4, n) row bands
    (a=rows 0-3, b=4-7, c=8-11, d=12-15); the diagonal half rotates
    the b/c/d bands into place first. ~300 numpy dispatches per call
    instead of 960 — and the per-call cost is nearly independent of n,
    so callers batch as many blocks as possible (see _StreamCache)."""
    import numpy as np

    s = init.copy()
    a, b, c, d = s[0:4], s[4:8], s[8:12], s[12:16]  # in-place views

    def qr(a, b, c, d):
        a += b
        d ^= a
        d[:] = (d << np.uint32(16)) | (d >> np.uint32(16))
        c += d
        b ^= c
        b[:] = (b << np.uint32(12)) | (b >> np.uint32(20))
        a += b
        d ^= a
        d[:] = (d << np.uint32(8)) | (d >> np.uint32(24))
        c += d
        b ^= c
        b[:] = (b << np.uint32(7)) | (b >> np.uint32(25))

    for _ in range(10):
        qr(a, b, c, d)  # column round
        # diagonalize: band-local row rotations line up the diagonals
        b[:] = np.roll(b, -1, axis=0)
        c[:] = np.roll(c, -2, axis=0)
        d[:] = np.roll(d, -3, axis=0)
        qr(a, b, c, d)  # diagonal round
        b[:] = np.roll(b, 1, axis=0)
        c[:] = np.roll(c, 2, axis=0)
        d[:] = np.roll(d, 3, axis=0)
    s += init
    return s


def _init_state(key: bytes, nonces, counter: int, nblocks: int):
    """(16, len(nonces)*nblocks) init state: for each nonce, blocks
    counter..counter+nblocks-1."""
    import numpy as np

    n = len(nonces) * nblocks
    init = np.empty((16, n), dtype=np.uint32)
    init[0:4] = np.frombuffer(b"expand 32-byte k", dtype="<u4")[:, None]
    init[4:12] = np.frombuffer(key, dtype="<u4")[:, None]
    # 32-bit block counter wraps like the reference implementation
    ctr = (
        np.arange(counter, counter + nblocks, dtype=np.uint64) & 0xFFFFFFFF
    ).astype(np.uint32)
    init[12] = np.tile(ctr, len(nonces))
    for j, nc in enumerate(nonces):
        init[13:16, j * nblocks : (j + 1) * nblocks] = np.frombuffer(
            nc, dtype="<u4"
        )[:, None]
    return init


def chacha20_keystream(
    key: bytes, nonce: bytes, counter: int, length: int
) -> bytes:
    """``length`` bytes of RFC 8439 keystream starting at block
    ``counter``. numpy-vectorized over blocks."""
    if len(key) != KEY_SIZE or len(nonce) != NONCE_SIZE:
        raise ValueError("chacha20: need 32-byte key, 12-byte nonce")
    nblocks = (length + 63) // 64
    if nblocks == 0:
        return b""
    s = _permute(_init_state(key, [nonce], counter, nblocks))
    # each block serializes as 16 little-endian words
    return s.T.astype("<u4").tobytes()[:length]


def poly1305(key: bytes, msg: bytes) -> bytes:
    """RFC 8439 Poly1305 one-time MAC (16-byte tag)."""
    if len(key) != 32:
        raise ValueError("poly1305: need 32-byte one-time key")
    r = int.from_bytes(key[:16], "little") & _POLY_R_MASK
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        acc = (
            (acc + int.from_bytes(block, "little") + (1 << (8 * len(block))))
            * r
            % _POLY_P
        )
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _mac_data(aad: bytes, ct: bytes) -> bytes:
    def pad16(b: bytes) -> bytes:
        return b"\x00" * (-len(b) % 16)

    return (
        aad
        + pad16(aad)
        + ct
        + pad16(ct)
        + struct.pack("<QQ", len(aad), len(ct))
    )


# The dominant fallback consumer is SecretConnection, whose nonces are
# per-direction little-endian message counters and whose frames are a
# fixed 1024 bytes: once two successive nonces arrive we precompute
# keystreams for a growing window of FUTURE nonces in one numpy call,
# amortizing the fixed ~1ms permutation-dispatch cost across frames.
# Random-access nonce users (XChaCha's fresh per-seal subkey objects)
# never trigger the batch and pay single-shot cost only.
_SEQ_BLOCKS = 17  # otk block + 16 blocks = one 1024B frame
_MAX_BATCH = 48


class _StreamCache:
    def __init__(self, key: bytes):
        self.key = key
        self.entries = {}  # nonce -> 17*64B keystream (otk first)
        self.last = None  # int of last requested nonce
        self.batch = 4

    def take(self, nonce: bytes):
        cur = int.from_bytes(nonce, "little")
        sequential = self.last is not None and cur == self.last + 1
        self.last = cur
        ent = self.entries.pop(nonce, None)
        if ent is not None:
            return ent
        count = 1
        if sequential:
            count = self.batch
            self.batch = min(self.batch * 2, _MAX_BATCH)
        nonces = [
            ((cur + i) % (1 << 96)).to_bytes(12, "little")
            for i in range(count)
        ]
        s = _permute(_init_state(self.key, nonces, 0, _SEQ_BLOCKS))
        raw = s.T.astype("<u4").tobytes()
        per = _SEQ_BLOCKS * 64
        for i, nc in enumerate(nonces[1:], start=1):
            self.entries[nc] = raw[i * per : (i + 1) * per]
        if len(self.entries) > 4 * _MAX_BATCH:  # runaway guard
            self.entries.clear()
        return raw[:per]


class PureChaCha20Poly1305:
    """API-compatible subset of
    cryptography.hazmat.primitives.ciphers.aead.ChaCha20Poly1305.
    Always importable (differential tests pin it against OpenSSL);
    exported as ``ChaCha20Poly1305`` only when OpenSSL is absent."""

    def __init__(self, key: bytes):
        if len(key) != KEY_SIZE:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        self._cache = _StreamCache(self._key)

    def _streams(self, nonce: bytes, length: int):
        """(one-time poly key, data keystream) for this nonce."""
        if len(nonce) != NONCE_SIZE:
            # match the OpenSSL backends exactly — the cache path would
            # otherwise silently zero-extend a short nonce
            raise ValueError("ChaCha20Poly1305 nonce must be 12 bytes")
        if length <= (_SEQ_BLOCKS - 1) * 64:
            ks = self._cache.take(nonce)
            return ks[:32], ks[64 : 64 + length]
        # oversize: one contiguous run (block 0 = poly key, 1.. = data)
        ks = chacha20_keystream(self._key, nonce, 0, 64 + length)
        return ks[:32], ks[64:]

    @staticmethod
    def _xor(data: bytes, ks: bytes) -> bytes:
        import numpy as np

        return (
            np.frombuffer(data, dtype=np.uint8)
            ^ np.frombuffer(ks, dtype=np.uint8)
        ).tobytes()

    def encrypt(
        self, nonce: bytes, data: bytes, associated_data=None
    ) -> bytes:
        aad = associated_data or b""
        otk, ks = self._streams(nonce, len(data))
        ct = self._xor(data, ks)
        return ct + poly1305(otk, _mac_data(aad, ct))

    def decrypt(
        self, nonce: bytes, data: bytes, associated_data=None
    ) -> bytes:
        if len(data) < TAG_SIZE:
            raise InvalidTag("ciphertext shorter than tag")
        aad = associated_data or b""
        ct, tag = data[:-TAG_SIZE], data[-TAG_SIZE:]
        otk, ks = self._streams(nonce, len(ct))
        if not hmac.compare_digest(tag, poly1305(otk, _mac_data(aad, ct))):
            raise InvalidTag("poly1305 tag mismatch")
        return self._xor(ct, ks)


if not HAVE_OPENSSL:
    # middle tier: system libcrypto via ctypes; pure numpy last
    from . import _ossl as _ctossl

    if _ctossl.available():
        ChaCha20Poly1305 = _ctossl.OsslChaCha20Poly1305  # noqa: F811
    else:
        ChaCha20Poly1305 = PureChaCha20Poly1305  # noqa: F811
