"""Multi-chip mesh batch-verification backend (registered as "mesh").

Promotes the MULTICHIP_r04/r05 dryrun path into a first-class,
config-selectable backend (config.CryptoConfig.batch_backend =
"mesh"): ed25519 lanes are sharded across every local device through
the shard_map/PartitionSpec program ops/ed25519 builds over
parallel/mesh.make_mesh — signature lanes are the data axis, each
device verifies its slice, verdicts gather back in lane order
(docs/PERF.md "Unified verify scheduler", SNIPPETS pjit pattern).

Degradable contract (the common path on a throttled 2-vCPU box with
no mesh): when fewer than two devices materialize — or the device
dispatch itself fails — the batch verifies on the cpu-parallel host
plane instead, bit-identically and WITHOUT wedging. Selecting "mesh"
is therefore always safe; it means "shard when you can, host
otherwise", and the degrade is visible (``LAST_MESH`` + scheduler
``degraded`` counter + the bench verify-sched leg's structured
record).

Unlike the "tpu" backend there is no calibration gate: the operator
explicitly chose sharded dispatch, so any eligible batch (>= the
_MIN_TPU_BATCH floor, set_min_tpu_batch(1) forces) goes to the mesh.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..utils.log import get_logger
from .batch import (
    BatchVerifier,
    ResolvedVerdicts,
    _PendingVerdicts,
)
from . import batch as crypto_batch
from .keys import Ed25519PubKey, PubKey

_log = get_logger("crypto.mesh")

_DEVICES: Optional[int] = None
_DEVICES_LOCK = threading.Lock()

# Introspection: how the last mesh-backend verify dispatched
# (tests + the bench verify-sched leg's parity gate).
LAST_MESH = {"path": None, "n": 0, "devices": 0}


def mesh_devices(refresh: bool = False) -> int:
    """Local device count (cached — jax enumeration is not free), or
    0 when the backend cannot initialize. A mesh exists when > 1."""
    global _DEVICES
    with _DEVICES_LOCK:
        if _DEVICES is None or refresh:
            try:
                import jax

                _DEVICES = len(jax.devices())
            except Exception:  # pragma: no cover - uninitializable
                _DEVICES = 0
        return _DEVICES


class MeshBatchVerifier(BatchVerifier):
    """Shards ed25519 lanes over the device mesh; degrades to the
    cpu-parallel host plane when no mesh materializes. Verdict parity
    with CpuBatchVerifier is differential-tested
    (tests/test_verify_scheduler.py) and gated in-bench."""

    def __init__(self) -> None:
        self.items: List[Tuple[PubKey, bytes, bytes]] = []

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        self.items.append((pk, msg, sig))

    def __len__(self) -> int:
        return len(self.items)

    def _split(self):
        ed_idx, ed_items, other_idx = [], [], []
        for i, (pk, msg, sig) in enumerate(self.items):
            if isinstance(pk, Ed25519PubKey):
                ed_idx.append(i)
                ed_items.append((msg, pk.key_bytes, sig))
            else:
                other_idx.append(i)
        return ed_idx, ed_items, other_idx

    def _use_mesh(self, n_ed: int) -> bool:
        devices = mesh_devices()
        floor = max(crypto_batch._MIN_TPU_BATCH, 1)
        use = devices > 1 and n_ed >= floor
        LAST_MESH.update(
            path="mesh" if use else "host", n=n_ed, devices=devices
        )
        return use

    def _host(self, oks, ed_idx, other_idx) -> Tuple[bool, List[bool]]:
        if ed_idx:
            from .parallel_verify import engine

            verdicts = engine().verify([self.items[i] for i in ed_idx])
            for i, v in zip(ed_idx, verdicts):
                oks[i] = v
        for i in other_idx:
            pk, msg, sig = self.items[i]
            oks[i] = pk.verify(msg, sig)
        return all(oks) and bool(oks), oks

    def verify(self) -> Tuple[bool, List[bool]]:
        ed_idx, ed_items, other_idx = self._split()
        oks = [False] * len(self.items)
        if self._use_mesh(len(ed_items)):
            try:
                from ..ops import ed25519 as _ed

                verdicts = _ed.verify_batch(ed_items)
            except Exception as e:
                _log.error(
                    "mesh dispatch failed; host degrade",
                    err=repr(e),
                    lanes=len(ed_items),
                )
                LAST_MESH["path"] = "host-degraded"
                return self._host(oks, ed_idx, other_idx)
            for i, v in zip(ed_idx, verdicts):
                oks[i] = bool(v)
            for i in other_idx:
                pk, msg, sig = self.items[i]
                oks[i] = pk.verify(msg, sig)
            return all(oks) and bool(oks), oks
        return self._host(oks, ed_idx, other_idx)

    def verify_async(self):
        ed_idx, ed_items, other_idx = self._split()
        oks = [False] * len(self.items)
        if not self._use_mesh(len(ed_items)):
            return ResolvedVerdicts(*self._host(oks, ed_idx, other_idx))
        try:
            from ..ops import ed25519 as _ed

            handle = _ed.verify_batch_async(ed_items)
        except Exception as e:
            _log.error(
                "mesh async dispatch failed; host degrade",
                err=repr(e),
                lanes=len(ed_items),
            )
            LAST_MESH["path"] = "host-degraded"
            return ResolvedVerdicts(*self._host(oks, ed_idx, other_idx))
        for i in other_idx:
            pk, msg, sig = self.items[i]
            oks[i] = pk.verify(msg, sig)
        return _PendingVerdicts(handle, ed_idx, oks)
