"""ctypes bindings to the SYSTEM libcrypto (OpenSSL >= 1.1.1).

Middle tier of the crypto dependency gate. Preference order everywhere
in this package:

1. the ``cryptography`` wheel (when installed) — the usual fast path;
2. **this module** — the same OpenSSL primitives through ctypes
   against the system ``libcrypto.so``, for containers that have the
   library but not the wheel (no pip allowed);
3. the pure-Python/numpy implementations (ref_ed25519,
   chacha20poly1305.Pure*, x25519 ladder) — always available, slow.

Only the narrow EVP surface this repo needs is bound: Ed25519
sign/verify/public-from-seed, X25519 derive, ChaCha20-Poly1305 AEAD.
Every binding sets argtypes/restype explicitly (size_t truncation on
64-bit is the classic ctypes bug) and frees its EVP objects. All
functions raise/return exactly like their package-backed twins so
callers cannot tell the tiers apart; differential tests pin this
module against the pure implementations (tests/test_crypto_fallback.py).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_EVP_PKEY_ED25519 = 1087  # NID_ED25519
_EVP_PKEY_X25519 = 1034  # NID_X25519
_CTRL_AEAD_SET_IVLEN = 0x9
_CTRL_AEAD_GET_TAG = 0x10
_CTRL_AEAD_SET_TAG = 0x11

_lib = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("crypto")
    candidates = [name] if name else []
    candidates += ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]
    for cand in candidates:
        if not cand:
            continue
        try:
            lib = ctypes.CDLL(cand)
        except OSError:
            continue
        try:
            _bind(lib)
        except AttributeError:
            continue  # too old: missing EVP raw-key / AEAD symbols
        _lib = lib
        return _lib
    return None


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    P = c.c_void_p
    S = c.c_size_t
    B = c.c_char_p
    lib.EVP_PKEY_new_raw_public_key.argtypes = [c.c_int, P, B, S]
    lib.EVP_PKEY_new_raw_public_key.restype = P
    lib.EVP_PKEY_new_raw_private_key.argtypes = [c.c_int, P, B, S]
    lib.EVP_PKEY_new_raw_private_key.restype = P
    lib.EVP_PKEY_get_raw_public_key.argtypes = [P, B, c.POINTER(S)]
    lib.EVP_PKEY_get_raw_public_key.restype = c.c_int
    lib.EVP_PKEY_free.argtypes = [P]
    lib.EVP_PKEY_free.restype = None
    lib.EVP_MD_CTX_new.restype = P
    lib.EVP_MD_CTX_free.argtypes = [P]
    lib.EVP_MD_CTX_free.restype = None
    lib.EVP_DigestVerifyInit.argtypes = [P, P, P, P, P]
    lib.EVP_DigestVerifyInit.restype = c.c_int
    lib.EVP_DigestVerify.argtypes = [P, B, S, B, S]
    lib.EVP_DigestVerify.restype = c.c_int
    lib.EVP_DigestSignInit.argtypes = [P, P, P, P, P]
    lib.EVP_DigestSignInit.restype = c.c_int
    lib.EVP_DigestSign.argtypes = [P, B, c.POINTER(S), B, S]
    lib.EVP_DigestSign.restype = c.c_int
    lib.EVP_PKEY_CTX_new.argtypes = [P, P]
    lib.EVP_PKEY_CTX_new.restype = P
    lib.EVP_PKEY_CTX_free.argtypes = [P]
    lib.EVP_PKEY_CTX_free.restype = None
    lib.EVP_PKEY_derive_init.argtypes = [P]
    lib.EVP_PKEY_derive_init.restype = c.c_int
    lib.EVP_PKEY_derive_set_peer.argtypes = [P, P]
    lib.EVP_PKEY_derive_set_peer.restype = c.c_int
    lib.EVP_PKEY_derive.argtypes = [P, B, c.POINTER(S)]
    lib.EVP_PKEY_derive.restype = c.c_int
    lib.EVP_CIPHER_CTX_new.restype = P
    lib.EVP_CIPHER_CTX_free.argtypes = [P]
    lib.EVP_CIPHER_CTX_free.restype = None
    lib.EVP_chacha20_poly1305.restype = P
    lib.EVP_CipherInit_ex.argtypes = [P, P, P, B, B, c.c_int]
    lib.EVP_CipherInit_ex.restype = c.c_int
    lib.EVP_CIPHER_CTX_ctrl.argtypes = [P, c.c_int, c.c_int, P]
    lib.EVP_CIPHER_CTX_ctrl.restype = c.c_int
    lib.EVP_CipherUpdate.argtypes = [P, B, c.POINTER(c.c_int), B, c.c_int]
    lib.EVP_CipherUpdate.restype = c.c_int
    lib.EVP_CipherFinal_ex.argtypes = [P, B, c.POINTER(c.c_int)]
    lib.EVP_CipherFinal_ex.restype = c.c_int


def available() -> bool:
    return _load() is not None


# --- ed25519 ------------------------------------------------------------


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """RFC 8032 (cofactorless) verify — the strict subset of ZIP-215;
    callers fall back to the liberal pure check on rejection, exactly
    like the package-backed path in keys.py."""
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_public_key(
        _EVP_PKEY_ED25519, None, pub, len(pub)
    )
    if not pkey:
        return False
    ctx = lib.EVP_MD_CTX_new()
    try:
        if lib.EVP_DigestVerifyInit(ctx, None, None, None, pkey) != 1:
            return False
        return (
            lib.EVP_DigestVerify(ctx, sig, len(sig), msg, len(msg)) == 1
        )
    finally:
        lib.EVP_MD_CTX_free(ctx)
        lib.EVP_PKEY_free(pkey)


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_ED25519, None, seed, len(seed)
    )
    if not pkey:
        raise ValueError("ed25519: bad private key")
    ctx = lib.EVP_MD_CTX_new()
    try:
        if lib.EVP_DigestSignInit(ctx, None, None, None, pkey) != 1:
            raise ValueError("ed25519: sign init failed")
        sig = ctypes.create_string_buffer(64)
        siglen = ctypes.c_size_t(64)
        if (
            lib.EVP_DigestSign(
                ctx, sig, ctypes.byref(siglen), msg, len(msg)
            )
            != 1
        ):
            raise ValueError("ed25519: sign failed")
        return sig.raw[: siglen.value]
    finally:
        lib.EVP_MD_CTX_free(ctx)
        lib.EVP_PKEY_free(pkey)


def _raw_public(pkey) -> bytes:
    lib = _load()
    out = ctypes.create_string_buffer(32)
    outlen = ctypes.c_size_t(32)
    if lib.EVP_PKEY_get_raw_public_key(pkey, out, ctypes.byref(outlen)) != 1:
        raise ValueError("get_raw_public_key failed")
    return out.raw[: outlen.value]


def ed25519_public(seed: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_ED25519, None, seed, len(seed)
    )
    if not pkey:
        raise ValueError("ed25519: bad private key")
    try:
        return _raw_public(pkey)
    finally:
        lib.EVP_PKEY_free(pkey)


# --- x25519 -------------------------------------------------------------


def x25519_public(priv: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_X25519, None, priv, len(priv)
    )
    if not pkey:
        raise ValueError("x25519: bad private key")
    try:
        return _raw_public(pkey)
    finally:
        lib.EVP_PKEY_free(pkey)


def x25519_shared(priv: bytes, peer_pub: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_X25519, None, priv, len(priv)
    )
    peer = lib.EVP_PKEY_new_raw_public_key(
        _EVP_PKEY_X25519, None, peer_pub, len(peer_pub)
    )
    if not pkey or not peer:
        lib.EVP_PKEY_free(pkey)
        lib.EVP_PKEY_free(peer)
        raise ValueError("x25519: bad key")
    ctx = lib.EVP_PKEY_CTX_new(pkey, None)
    try:
        if (
            lib.EVP_PKEY_derive_init(ctx) != 1
            or lib.EVP_PKEY_derive_set_peer(ctx, peer) != 1
        ):
            raise ValueError("x25519: derive init failed")
        out = ctypes.create_string_buffer(32)
        outlen = ctypes.c_size_t(32)
        if lib.EVP_PKEY_derive(ctx, out, ctypes.byref(outlen)) != 1:
            # OpenSSL refuses low-order results; match the wheel's error
            raise ValueError("x25519: low-order point, zero shared secret")
        return out.raw[: outlen.value]
    finally:
        lib.EVP_PKEY_CTX_free(ctx)
        lib.EVP_PKEY_free(peer)
        lib.EVP_PKEY_free(pkey)


# --- ChaCha20-Poly1305 --------------------------------------------------


class OsslChaCha20Poly1305:
    """API-compatible subset of the wheel's ChaCha20Poly1305, bound to
    the system libcrypto. One EVP context per operation (the contexts
    are not safely reusable across asyncio interleavings)."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        if _load() is None:  # pragma: no cover - guarded by callers
            raise RuntimeError("libcrypto unavailable")

    def _run(self, enc: int, nonce, data, aad, tag=None):
        from .chacha20poly1305 import InvalidTag

        lib = _load()
        ctx = lib.EVP_CIPHER_CTX_new()
        try:
            if (
                lib.EVP_CipherInit_ex(
                    ctx, lib.EVP_chacha20_poly1305(), None, None, None, enc
                )
                != 1
            ):
                raise RuntimeError("chacha20poly1305: init failed")
            lib.EVP_CIPHER_CTX_ctrl(
                ctx, _CTRL_AEAD_SET_IVLEN, len(nonce), None
            )
            if (
                lib.EVP_CipherInit_ex(
                    ctx, None, None, self._key, bytes(nonce), enc
                )
                != 1
            ):
                raise RuntimeError("chacha20poly1305: key/iv init failed")
            outl = ctypes.c_int(0)
            if aad:
                if (
                    lib.EVP_CipherUpdate(
                        ctx, None, ctypes.byref(outl), aad, len(aad)
                    )
                    != 1
                ):
                    raise RuntimeError("chacha20poly1305: aad failed")
            out = ctypes.create_string_buffer(len(data) or 1)
            if (
                lib.EVP_CipherUpdate(
                    ctx, out, ctypes.byref(outl), data, len(data)
                )
                != 1
            ):
                raise InvalidTag("chacha20poly1305: update failed")
            n = outl.value
            if not enc:
                lib.EVP_CIPHER_CTX_ctrl(
                    ctx,
                    _CTRL_AEAD_SET_TAG,
                    16,
                    ctypes.cast(
                        ctypes.c_char_p(tag), ctypes.c_void_p
                    ),
                )
            fin = ctypes.create_string_buffer(16)
            if lib.EVP_CipherFinal_ex(ctx, fin, ctypes.byref(outl)) != 1:
                raise InvalidTag("poly1305 tag mismatch")
            n += outl.value
            body = out.raw[:n]
            if enc:
                tagbuf = ctypes.create_string_buffer(16)
                lib.EVP_CIPHER_CTX_ctrl(
                    ctx,
                    _CTRL_AEAD_GET_TAG,
                    16,
                    ctypes.cast(tagbuf, ctypes.c_void_p),
                )
                return body + tagbuf.raw
            return body
        finally:
            lib.EVP_CIPHER_CTX_free(ctx)

    def encrypt(self, nonce, data, associated_data=None) -> bytes:
        return self._run(1, nonce, data, associated_data or b"")

    def decrypt(self, nonce, data, associated_data=None) -> bytes:
        from .chacha20poly1305 import InvalidTag

        if len(data) < 16:
            raise InvalidTag("ciphertext shorter than tag")
        return self._run(
            0, nonce, data[:-16], associated_data or b"", tag=data[-16:]
        )
