"""Host-side key API: ed25519 + secp256k1 key types, addresses, signing.

Mirrors the reference's ``crypto.PubKey/PrivKey`` interfaces
(reference crypto/crypto.go) with the same observable behavior:

- address = first 20 bytes of SHA-256(raw pubkey) (crypto/ed25519 and
  tmhash semantics),
- ed25519 signing is RFC 8032 (via the `cryptography`/OpenSSL backend,
  pure-python fallback for odd platforms),
- single-signature verification uses ZIP-215 semantics to match batch
  verification exactly (reference uses curve25519-voi ZIP-215 for both).

The TPU batch path lives in :mod:`cometbft_tpu.crypto.batch`.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass

from . import ref_ed25519 as _ref

try:
    from cryptography.hazmat.primitives import serialization as _ser
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _OsslPriv,
    )

    _HAVE_OSSL = True
except Exception:  # pragma: no cover
    _HAVE_OSSL = False

# middle tier: the system libcrypto through ctypes (crypto/_ossl.py)
# when the `cryptography` wheel is absent; pure python is last resort
from . import _ossl as _ctossl

_HAVE_CTYPES_OSSL = (not _HAVE_OSSL) and _ctossl.available()

ED25519_KEY_TYPE = "ed25519"
SECP256K1_KEY_TYPE = "secp256k1"

ADDRESS_LEN = 20


def address_from_pubkey_bytes(raw: bytes) -> bytes:
    return hashlib.sha256(raw).digest()[:ADDRESS_LEN]


@dataclass(frozen=True)
class PubKey:
    """Interface marker; concrete: Ed25519PubKey, Secp256k1PubKey."""

    key_bytes: bytes

    @property
    def type_(self) -> str:
        raise NotImplementedError

    def address(self) -> bytes:
        return address_from_pubkey_bytes(self.key_bytes)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        raise NotImplementedError

    def __bytes__(self) -> bytes:
        return self.key_bytes


# Constructed-OpenSSL-object cache: validator keys repeat massively
# (a 10k-block replay has ~150 distinct keys for ~1.5M verifies), and
# Ed25519PublicKey.from_public_bytes costs ~1.5x the hash of the vote
# itself (profile_replay r5). Only VALID constructions are cached;
# invalid keys re-raise (and fall through to the liberal check) every
# time, which is the rare path.
_EVP_CACHE: dict = {}
_EVP_CACHE_MAX = 4096
_EVP_LOCK = threading.Lock()


def _openssl_pub(key_bytes: bytes):
    with _EVP_LOCK:
        evp = _EVP_CACHE.get(key_bytes)
    if evp is None:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        evp = Ed25519PublicKey.from_public_bytes(key_bytes)
        # verification runs on worker threads (coalesce, statesync,
        # light proxy): eviction must not race — an escaped KeyError
        # here would silently demote the verify to the slow liberal
        # path via the caller's blanket except
        with _EVP_LOCK:
            while len(_EVP_CACHE) >= _EVP_CACHE_MAX:
                _EVP_CACHE.pop(next(iter(_EVP_CACHE)))
            _EVP_CACHE[key_bytes] = evp
    return evp


@dataclass(frozen=True)
class Ed25519PubKey(PubKey):
    @property
    def type_(self) -> str:
        return ED25519_KEY_TYPE

    def verify(self, msg: bytes, sig: bytes) -> bool:
        """ZIP-215 verification.

        Fast path: OpenSSL (accepts a strict subset of ZIP-215 — every
        honestly-generated signature). Only if OpenSSL rejects do we run
        the liberal pure-python cofactored check, so non-canonical /
        small-order edge cases still validate exactly like the TPU
        kernel and the reference's curve25519-voi."""
        if len(self.key_bytes) != 32 or len(sig) != 64:
            return False
        if _HAVE_OSSL:
            try:
                _openssl_pub(self.key_bytes).verify(sig, msg)
                return True
            except Exception:
                pass  # fall through to the liberal ZIP-215 check
        elif _HAVE_CTYPES_OSSL:
            try:
                if _ctossl.ed25519_verify(self.key_bytes, msg, sig):
                    return True
            except Exception:
                pass  # fall through to the liberal ZIP-215 check
        return _ref.verify_zip215(self.key_bytes, msg, sig)


@dataclass(frozen=True)
class Ed25519PrivKey:
    seed: bytes

    @classmethod
    def generate(cls) -> "Ed25519PrivKey":
        return cls(os.urandom(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "Ed25519PrivKey":
        assert len(seed) == 32
        return cls(seed)

    def pub_key(self) -> Ed25519PubKey:
        if _HAVE_OSSL:
            pk = _OsslPriv.from_private_bytes(self.seed).public_key()
            raw = pk.public_bytes(
                _ser.Encoding.Raw, _ser.PublicFormat.Raw
            )
        elif _HAVE_CTYPES_OSSL:
            raw = _ctossl.ed25519_public(self.seed)
        else:  # pragma: no cover
            raw = _ref.public_from_seed(self.seed)
        return Ed25519PubKey(raw)

    def sign(self, msg: bytes) -> bytes:
        if _HAVE_OSSL:
            return _OsslPriv.from_private_bytes(self.seed).sign(msg)
        if _HAVE_CTYPES_OSSL:
            return _ctossl.ed25519_sign(self.seed, msg)
        return _ref.sign(self.seed, msg)  # pragma: no cover

    def __bytes__(self) -> bytes:
        # 64-byte expanded form (seed || pubkey), matching the
        # reference's on-disk ed25519 private key layout.
        return self.seed + self.pub_key().key_bytes


# --- secp256k1 (CPU-only; mixed-curve sets fall back per split-batch) ---

_SECP_P = 2**256 - 2**32 - 977
_SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_SECP_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _secp_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2 and (y1 + y2) % _SECP_P == 0:
        return None
    if p == q:
        lam = (3 * x1 * x1) * pow(2 * y1, _SECP_P - 2, _SECP_P) % _SECP_P
    else:
        lam = (y2 - y1) * pow(x2 - x1, _SECP_P - 2, _SECP_P) % _SECP_P
    x3 = (lam * lam - x1 - x2) % _SECP_P
    y3 = (lam * (x1 - x3) - y1) % _SECP_P
    return (x3, y3)


def _secp_mul(k: int, p):
    r = None
    while k:
        if k & 1:
            r = _secp_add(r, p)
        p = _secp_add(p, p)
        k >>= 1
    return r


def _secp_decompress(raw: bytes):
    if len(raw) != 33 or raw[0] not in (2, 3):
        return None
    x = int.from_bytes(raw[1:], "big")
    if x >= _SECP_P:
        return None
    y2 = (pow(x, 3, _SECP_P) + 7) % _SECP_P
    y = pow(y2, (_SECP_P + 1) // 4, _SECP_P)
    if y * y % _SECP_P != y2:
        return None
    if (y & 1) != (raw[0] & 1):
        y = _SECP_P - y
    return (x, y)


@dataclass(frozen=True)
class Secp256k1PubKey(PubKey):
    """33-byte compressed SEC1 encoding, like the reference (dcrd)."""

    @property
    def type_(self) -> str:
        return SECP256K1_KEY_TYPE

    def verify(self, msg: bytes, sig: bytes) -> bool:
        """ECDSA verify; sig = 64 bytes r||s (reference-compatible),
        message is hashed with SHA-256. OpenSSL fast path (~100us, the
        mixed-curve host lane of the batch verifier rides this); the
        pure-python implementation remains as fallback + oracle."""
        if len(sig) != 64:
            return False
        try:
            from cryptography.hazmat.primitives import hashes as _h
            from cryptography.hazmat.primitives.asymmetric import ec as _ec
            from cryptography.hazmat.primitives.asymmetric.utils import (
                encode_dss_signature as _dss,
            )

            pub = _ec.EllipticCurvePublicKey.from_encoded_point(
                _ec.SECP256K1(), bytes(self.key_bytes)
            )
            der = _dss(
                int.from_bytes(sig[:32], "big"),
                int.from_bytes(sig[32:], "big"),
            )
            try:
                pub.verify(der, msg, _ec.ECDSA(_h.SHA256()))
                return True
            except Exception:
                return False
        except (ImportError, ValueError):
            pass  # fall through to the pure-python path
        pt = _secp_decompress(self.key_bytes)
        if pt is None:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < _SECP_N and 1 <= s < _SECP_N):
            return False
        z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % _SECP_N
        w = pow(s, _SECP_N - 2, _SECP_N)
        u1, u2 = z * w % _SECP_N, r * w % _SECP_N
        pt2 = _secp_add(_secp_mul(u1, _SECP_G), _secp_mul(u2, pt))
        if pt2 is None:
            return False
        return pt2[0] % _SECP_N == r


@dataclass(frozen=True)
class Secp256k1PrivKey:
    d: int

    @classmethod
    def generate(cls) -> "Secp256k1PrivKey":
        while True:
            d = int.from_bytes(os.urandom(32), "big")
            if 1 <= d < _SECP_N:
                return cls(d)

    def pub_key(self) -> Secp256k1PubKey:
        x, y = _secp_mul(self.d, _SECP_G)
        return Secp256k1PubKey(bytes([2 + (y & 1)]) + x.to_bytes(32, "big"))

    def sign(self, msg: bytes) -> bytes:
        """Deterministic-ish ECDSA (RFC6979-style nonce via HMAC-free
        hash chaining; low-s normalized), sig = r||s 64 bytes."""
        z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % _SECP_N
        k_seed = hashlib.sha256(
            self.d.to_bytes(32, "big") + hashlib.sha256(msg).digest()
        ).digest()
        ctr = 0
        while True:
            k = (
                int.from_bytes(
                    hashlib.sha256(k_seed + ctr.to_bytes(4, "big")).digest(),
                    "big",
                )
                % _SECP_N
            )
            ctr += 1
            if k == 0:
                continue
            pt = _secp_mul(k, _SECP_G)
            r = pt[0] % _SECP_N
            if r == 0:
                continue
            s = (z + r * self.d) * pow(k, _SECP_N - 2, _SECP_N) % _SECP_N
            if s == 0:
                continue
            if s > _SECP_N // 2:
                s = _SECP_N - s
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")


BLS12381_KEY_TYPE = "bls12381"


@dataclass(frozen=True)
class Bls12381PubKey(PubKey):
    """Feature-gated (reference crypto/bls12381 behind the `bls12381`
    build tag; stub otherwise). Construction fails unless
    COMETBFT_TPU_BLS12381 is set, mirroring the stub build's panic."""

    def __post_init__(self):
        from . import bls12381

        if not bls12381.enabled():
            raise NotImplementedError(
                "bls12381 support disabled; set COMETBFT_TPU_BLS12381=1"
            )

    @property
    def type_(self) -> str:
        return BLS12381_KEY_TYPE

    def verify(self, msg: bytes, sig: bytes) -> bool:
        from . import bls12381

        return bls12381.verify(self.key_bytes, msg, sig)


@dataclass(frozen=True)
class Bls12381PrivKey:
    sk: int

    @classmethod
    def generate(cls) -> "Bls12381PrivKey":
        from . import bls12381

        sk, _ = bls12381.keygen()
        return cls(sk)

    @classmethod
    def from_seed(cls, seed: bytes) -> "Bls12381PrivKey":
        from . import bls12381

        sk, _ = bls12381.keygen(seed)
        return cls(sk)

    def pub_key(self) -> Bls12381PubKey:
        from . import bls12381

        return Bls12381PubKey(
            bls12381.g1_compress(bls12381.g1_mul(bls12381.G1, self.sk))
        )

    def sign(self, msg: bytes) -> bytes:
        from . import bls12381

        return bls12381.sign(self.sk, msg)


def pubkey_from_type_bytes(type_: str, raw: bytes) -> PubKey:
    if type_ == ED25519_KEY_TYPE:
        return Ed25519PubKey(raw)
    if type_ == SECP256K1_KEY_TYPE:
        return Secp256k1PubKey(raw)
    if type_ == BLS12381_KEY_TYPE:
        return Bls12381PubKey(raw)
    raise ValueError(f"unknown key type {type_}")
