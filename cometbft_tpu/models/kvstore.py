"""kvstore: the universal fake application (reference abci/example/kvstore).

A replicated key=value store: txs are "key=value" bytes; state is a dict
with a deterministic app hash; supports validator-update txs
("val:pubkey_b64!power" in the reference — here "val:<hex pubkey>!<power>"),
queries, and snapshots over the full state. Used by every in-process
consensus/blocksync/statesync test.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List

from ..abci import types as abci
from ..crypto import merkle

VALIDATOR_TX_PREFIX = b"val:"


class KVStoreApplication(abci.Application):
    def __init__(
        self,
        persist_path: str = None,
        prove: bool = False,
        retain_height: int = 0,
        snapshot_store=None,
    ):
        self.state: Dict[bytes, bytes] = {}
        self.height = 0
        # app-driven pruning knob (ISSUE 17): Commit advertises
        # retain_height = height - retain_height so the node's
        # retention plane (store/retention.py) can exercise the
        # min-wins reconciliation. 0 = reference semantics (the app
        # allows no pruning).
        self.retain_height = retain_height
        # on-disk snapshot seam (statesync/snapshots.py): when set,
        # snapshots persist through the SnapshotStore instead of the
        # RAM-only dict — they survive restarts and a restarted node
        # can still seed joiners. None = reference RAM semantics.
        self.snapshot_store = snapshot_store
        # prove=True: the app hash becomes SHA-256(height || merkle
        # root over the sorted KV leaves) and Query(prove=True) returns
        # proof ops a light client can check against a verified AppHash
        # (crypto/merkle ProofRuntime; reference light/rpc/client.go).
        # Off by default: the flat legacy hash keeps existing chains
        # (incl. the cached bench corpus) byte-stable.
        self.prove = prove
        # reference abci/example/kvstore PersistentKVStoreApplication:
        # survive restarts so the handshake replay path is exercised
        self.persist_path = persist_path
        if persist_path:
            self._load_persisted()
        self.app_hash = self._compute_hash()
        self.staged: Dict[bytes, bytes] = {}
        self.val_updates: List[abci.ValidatorUpdate] = []
        self.snapshots: Dict[int, bytes] = {}
        self._restore_buf: List[bytes] = []
        self._restore_target = None
        # (block_height, type, validator_address, power, evidence_height)
        # tuples — the app-side slashing ledger
        self.misbehavior_seen: List[tuple] = []
        self.extensions_verified = 0  # accepted VerifyVoteExtension calls

    def _load_persisted(self) -> None:
        import os

        if not os.path.exists(self.persist_path):
            return
        with open(self.persist_path) as f:
            st = json.load(f)
        self.height = st["height"]
        self.state = {
            bytes.fromhex(k): bytes.fromhex(v)
            for k, v in st["state"].items()
        }

    def _persist(self) -> None:
        if not self.persist_path:
            return
        import os

        os.makedirs(os.path.dirname(self.persist_path), exist_ok=True)
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "height": self.height,
                    "state": {
                        k.hex(): v.hex() for k, v in self.state.items()
                    },
                },
                f,
            )
        os.replace(tmp, self.persist_path)

    # --- hashing ------------------------------------------------------
    #
    # The flat hash walks EVERY committed kv each block, which turns
    # quadratic over a long replay (10k blocks x growing state was
    # ~40% of the projected host pipeline — docs/PERF.md round-4
    # profile). The chunk cache keeps the per-key length-prefixed
    # encoding in a sorted list maintained incrementally, so the
    # per-block cost is the unavoidable hash updates plus O(delta log n)
    # bookkeeping — the digest itself is UNCHANGED byte for byte.

    @staticmethod
    def _chunk(k: bytes, v: bytes) -> bytes:
        return (
            len(k).to_bytes(4, "big") + k + len(v).to_bytes(4, "big") + v
        )

    def _chunks_for(self, state: Dict[bytes, bytes]):
        """Sorted (key, chunk) list for ``state``: cached for the
        committed state, and computed as a small sorted-overlay delta
        for finalize_block's prospective (staged) state. commit()
        promotes the overlay to the new committed cache."""
        import bisect

        cache = getattr(self, "_chunk_cache", None)
        if cache is None or cache[0] is not self.state:
            keys = sorted(self.state)
            chunks = [self._chunk(k, self.state[k]) for k in keys]
            cache = (self.state, keys, chunks)
            self._chunk_cache = cache
        if state is self.state:
            return cache[1], cache[2]
        keys, chunks = list(cache[1]), list(cache[2])
        for k in sorted(
            k for k in state if state[k] != self.state.get(k)
        ):
            i = bisect.bisect_left(keys, k)
            ch = self._chunk(k, state[k])
            if i < len(keys) and keys[i] == k:
                chunks[i] = ch
            else:
                keys.insert(i, k)
                chunks.insert(i, ch)
        for k in self.state.keys() - state.keys():  # deletions (unused)
            i = bisect.bisect_left(keys, k)
            if i < len(keys) and keys[i] == k:
                del keys[i]
                del chunks[i]
        self._chunk_cache_next = (state, keys, chunks)
        return keys, chunks

    def _hash_state(self, height: int, state: Dict[bytes, bytes], prove: bool):
        keys, chunks = self._chunks_for(state)
        if prove:
            root = merkle.hash_from_byte_slices(
                [
                    merkle.kv_leaf(k, state[k])
                    for k in keys
                ]
            )
            return hashlib.sha256(
                height.to_bytes(8, "big") + root
            ).digest()
        h = hashlib.sha256()
        h.update(height.to_bytes(8, "big"))
        for ch in chunks:
            h.update(ch)
        return h.digest()

    def _compute_hash(self) -> bytes:
        return self._hash_state(self.height, self.state, self.prove)

    # --- info/query ---------------------------------------------------

    def info(self, req):
        return abci.ResponseInfo(
            data=json.dumps({"size": len(self.state)}),
            version="kvstore-tpu-0.1",
            app_version=1,
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def query(self, req):
        if req.path == "/store" or req.path == "":
            v = self.state.get(req.data)
            proof_ops = b""
            if req.prove and self.prove:
                proof_ops = merkle.encode_proof_ops(
                    self._query_proof(req.data, v)
                )
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK if v is not None else 1,
                key=req.data,
                value=v or b"",
                height=self.height,
                proof_ops=proof_ops,
            )
        return abci.ResponseQuery(code=1, log=f"unknown path {req.path}")

    def _query_proof(self, key: bytes, value):
        """Proof-op chain for one committed key (or its absence):
        inclusion/absence against the sorted-KV merkle root, then the
        app-hash binding op (see crypto/merkle proof operators).

        The full proof-trail set is built once per committed height
        (state only changes at commit) and cached — per-query cost is
        then one bisect plus 1-2 proof encodings, not an O(n log n)
        tree rebuild."""
        import bisect

        from ..utils import proto

        cache = getattr(self, "_proof_cache", None)
        if cache is None or cache[0] != self.height:
            keys = sorted(self.state)
            _, proofs = merkle.proofs_from_byte_slices(
                [merkle.kv_leaf(k, self.state[k]) for k in keys]
            )
            cache = (self.height, keys, proofs)
            self._proof_cache = cache
        _, keys, proofs = cache

        def neighbor(i: int) -> bytes:
            return proto.field_message(
                1,
                proto.field_bytes(
                    1, merkle.encode_proof(proofs[i])
                )
                + proto.field_bytes(2, keys[i])
                + proto.field_bytes(3, self.state[keys[i]]),
            )

        if value is not None:
            idx = bisect.bisect_left(keys, key)
            first = merkle.ProofOp(
                merkle.OP_KV_VALUE,
                key,
                merkle.encode_proof(proofs[idx]),
            )
        else:
            pos = bisect.bisect_left(keys, key)
            nbs = b""
            if keys:
                if pos == 0:
                    nbs = neighbor(0)
                elif pos == len(keys):
                    nbs = neighbor(len(keys) - 1)
                else:
                    nbs = neighbor(pos - 1) + neighbor(pos)
            first = merkle.ProofOp(merkle.OP_KV_ABSENCE, key, nbs)
        app_op = merkle.ProofOp(
            merkle.OP_APP_HASH,
            b"",
            proto.field_varint(1, self.height),
        )
        return [first, app_op]

    # --- mempool ------------------------------------------------------

    @staticmethod
    def _valid_tx(tx: bytes) -> bool:
        if tx.startswith(VALIDATOR_TX_PREFIX):
            try:
                body = tx[len(VALIDATOR_TX_PREFIX) :]
                pk, power = body.split(b"!", 1)
                bytes.fromhex(pk.decode())
                int(power)
                return True
            except Exception:
                return False
        return b"=" in tx

    def check_tx(self, req):
        if not self._valid_tx(req.tx):
            return abci.ResponseCheckTx(code=1, log="invalid tx format")
        return abci.ResponseCheckTx(gas_wanted=1)

    # --- consensus ----------------------------------------------------

    def init_chain(self, req):
        self.height = req.initial_height - 1
        if req.app_state_bytes:
            st = json.loads(req.app_state_bytes)
            self.state = {
                bytes.fromhex(k): bytes.fromhex(v) for k, v in st.items()
            }
        self.app_hash = self._compute_hash()
        return abci.ResponseInitChain(app_hash=self.app_hash)

    def process_proposal(self, req):
        for tx in req.txs:
            if not self._valid_tx(tx):
                return abci.ResponseProcessProposal(
                    status=abci.PROCESS_PROPOSAL_REJECT
                )
        return abci.ResponseProcessProposal()

    # --- vote extensions (reference test/e2e/app shape) ---------------

    def extend_vote(self, req):
        """Deterministic extension content bound to (height, hash)."""
        return abci.ResponseExtendVote(
            vote_extension=b"ext|%d|" % req.height + req.hash[:8]
        )

    def verify_vote_extension(self, req):
        ok = req.vote_extension.startswith(b"ext|%d|" % req.height)
        self.extensions_verified += 1 if ok else 0
        return abci.ResponseVerifyVoteExtension(
            status=abci.VERIFY_VOTE_EXT_ACCEPT
            if ok
            else abci.VERIFY_VOTE_EXT_REJECT
        )

    def _exec_tx(self, tx: bytes) -> abci.ExecTxResult:
        if not self._valid_tx(tx):
            return abci.ExecTxResult(code=1, log="invalid tx")
        if tx.startswith(VALIDATOR_TX_PREFIX):
            body = tx[len(VALIDATOR_TX_PREFIX) :]
            pk, power = body.split(b"!", 1)
            self.val_updates.append(
                abci.ValidatorUpdate(
                    pub_key_type="ed25519",
                    pub_key_bytes=bytes.fromhex(pk.decode()),
                    power=int(power),
                )
            )
            return abci.ExecTxResult(
                events=[abci.Event("val_update", [("power", power.decode(), True)])]
            )
        k, v = tx.split(b"=", 1)
        self.staged[k] = v
        return abci.ExecTxResult(
            events=[
                abci.Event(
                    "app",
                    [("creator", "kvstore", True), ("key", k.decode(errors="replace"), True)],
                )
            ]
        )

    def finalize_block(self, req):
        self.staged = {}
        self.val_updates = []
        # app-side slashing record (reference e2e app): every
        # Misbehavior delivered by consensus is retained so the
        # offender's power is attributable/slashable from app state
        for mb in req.misbehavior:
            self.misbehavior_seen.append(
                (
                    req.height,
                    mb.type_,
                    bytes(mb.validator_address),
                    mb.validator_power,
                    mb.height,
                )
            )
        results = [self._exec_tx(tx) for tx in req.txs]
        # stage, compute prospective hash
        pending = dict(self.state)
        pending.update(self.staged)
        app_hash = self._hash_state(req.height, pending, self.prove)
        self._pending = (req.height, pending, app_hash)
        return abci.ResponseFinalizeBlock(
            tx_results=results,
            validator_updates=list(self.val_updates),
            app_hash=app_hash,
        )

    def commit(self):
        height, pending, app_hash = self._pending
        self.height = height
        self.state = pending
        self.app_hash = app_hash
        self.staged = {}
        # promote finalize's overlay chunks to the committed cache so
        # the per-block hash stays incremental across commits
        nxt = getattr(self, "_chunk_cache_next", None)
        if nxt is not None and nxt[0] is pending:
            self._chunk_cache = nxt
            self._chunk_cache_next = None
        if self.height % 10 == 0:
            self._take_snapshot()
        self._persist()
        return abci.ResponseCommit(
            retain_height=max(0, self.height - self.retain_height)
            if self.retain_height > 0
            else 0
        )

    # --- snapshots ----------------------------------------------------

    SNAPSHOT_CHUNK = 1024

    def _take_snapshot(self):
        blob = json.dumps(
            {
                "height": self.height,
                "state": {
                    k.hex(): v.hex() for k, v in sorted(self.state.items())
                },
            }
        ).encode()
        if self.snapshot_store is not None:
            # disk-backed seam: chunk size matches the wire chunking
            # so served chunks stay byte-identical to the RAM era
            self.snapshot_store.save(
                self.height, blob, format_=1,
                chunk_size=self.SNAPSHOT_CHUNK,
            )
            return
        self.snapshots[self.height] = blob
        while len(self.snapshots) > 4:
            del self.snapshots[min(self.snapshots)]

    def list_snapshots(self):
        if self.snapshot_store is not None:
            return self.snapshot_store.list_snapshots()
        out = []
        for h, blob in sorted(self.snapshots.items()):
            nchunks = (len(blob) + self.SNAPSHOT_CHUNK - 1) // self.SNAPSHOT_CHUNK
            out.append(
                abci.Snapshot(
                    height=h,
                    format=1,
                    chunks=nchunks,
                    hash=hashlib.sha256(blob).digest(),
                )
            )
        return out

    def load_snapshot_chunk(self, height, format_, chunk):
        if self.snapshot_store is not None:
            return self.snapshot_store.load_chunk(height, format_, chunk)
        blob = self.snapshots.get(height, b"")
        off = chunk * self.SNAPSHOT_CHUNK
        return blob[off : off + self.SNAPSHOT_CHUNK]

    def offer_snapshot(self, snapshot, app_hash):
        if snapshot.format != 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OFFER_SNAPSHOT_REJECT_FORMAT
            )
        self._restore_buf = []
        self._restore_target = (snapshot, app_hash)
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, index, chunk, sender):
        self._restore_buf.append(chunk)
        snapshot, app_hash = self._restore_target
        if len(self._restore_buf) == snapshot.chunks:
            blob = b"".join(self._restore_buf)
            if hashlib.sha256(blob).digest() != snapshot.hash:
                return abci.ResponseApplySnapshotChunk(
                    result=abci.APPLY_CHUNK_REJECT_SNAPSHOT
                )
            st = json.loads(blob)
            self.height = st["height"]
            self.state = {
                bytes.fromhex(k): bytes.fromhex(v)
                for k, v in st["state"].items()
            }
            self.app_hash = self._compute_hash()
        return abci.ResponseApplySnapshotChunk(result=abci.APPLY_CHUNK_ACCEPT)


class AppMempoolKVStore(KVStoreApplication):
    """kvstore variant owning its mempool (fork feature: InsertTx/ReapTxs,
    reference mempool/app_mempool.go)."""

    def __init__(self):
        super().__init__()
        self.pool: List[bytes] = []

    def insert_tx(self, tx: bytes) -> bool:
        if not self._valid_tx(tx) or tx in self.pool:
            return False
        self.pool.append(tx)
        return True

    def reap_txs(self, max_bytes: int, max_gas: int) -> List[bytes]:
        out, total = [], 0
        for tx in self.pool:
            if max_bytes >= 0 and total + len(tx) > max_bytes:
                break
            out.append(tx)
            total += len(tx)
        return out

    def commit(self):
        resp = super().commit()
        committed = set()
        for k, v in self.state.items():
            committed.add(k + b"=" + v)
        self.pool = [tx for tx in self.pool if tx not in committed]
        return resp
