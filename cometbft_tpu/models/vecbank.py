"""vecbank: the vectorized hot-state apply model (native finalize lane).

A replicated fixed-width account bank whose ``finalize_block`` applies
the WHOLE block against numpy array state instead of a per-tx Python
loop: txs are 16-byte transfer records ``(src u32, dst u32, amt u64)``
big-endian, state is one uint64 balance vector, and a block decodes
with ONE ``np.frombuffer`` over the joined tx bytes (no per-tx
``struct.unpack``) then applies as two scatter-adds (``np.add.at`` /
``np.subtract.at``) over the record batch. Balances wrap mod 2^64 — add/sub are then commutative,
so the batched application is order-independent and digest-identical
to the scalar per-tx loop (``scalar=True``), which stays the semantic
reference and the no-numpy fallback.

This is the apply-leg counterpart of state/native_finalize.py: where
the native pass removes the per-item HASH/ENCODE overhead of the
finalize path, this model removes the per-item STATE-APPLY overhead,
so ``bench.py finalize`` can show an end-to-end blocks/s ceiling for
the whole height loop rather than a crypto-only one (docs/PERF.md
"Native finalize lane"). The kvstore keeps its dict semantics as the
universal fake app; vecbank is the throughput app.

app_hash = SHA-256(height_8B_BE || balances as big-endian u64s) —
identical bytes from either mode, differential-tested in
tests/test_native_finalize.py.
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Optional

from ..abci import types as abci

TX_SIZE = 16  # >IIQ : src u32, dst u32, amt u64
_U64 = 1 << 64
# structured view of a transfer record — the vector path decodes the
# WHOLE block with one np.frombuffer over the joined tx bytes instead
# of a struct.unpack per tx
_REC_DTYPE = [("src", ">u4"), ("dst", ">u4"), ("amt", ">u8")]


def make_transfer(src: int, dst: int, amt: int) -> bytes:
    return struct.pack(">IIQ", src, dst, amt)


class VecBankApplication(abci.Application):
    """Account-bank app with a batch (vectorized) or per-tx (scalar)
    finalize apply — byte-identical app hashes either way."""

    def __init__(
        self,
        n_accounts: int = 1 << 14,
        initial_balance: int = 1_000_000,
        scalar: bool = False,
    ):
        self.n_accounts = n_accounts
        self.height = 0
        self.scalar = scalar
        self._np = None
        if not scalar:
            try:
                import numpy as np

                self._np = np
            except Exception:  # pragma: no cover - numpy is baked in
                self._np = None
        if self._np is not None:
            self.balances = self._np.full(
                n_accounts, initial_balance, dtype=self._np.uint64
            )
        else:
            self.balances = [initial_balance] * n_accounts
        self.app_hash = self._compute_hash(self.height, self.balances)
        self._pending = None
        self.applied_txs = 0

    # --- hashing ------------------------------------------------------

    def _compute_hash(self, height: int, balances) -> bytes:
        if self._np is not None:
            body = balances.astype(">u8").tobytes()
        else:
            body = b"".join(b.to_bytes(8, "big") for b in balances)
        return hashlib.sha256(
            struct.pack(">Q", height) + body
        ).digest()

    # --- tx decode/validate -------------------------------------------

    def _decode(self, tx: bytes):
        if len(tx) != TX_SIZE:
            return None
        src, dst, amt = struct.unpack(">IIQ", tx)
        if src >= self.n_accounts or dst >= self.n_accounts:
            return None
        return src, dst, amt

    # --- ABCI ---------------------------------------------------------

    def info(self, req):
        return abci.ResponseInfo(
            last_block_height=self.height,
            last_block_app_hash=self.app_hash if self.height else b"",
        )

    def init_chain(self, req):
        return abci.ResponseInitChain(app_hash=self.app_hash)

    def check_tx(self, req):
        return abci.ResponseCheckTx(
            code=0 if self._decode(req.tx) is not None else 1
        )

    def finalize_block(self, req):
        if self._np is not None and not self.scalar:
            return self._finalize_vector(req)
        return self._finalize_scalar(req)

    def _finalize_scalar(self, req):
        """The semantic reference (and no-numpy fallback): per-tx
        decode, per-tx result, sequential wraparound apply."""
        results: List[abci.ExecTxResult] = []
        decoded = []
        for tx in req.txs:
            rec = self._decode(tx)
            if rec is None:
                results.append(
                    abci.ExecTxResult(code=1, log="invalid transfer")
                )
            else:
                decoded.append(rec)
                results.append(abci.ExecTxResult())
        if self._np is not None:
            pending = self.balances.copy()
            np = self._np
            if decoded:
                recs = np.asarray(decoded, dtype=np.uint64)
                with np.errstate(over="ignore", under="ignore"):
                    np.subtract.at(
                        pending, recs[:, 0].astype(np.intp), recs[:, 2]
                    )
                    np.add.at(
                        pending, recs[:, 1].astype(np.intp), recs[:, 2]
                    )
        else:
            pending = list(self.balances)
            for src, dst, amt in decoded:
                pending[src] = (pending[src] - amt) % _U64
                pending[dst] = (pending[dst] + amt) % _U64
        app_hash = self._compute_hash(req.height, pending)
        self._pending = (req.height, pending, app_hash, len(decoded))
        return abci.ResponseFinalizeBlock(
            tx_results=results, app_hash=app_hash
        )

    def _finalize_vector(self, req):
        """The batch path: ONE np.frombuffer decode over the joined
        block, vectorized range validation, two scatter-adds.
        Wraparound add/sub mod 2^64 is commutative, so the batch is
        order-independent and digest-identical to the scalar loop."""
        np = self._np
        txs = req.txs
        n = len(txs)
        if n and all(len(t) == TX_SIZE for t in txs):
            recs = np.frombuffer(b"".join(txs), dtype=_REC_DTYPE)
            src = recs["src"].astype(np.intp)
            dst = recs["dst"].astype(np.intp)
            amt = recs["amt"].astype(np.uint64)
            valid = (src < self.n_accounts) & (dst < self.n_accounts)
            if not valid.all():
                src, dst, amt = src[valid], dst[valid], amt[valid]
        else:
            # odd-sized tx in the block: per-tx decode (the rare
            # path), batch apply below unchanged
            rows = [self._decode(tx) for tx in txs]
            valid = np.fromiter(
                (r is not None for r in rows), dtype=bool, count=n
            )
            kept = [r for r in rows if r is not None]
            arr = np.asarray(kept, dtype=np.uint64).reshape(-1, 3)
            src = arr[:, 0].astype(np.intp)
            dst = arr[:, 1].astype(np.intp)
            amt = arr[:, 2]
        n_valid = int(src.shape[0])
        pending = self.balances.copy()
        if n_valid:
            with np.errstate(over="ignore", under="ignore"):
                np.subtract.at(pending, src, amt)
                np.add.at(pending, dst, amt)
        app_hash = self._compute_hash(req.height, pending)
        self._pending = (req.height, pending, app_hash, n_valid)
        # result objects are value-only (read, encoded, never
        # mutated downstream): the all-valid block shares ONE ok
        # result instead of constructing n of them
        ok = abci.ExecTxResult()
        if n_valid == n:
            results = [ok] * n
        else:
            bad = abci.ExecTxResult(code=1, log="invalid transfer")
            results = [ok if v else bad for v in valid]
        return abci.ResponseFinalizeBlock(
            tx_results=results, app_hash=app_hash
        )

    def commit(self):
        if self._pending is not None:
            height, pending, app_hash, n = self._pending
            self.height = height
            self.balances = pending
            self.app_hash = app_hash
            self.applied_txs += n
            self._pending = None
        return abci.ResponseCommit()

    def query(self, req):
        """key = 4-byte big-endian account index -> 8-byte balance."""
        try:
            (idx,) = struct.unpack(">I", req.data)
        except struct.error:
            return abci.ResponseQuery(code=1, log="bad account key")
        if idx >= self.n_accounts:
            return abci.ResponseQuery(code=1, log="no such account")
        bal = int(self.balances[idx])
        return abci.ResponseQuery(
            code=0,
            key=req.data,
            value=bal.to_bytes(8, "big"),
            height=self.height,
        )


def make_block_txs(
    rng, n_txs: int, n_accounts: int, max_amt: int = 1000
) -> List[bytes]:
    """Deterministic transfer batch for tests/bench (rng = random.Random)."""
    return [
        make_transfer(
            rng.randrange(n_accounts),
            rng.randrange(n_accounts),
            rng.randrange(max_amt),
        )
        for _ in range(n_txs)
    ]
