"""JSON encoding of core types for the RPC layer.

Human-readable JSON (hex hashes/addresses, base64 txs — the reference's
conventions) PLUS lossless framework-native bytes: responses that feed
verification (light client, statesync) carry `*_b64` fields holding
the canonical codec encoding, so hashes recompute exactly on the
client side without a second JSON-canonicalisation scheme."""

from __future__ import annotations

import base64
from typing import Any, Dict, Optional

from .. import types as T
from ..abci.types import attr_kvi
from ..utils import codec


def b64(b: bytes) -> str:
    return base64.b64encode(bytes(b)).decode()


def unb64(s: str) -> bytes:
    return base64.b64decode(s)


def hexb(b) -> str:
    return bytes(b).hex().upper()


def header_json(h: T.Header) -> Dict[str, Any]:
    return {
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time_ns": str(h.time_ns),
        "last_block_id": block_id_json(h.last_block_id),
        "last_commit_hash": hexb(h.last_commit_hash),
        "data_hash": hexb(h.data_hash),
        "validators_hash": hexb(h.validators_hash),
        "next_validators_hash": hexb(h.next_validators_hash),
        "consensus_hash": hexb(h.consensus_hash),
        "app_hash": hexb(h.app_hash),
        "last_results_hash": hexb(h.last_results_hash),
        "evidence_hash": hexb(h.evidence_hash),
        "proposer_address": hexb(h.proposer_address),
    }


def block_id_json(bid: Optional[T.BlockID]) -> Dict[str, Any]:
    if bid is None:
        return {"hash": "", "parts": {"total": 0, "hash": ""}}
    return {
        "hash": hexb(bid.hash) if bid.hash else "",
        "parts": {
            "total": bid.part_set_header.total if bid.part_set_header else 0,
            "hash": hexb(bid.part_set_header.hash)
            if bid.part_set_header and bid.part_set_header.hash
            else "",
        },
    }


def commit_json(c: Optional[T.Commit]) -> Optional[Dict[str, Any]]:
    if c is None:
        return None
    return {
        "height": str(c.height),
        "round": c.round,
        "block_id": block_id_json(c.block_id),
        "signatures": [
            {
                "block_id_flag": cs.block_id_flag,
                "validator_address": hexb(cs.validator_address)
                if cs.validator_address
                else "",
                "timestamp_ns": str(cs.timestamp_ns),
                "signature": b64(cs.signature) if cs.signature else None,
            }
            for cs in c.signatures
        ],
    }


def block_json(b: T.Block) -> Dict[str, Any]:
    return {
        "header": header_json(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {
            "evidence": [
                {
                    "type": type(e).__name__,
                    "height": str(e.height()),
                    "bytes": b64(e.encode()),
                }
                for e in (b.evidence or [])
            ]
        },
        "last_commit": commit_json(b.last_commit),
    }


def validator_json(v: T.Validator) -> Dict[str, Any]:
    return {
        "address": hexb(v.address),
        "pub_key": {"type": v.pub_key.type_, "value": b64(bytes(v.pub_key))},
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def validator_set_json(vs: T.ValidatorSet) -> Dict[str, Any]:
    return {
        "validators": [validator_json(v) for v in vs.validators],
        "proposer": validator_json(vs.get_proposer())
        if vs.validators
        else None,
    }


def abci_event_json(e) -> Dict[str, Any]:
    return {
        "type": e.type_,
        "attributes": [
            dict(zip(("key", "value", "index"), attr_kvi(a)))
            for a in e.attributes
        ],
    }


def tx_result_json(r) -> Dict[str, Any]:
    return {
        "code": r.code,
        "data": b64(r.data) if getattr(r, "data", b"") else "",
        "log": getattr(r, "log", ""),
        "gas_wanted": str(getattr(r, "gas_wanted", 0)),
        "gas_used": str(getattr(r, "gas_used", 0)),
        # codespace is part of the DETERMINISTIC result subset that
        # feeds LastResultsHash — the light proxy recomputes the hash
        # from this JSON (light/proxy.py _verified_block_results)
        "codespace": getattr(r, "codespace", ""),
        "events": [
            abci_event_json(e) for e in getattr(r, "events", [])
        ],
    }
