"""JSON-RPC API layer (reference rpc/): HTTP + WebSocket server over
the node's internals, and the matching client library."""

from .client import HTTPClient
from .env import Environment
from .server import RPCServer

__all__ = ["RPCServer", "Environment", "HTTPClient"]
