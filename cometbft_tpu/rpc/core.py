"""RPC route implementations (reference rpc/core/*.go, routes table at
rpc/core/routes.go:15-62).

Every handler takes (env, **params) and returns a JSON-able dict.
Heights arrive as strings or ints (JSON-RPC clients send both)."""

from __future__ import annotations

import asyncio
import base64
import time
from typing import Any, Dict, List, Optional

from .. import types as T
from ..abci import types as abci
from ..utils import codec
from ..utils.pubsub_query import parse as parse_query
from . import encoding as enc


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.data = data


def _h(v, default=None) -> Optional[int]:
    if v is None or v == "":
        return default
    return int(v)


def _bool(v) -> bool:
    """GET params arrive as strings; 'false'/'0'/'' are False."""
    if isinstance(v, str):
        return v.strip().lower() not in ("", "0", "false", "no")
    return bool(v)


def _page(v) -> int:
    p = _h(v, 1) or 1
    if p < 1:
        raise RPCError(-32602, f"page must be >= 1, got {p}")
    return p


def _bytes_param(v) -> bytes:
    """Accept hex (0x... or bare) or base64."""
    if v is None:
        return b""
    if isinstance(v, bytes):
        return v
    s = str(v)
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    try:
        return bytes.fromhex(s)
    except ValueError:
        return base64.b64decode(s)


def _latest_height(env) -> int:
    return env.block_store.height()


def _pruned_error(h: int, base: int) -> "RPCError":
    """The structured below-base error every height-taking route
    raises once retention pruning (store/retention.py) has moved the
    store base past the request — a clean, machine-readable verdict
    instead of the not-found/None-load a pruned height used to hit."""
    return RPCError(
        -32603,
        f"height {h} is pruned (base={base})",
        data=f'{{"pruned": true, "base": "{base}"}}',
    )


def _check_pruned(env, h: int) -> None:
    base = env.block_store.base()
    if h < base:
        raise _pruned_error(h, base)


def _norm_height(env, height) -> int:
    h = _h(height)
    if h is None:
        return _latest_height(env)
    if h <= 0:
        raise RPCError(-32603, f"height must be positive, got {h}")
    if h > _latest_height(env):
        raise RPCError(
            -32603,
            f"height {h} is ahead of the latest height {_latest_height(env)}",
        )
    _check_pruned(env, h)
    return h


# --- info routes --------------------------------------------------------


# loop-lag p95 above this marks the node degraded: a loop that takes
# a quarter second to schedule a ready callback is serving tails, not
# traffic (half the default stall threshold, config loop_stall_ms)
_HEALTH_LAG_P95_MS = 250.0
# a flight-recorded stall within this window marks the node degraded
_HEALTH_STALL_RECENT_S = 60.0


def health(env) -> Dict[str, Any]:
    """Runtime health verdict (docs/OBS.md): loop responsiveness,
    commit freshness and queue backpressure, with a degraded/ok
    verdict + reasons. The reference returns {} here; every field is
    additive so `health == ok` probes keep working."""
    reasons: List[str] = []
    out: Dict[str, Any] = {}
    wd = env.loop_watchdog
    if wd is not None:
        lag = wd.lag_stats()
        out["loop_lag_ms"] = {
            k: lag[k] for k in ("p50_ms", "p95_ms", "p99_ms", "max_ms")
        }
        out["loop_stalls"] = wd.stall_count
        if lag["samples"] >= 20 and lag["p95_ms"] > _HEALTH_LAG_P95_MS:
            reasons.append(
                f"loop lag p95 {lag['p95_ms']}ms > "
                f"{_HEALTH_LAG_P95_MS}ms"
            )
        ago = wd.last_stall_ago_s()
        if ago is not None and ago < _HEALTH_STALL_RECENT_S:
            reasons.append(
                f"loop stall flight-recorded {ago:.0f}s ago "
                f"(see dump_tasks / the trace ring)"
            )
    latest = env.block_store.height()
    out["latest_block_height"] = str(latest)
    meta = env.block_store.load_block_meta(latest) if latest else None
    if meta is not None:
        age_s = max(0.0, (time.time_ns() - meta.header.time_ns) / 1e9)
        out["last_commit_age_s"] = round(age_s, 3)
    if env.queues is not None:
        # ONE registry pass per request (every stats_fn walks live
        # structures — p2p.send iterates all peers' channels)
        snap = env.queues.snapshot()
        out["queue_high_watermarks"] = {
            name: int(s.get("high_watermark", 0))
            for name, s in snap.items()
        }
        out["queue_dropped_total"] = sum(
            int(s.get("dropped", 0)) for s in snap.values()
        )
        for name, s in snap.items():
            # only single bounded queues report "maxsize"; aggregate
            # entries and soft targets use other field names exactly
            # so this check cannot misread a summed depth
            maxsize = int(s.get("maxsize", 0) or 0)
            if maxsize and int(s.get("depth", 0)) >= maxsize:
                reasons.append(f"queue {name} is full ({maxsize})")
    sw = env.switch
    if sw is not None and hasattr(sw, "num_peers"):
        # connectivity verdict (self-healing plane, p2p/reconnect.py):
        # degraded below min_peers — but only once the node has
        # evidence it is MEANT to be connected (persistent peers
        # configured, addresses learned, or a peer ever lost); a
        # single-node net with nothing to dial stays ok
        n = sw.num_peers()
        min_peers = getattr(sw, "min_peers", 1)
        conn: Dict[str, Any] = {"n_peers": n, "min_peers": min_peers}
        plane = getattr(sw, "reconnect", None)
        if plane is not None:
            st = plane.stats()
            conn.update(st)
            expects_peers = plane.expects_peers()
        else:
            expects_peers = bool(getattr(sw, "persistent_addrs", None))
        conn_reasons: List[str] = []
        if expects_peers and n < min_peers:
            detail = ""
            if plane is not None:
                detail = (
                    f" (reconnect: {st['fast_lane']} fast-lane, "
                    f"{st['slow_lane']} slow-lane, "
                    f"{st['attempts_total']} attempts)"
                )
            conn_reasons.append(
                f"connectivity: {n}/{min_peers} peers connected"
                + detail
            )
        if plane is not None and plane.starving():
            conn_reasons.append(
                "connectivity: starving — zero peers for "
                f"{st['starving_for_s']}s"
            )
        conn["status"] = "degraded" if conn_reasons else "ok"
        out["connectivity"] = conn
        reasons.extend(conn_reasons)
    hc_fn = getattr(env, "light_header_cache_fn", None)
    hc = hc_fn() if hc_fn is not None else None
    if hc is not None and len(hc):
        # shared verified-header cache (light/serving.py): present
        # once statesync restored through it or a co-resident serving
        # plane injected one — hit/miss/flight counters for "is the
        # serving side sharing verification work"
        out["light_header_cache"] = hc.stats()
    ret = getattr(env, "retention", None)
    if ret is not None and getattr(ret, "enabled", False):
        # storage lifecycle verdict (store/retention.py): the plane's
        # base/pruned/snapshot counters, degraded when the reconciler
        # has stopped keeping the window (pruning far behind target)
        st = ret.stats()
        out["storage"] = st
        cfg = getattr(ret, "cfg", None)
        if cfg is not None and cfg.retain_blocks > 0:
            lag = latest - st["base_height"]
            # 3 windows behind = the reconciler is not keeping up
            # (wedged worker, dead loop) — disk is growing unbounded
            if st["reconciles"] > 0 and lag > 3 * max(
                cfg.retain_blocks, cfg.prune_batch
            ):
                reasons.append(
                    f"storage: prune base {st['base_height']} lags "
                    f"head {latest} by {lag} "
                    f"(> 3x retain_blocks={cfg.retain_blocks})"
                )
    out["serving_role"] = _serving_role(env)
    lag = _replica_lag(env)
    out["replica_lag_heights"] = lag
    cfg = getattr(env, "config", None)
    fleet_cfg = getattr(cfg, "fleet", None) if cfg is not None else None
    if fleet_cfg is not None and lag > fleet_cfg.max_lag_heights:
        reasons.append(
            f"replica lag {lag} heights > "
            f"max_lag_heights={fleet_cfg.max_lag_heights}"
        )
    fr = getattr(env, "fleet_router", None)
    if fr is not None:
        # fleet verdict (docs/FLEET.md): per-replica lag + degraded
        # flags straight from the router — a degraded or dead replica
        # degrades THIS health verdict (the router is the seam an
        # operator probes)
        fs = fr.fleet_status()
        out["fleet"] = {
            "sessions": fs["sessions"],
            "failovers": fs["failovers"],
            "sheds": fs["sheds"],
            "replicas": [
                {
                    "name": r["name"],
                    "alive": r["alive"],
                    "lag_heights": r["lag_heights"],
                    "degraded": r["degraded"],
                }
                for r in fs["replicas"]
            ],
        }
        for r in fs["replicas"]:
            if not r["alive"]:
                reasons.append(f"fleet: replica {r['name']} dead")
            elif r["degraded"]:
                reasons.append(
                    f"fleet: replica {r['name']} degraded "
                    f"(lag {r['lag_heights']} heights)"
                )
    bd = getattr(env.consensus_state, "last_commit_breakdown", None)
    if bd is not None:
        # per-phase attribution of the last committed height (ISSUE 7
        # cross-node tracing, docs/TRACE.md "Cross-node timelines"):
        # proposal wait, quorum waits, verify, persist/wal/apply, plus
        # the dominant disjoint segment
        out["last_height_commit_breakdown"] = bd
    out["status"] = "degraded" if reasons else "ok"
    if reasons:
        if bd is not None:
            # a degraded verdict cites WHERE the last commit spent
            # its time, so the operator starts at the right phase
            reasons.append(
                f"last commit h={bd['height']} dominated by "
                f"{bd['dominant']} "
                f"({bd['phases'].get(bd['dominant'], '?')}ms)"
            )
        out["reasons"] = reasons
    return out


def _serving_role(env) -> str:
    """validator|follower (docs/FLEET.md): a node without a signing
    key serves reads only — the fleet deployment shape."""
    return "validator" if env.privval_pubkey is not None else "follower"


def _replica_lag(env) -> int:
    fn = getattr(env, "replica_lag_fn", None)
    if fn is None:
        return 0
    try:
        return max(0, int(fn()))
    except Exception:
        return 0


def fleet_status(env) -> Dict[str, Any]:
    """Per-replica serving-fleet view (docs/FLEET.md): head, sessions,
    admission/shed counters and each replica's height/sessions/lag.
    Only meaningful on a node fronting a SessionRouter; elsewhere it
    answers a well-formed JSON-RPC error."""
    fr = getattr(env, "fleet_router", None)
    if fr is None:
        raise RPCError(
            -32603, "this node does not front a serving fleet"
        )
    return fr.fleet_status()


def dump_tasks(env) -> Dict[str, Any]:
    """Debug route: every asyncio task's stack (the goroutine-dump
    analog, scoped to the loop serving this RPC)."""
    from ..obs.watchdog import all_task_stacks

    tasks = all_task_stacks()
    return {"n_tasks": str(len(tasks)), "tasks": tasks}


def status(env) -> Dict[str, Any]:
    bs = env.block_store
    latest = bs.height()
    meta = bs.load_block_meta(latest) if latest else None
    state = env.state_store.load()
    pub = env.privval_pubkey
    return {
        "node_info": {
            "id": env.node_info.node_id if env.node_info else "",
            "network": env.chain_id,
            "moniker": env.node_info.moniker if env.node_info else "",
            "version": env.node_info.version if env.node_info else "",
            "listen_addr": env.node_info.listen_addr if env.node_info else "",
        },
        "sync_info": {
            "latest_block_height": str(latest),
            "latest_block_hash": enc.hexb(meta.block_id.hash) if meta else "",
            "latest_app_hash": enc.hexb(state.app_hash) if state else "",
            "latest_block_time_ns": str(meta.header.time_ns) if meta else "0",
            "earliest_block_height": str(bs.base()),
            "catching_up": bool(
                env.consensus_state is None
                or getattr(env.consensus_state, "queue", None) is None
            ),
        },
        "validator_info": {
            "address": enc.hexb(pub.address()) if pub else "",
            "pub_key": {
                "type": pub.type_,
                "value": enc.b64(bytes(pub)),
            }
            if pub
            else None,
            "voting_power": str(
                _own_power(state, pub) if state and pub else 0
            ),
        },
        "serving_role": _serving_role(env),
        "replica_lag_heights": str(_replica_lag(env)),
    }


def _own_power(state, pub) -> int:
    try:
        _, val = state.validators.get_by_address(pub.address())
        return val.voting_power if val else 0
    except Exception:
        return 0


def net_info(env) -> Dict[str, Any]:
    sw = env.switch
    peers = list(sw.peers.values()) if sw else []
    return {
        "listening": bool(sw),
        "listeners": [sw.transport.listen_addr] if sw else [],
        "n_peers": str(len(peers)),
        "peers": [
            {
                "node_info": {
                    "id": p.peer_id,
                    "moniker": p.node_info.moniker,
                    "network": p.node_info.network,
                    "listen_addr": p.node_info.listen_addr,
                },
                "is_outbound": p.outbound,
                "remote_ip": p.conn_str,
            }
            for p in peers
        ],
    }


def genesis(env) -> Dict[str, Any]:
    import json

    return {"genesis": json.loads(env.genesis.to_json())}


def genesis_chunked(env, chunk=0) -> Dict[str, Any]:
    data = env.genesis.to_json().encode()
    size = 16 * 1024
    chunks = [data[i : i + size] for i in range(0, len(data), size)] or [b""]
    c = _h(chunk, 0)
    if not 0 <= c < len(chunks):
        raise RPCError(-32603, f"chunk {c} out of range [0,{len(chunks)})")
    return {
        "chunk": str(c),
        "total": str(len(chunks)),
        "data": enc.b64(chunks[c]),
    }


# --- block routes -------------------------------------------------------


def blockchain(env, minHeight=None, maxHeight=None) -> Dict[str, Any]:
    latest = _latest_height(env)
    max_h = min(_h(maxHeight, latest) or latest, latest)
    min_h = max(_h(minHeight, 1) or 1, env.block_store.base())
    max_h = max(min_h, max_h)
    metas = []
    for h in range(max_h, min_h - 1, -1):
        if len(metas) >= 20:
            break
        meta = env.block_store.load_block_meta(h)
        if meta is None:
            continue
        metas.append(
            {
                "block_id": enc.block_id_json(meta.block_id),
                "block_size": str(meta.block_size),
                "header": enc.header_json(meta.header),
                "num_txs": str(meta.num_txs),
            }
        )
    return {"last_height": str(latest), "block_metas": metas}


def block(env, height=None) -> Dict[str, Any]:
    h = _norm_height(env, height)
    blk = env.block_store.load_block(h)
    meta = env.block_store.load_block_meta(h)
    if blk is None or meta is None:
        raise RPCError(-32603, f"block at height {h} not found")
    commit = env.block_store.load_seen_commit(
        h
    ) or env.block_store.load_block_commit(h)
    return {
        "block_id": enc.block_id_json(meta.block_id),
        "block": enc.block_json(blk),
        "block_b64": enc.b64(codec.encode_block(blk)),
        "commit_b64": enc.b64(codec.encode_commit(commit)) if commit else "",
    }


def block_by_hash(env, hash=None) -> Dict[str, Any]:
    blk = env.block_store.load_block_by_hash(_bytes_param(hash))
    if blk is None:
        raise RPCError(-32603, "block not found")
    return block(env, blk.height)


def header(env, height=None) -> Dict[str, Any]:
    h = _norm_height(env, height)
    blk = env.block_store.load_block(h)
    if blk is None:
        raise RPCError(-32603, f"header at height {h} not found")
    return {
        "header": enc.header_json(blk.header),
        "header_b64": enc.b64(codec.encode_header(blk.header)),
    }


def header_by_hash(env, hash=None) -> Dict[str, Any]:
    blk = env.block_store.load_block_by_hash(_bytes_param(hash))
    if blk is None:
        raise RPCError(-32603, "header not found")
    return header(env, blk.height)


def commit(env, height=None) -> Dict[str, Any]:
    h = _norm_height(env, height)
    blk = env.block_store.load_block(h)
    # canonical = the immutable commit from block h+1's LastCommit;
    # at the store tip only the mutable seen commit exists
    # (reference rpc/core/blocks.go Commit)
    cm = env.block_store.load_block_commit(h)
    canonical = cm is not None
    if cm is None:
        cm = env.block_store.load_seen_commit(h)
    if blk is None or cm is None:
        raise RPCError(-32603, f"commit for height {h} not found")
    return {
        "signed_header": {
            "header": enc.header_json(blk.header),
            "commit": enc.commit_json(cm),
        },
        "header_b64": enc.b64(codec.encode_header(blk.header)),
        "commit_b64": enc.b64(codec.encode_commit(cm)),
        "canonical": canonical,
    }


def block_results(env, height=None) -> Dict[str, Any]:
    h = _norm_height(env, height)
    raw = env.state_store.load_finalize_block_response(h)
    if raw is None:
        raise RPCError(-32603, f"no results for height {h}")
    from ..state.execution import decode_finalize_response

    resp = decode_finalize_response(raw)
    return {
        "height": str(h),
        "txs_results": [enc.tx_result_json(r) for r in resp.tx_results],
        # block-level events persist with the response now (ISSUE 15:
        # the stored record is the indexer's crash-replay source, so
        # it must carry everything live indexing saw)
        "finalize_block_events": [
            enc.abci_event_json(e) for e in resp.events
        ],
        "app_hash": enc.hexb(resp.app_hash),
        "validator_updates": [
            {"power": str(u.power), "pub_key_type": u.pub_key_type,
             "pub_key": enc.b64(u.pub_key_bytes)}
            for u in resp.validator_updates
        ],
    }


def validators(env, height=None, page=1, per_page=30) -> Dict[str, Any]:
    h = _norm_height(env, height)
    vs = env.state_store.load_validators(h)
    if vs is None:
        raise RPCError(-32603, f"no validator set at height {h}")
    page, per_page = _page(page), min(_h(per_page, 30) or 30, 100)
    vals = vs.validators
    start = (page - 1) * per_page
    return {
        "block_height": str(h),
        "validators": [
            enc.validator_json(v) for v in vals[start : start + per_page]
        ],
        "count": str(min(per_page, max(0, len(vals) - start))),
        "total": str(len(vals)),
        "validator_set_b64": enc.b64(codec.encode_validator_set(vs)),
    }


# --- consensus routes ---------------------------------------------------


def consensus_state(env) -> Dict[str, Any]:
    cs = env.consensus_state
    if cs is None:
        raise RPCError(-32603, "consensus state not available")
    rs = cs.rs
    return {
        "round_state": {
            "height": str(rs.height),
            "round": rs.round,
            "step": int(rs.step),
            "proposal": rs.proposal is not None,
            "proposal_block": rs.proposal_block is not None,
            "locked_round": rs.locked_round,
            "valid_round": rs.valid_round,
        }
    }


def dump_consensus_state(env) -> Dict[str, Any]:
    out = consensus_state(env)
    sw = env.switch
    out["peers"] = [
        {
            "node_address": p.conn_str,
            "peer_state": {
                "round_state": vars(p.get("prs"))
                if p.get("prs") is not None and hasattr(p.get("prs"), "height")
                else {},
            },
        }
        for p in (sw.peers.values() if sw else [])
    ]
    # sets are not JSON-able; flatten
    for p in out["peers"]:
        prs = p["peer_state"]["round_state"]
        if prs:
            p["peer_state"]["round_state"] = {
                "height": prs.get("height"),
                "round": prs.get("round"),
                "step": prs.get("step"),
            }
    return out


def consensus_params(env, height=None) -> Dict[str, Any]:
    h = _norm_height(env, height)
    # per-HEIGHT params (reference env.ConsensusParams loads the
    # params as of the requested height, not the tip): the light
    # proxy verifies their hash against header(h).consensus_hash
    cp = env.state_store.load_consensus_params(h)
    if cp is None:
        cp = env.state_store.load().consensus_params
    return {
        "params_b64": enc.b64(cp.encode()),
        "block_height": str(h),
        "consensus_params": {
            "block": {
                "max_bytes": str(cp.block.max_bytes),
                "max_gas": str(cp.block.max_gas),
            },
            "validator": {
                "pub_key_types": list(cp.validator.pub_key_types)
            },
            "evidence": {
                "max_age_num_blocks": str(cp.evidence.max_age_num_blocks),
                "max_age_duration_ns": str(cp.evidence.max_age_duration_ns),
                "max_bytes": str(cp.evidence.max_bytes),
            },
            "abci": {
                "vote_extensions_enable_height": str(
                    cp.abci.vote_extensions_enable_height
                ),
            },
        },
    }


# --- mempool routes -----------------------------------------------------


def unconfirmed_txs(env, limit=30) -> Dict[str, Any]:
    lim = min(_h(limit, 30) or 30, 100)
    txs = env.mempool.iter_txs()[:lim]
    return {
        "n_txs": str(len(txs)),
        "total": str(env.mempool.size()),
        "total_bytes": str(sum(len(t) for t in txs)),
        "txs": [enc.b64(t) for t in txs],
    }


def num_unconfirmed_txs(env) -> Dict[str, Any]:
    return {
        "n_txs": str(env.mempool.size()),
        "total": str(env.mempool.size()),
        "total_bytes": "0",
    }


def check_tx(env, tx=None) -> Dict[str, Any]:
    res = env.proxy.mempool.check_tx(
        abci.RequestCheckTx(tx=_bytes_param(tx))
    )
    return {"code": res.code, "log": res.log, "gas_wanted": str(res.gas_wanted)}


def broadcast_tx_async(env, tx=None) -> Dict[str, Any]:
    raw = _bytes_param(tx)
    env.submit_tx_nowait(raw)
    return {"code": 0, "data": "", "log": "", "hash": enc.hexb(_tx_hash(raw))}


async def broadcast_tx_sync(env, tx=None) -> Dict[str, Any]:
    raw = _bytes_param(tx)
    res = await env.submit_tx_async(raw)
    return {
        "code": res.code,
        "data": "",
        "log": res.log,
        "hash": enc.hexb(_tx_hash(raw)),
    }


async def broadcast_tx_commit(env, tx=None, timeout_s: float = 10.0):
    """CheckTx, then await inclusion through the height-keyed
    CommitWaiterMap (rpc/fanout.py): ONE lossless sync bus listener
    total and a dict lookup per committed tx, instead of the per-RPC
    predicate subscription the reference shape
    (rpc/core/mempool.go:70) pays on every publish."""
    raw = _bytes_param(tx)
    key = _tx_hash(raw)
    waiters = env.commit_waiters()
    # register BEFORE submitting (the subscribe-before-CheckTx
    # ordering): a commit can never race past the waiter
    fut = waiters.register(key.hex())
    try:
        res = await env.submit_tx_async(raw)
        if res.code != 0:
            return {
                "check_tx": {"code": res.code, "log": res.log},
                "tx_result": {},
                "hash": enc.hexb(key),
                "height": "0",
            }
        event = await asyncio.wait_for(fut, timeout_s)
        return {
            "check_tx": {"code": 0, "log": ""},
            "tx_result": enc.tx_result_json(event.data["result"]),
            "hash": enc.hexb(key),
            "height": str(event.data["height"]),
        }
    except asyncio.TimeoutError:
        raise RPCError(-32603, "timed out waiting for tx to be included")
    finally:
        # timeout, cancellation (gRPC grace expiry) and success all
        # release the map entry here — no leak, no stale resolution
        waiters.unregister(key.hex(), fut)


def _tx_hash(tx: bytes) -> bytes:
    import hashlib

    return hashlib.sha256(tx).digest()


def broadcast_evidence(env, evidence=None) -> Dict[str, Any]:
    from ..evidence.types import decode_evidence

    ev = decode_evidence(_bytes_param(evidence))
    env.evidence_pool.add_evidence(ev)
    return {"hash": enc.hexb(ev.hash())}


# --- abci passthrough ---------------------------------------------------


def abci_info(env) -> Dict[str, Any]:
    res = env.proxy.query.info(abci.RequestInfo())
    return {
        "response": {
            "data": res.data,
            "version": res.version,
            "app_version": str(res.app_version),
            "last_block_height": str(res.last_block_height),
            "last_block_app_hash": enc.b64(res.last_block_app_hash),
        }
    }


def abci_query(env, path="", data=None, height=0, prove=False) -> Dict[str, Any]:
    res = env.proxy.query.query(
        abci.RequestQuery(
            data=_bytes_param(data),
            path=str(path or ""),
            height=_h(height, 0) or 0,
            prove=_bool(prove),
        )
    )
    return {
        "response": {
            "code": res.code,
            "log": res.log,
            "key": enc.b64(res.key) if res.key else "",
            "value": enc.b64(res.value) if res.value else "",
            "height": str(res.height),
            # encoded crypto/merkle proof-op chain (apps that support
            # prove=true); light proxies verify it against the
            # light-verified AppHash of height+1
            "proof_ops": enc.b64(res.proof_ops)
            if getattr(res, "proof_ops", b"")
            else "",
        }
    }


# --- tx / block search (indexer-backed) ---------------------------------


async def _index_barrier(env) -> None:
    """Read-your-writes for index queries: indexing flushes per
    height from a bounded async drain (state/indexer.py), so a query
    racing the commit that published its tx waits (bounded) for the
    sealed heights to land before scanning."""
    svc = getattr(env, "indexer_service", None)
    if svc is not None:
        await svc.barrier()


async def tx(env, hash=None, prove=False) -> Dict[str, Any]:
    if env.tx_indexer is None:
        raise RPCError(-32603, "tx indexing is disabled")
    await _index_barrier(env)
    key = _bytes_param(hash)
    res = env.tx_indexer.get(key)
    if res is None:
        ibase = (
            env.tx_indexer.base_height()
            if hasattr(env.tx_indexer, "base_height")
            else 0
        )
        if ibase:
            # the row may have been retention-pruned (idx:base):
            # say so instead of a bare not-found
            raise RPCError(
                -32603,
                f"tx {key.hex()} not found "
                f"(tx index pruned below height {ibase})",
                data=f'{{"index_base": "{ibase}"}}',
            )
        raise RPCError(-32603, f"tx {key.hex()} not found")
    height, index, tx_bytes, tx_result = res
    out = {
        "hash": enc.hexb(key),
        "height": str(height),
        "index": index,
        "tx_result": enc.tx_result_json(tx_result),
        "tx": enc.b64(tx_bytes),
    }
    if _bool(prove):
        # merkle inclusion proof against the block's data_hash
        # (reference rpc/core/tx.go Prove; the light proxy verifies
        # it against the light-verified header)
        out["proof"] = _tx_proof(env, height, index, tx_bytes, {})
    return out


def _height_tx_proofs(env, height: int, cache: dict):
    """(data_hash, [Proof per tx]) for one block, memoized in ``cache``
    so a proved tx_search page over one block builds the merkle tree
    ONCE, not per hit. Raises when the block is pruned/missing — a
    requested proof that cannot be produced is an error, never a
    silently proof-less response (reference rpc/core/tx.go proveTx)."""
    got = cache.get(height)
    if got is None:
        blk = env.block_store.load_block(height)
        if blk is None:
            _check_pruned(env, height)
            raise RPCError(
                -32603,
                f"cannot prove tx: block {height} not in store",
            )
        from ..crypto import merkle
        from ..types.block import tx_hash

        _, proofs = merkle.proofs_from_byte_slices(
            [tx_hash(t) for t in blk.data.txs]
        )
        got = (blk.header.data_hash, proofs)
        cache[height] = got
    return got


def _tx_proof(env, height: int, index: int, tx_bytes: bytes, cache: dict):
    from ..crypto import merkle

    data_hash, proofs = _height_tx_proofs(env, height, cache)
    if index >= len(proofs):
        raise RPCError(
            -32603, f"cannot prove tx: index {index} out of range"
        )
    return {
        "root_hash": enc.hexb(data_hash),
        "data": enc.b64(tx_bytes),
        "proof_b64": enc.b64(merkle.encode_proof(proofs[index])),
    }


async def tx_search(
    env, query="", prove=False, page=1, per_page=30, order_by="asc"
) -> Dict[str, Any]:
    if env.tx_indexer is None:
        raise RPCError(-32603, "tx indexing is disabled")
    await _index_barrier(env)
    q = parse_query(str(query))
    hits = env.tx_indexer.search(q)
    if str(order_by) == "desc":
        hits = list(reversed(hits))
    page, per_page = _page(page), min(_h(per_page, 30) or 30, 100)
    start = (page - 1) * per_page
    with_proof = _bool(prove)
    proof_cache: dict = {}  # height -> (data_hash, proofs): one tree
    out = []                # build per block, however many hits share it
    for height, index, tx_bytes, tx_result, key in hits[start : start + per_page]:
        item = {
            "hash": enc.hexb(key),
            "height": str(height),
            "index": index,
            "tx_result": enc.tx_result_json(tx_result),
            "tx": enc.b64(tx_bytes),
        }
        if with_proof:
            item["proof"] = _tx_proof(
                env, height, index, tx_bytes, proof_cache
            )
        out.append(item)
    return {"txs": out, "total_count": str(len(hits))}


async def block_search(env, query="", page=1, per_page=30, order_by="asc"):
    if env.block_indexer is None:
        raise RPCError(-32603, "block indexing is disabled")
    await _index_barrier(env)
    q = parse_query(str(query))
    heights = env.block_indexer.search(q)
    if str(order_by) == "desc":
        heights = list(reversed(heights))
    page, per_page = _page(page), min(_h(per_page, 30) or 30, 100)
    start = (page - 1) * per_page
    blocks = []
    for h in heights[start : start + per_page]:
        # an index hit whose block has been retention-pruned must say
        # so, not silently shrink the page (retain_index can be wider
        # than retain_blocks — the row legitimately outlives the body)
        _check_pruned(env, h)
        blk = env.block_store.load_block(h)
        if blk:
            blocks.append(
                {
                    "block_id": enc.block_id_json(T.BlockID(blk.hash(), None)),
                    "block": enc.block_json(blk),
                }
            )
    return {"blocks": blocks, "total_count": str(len(heights))}


# --- route table --------------------------------------------------------

# --- unsafe routes (reference rpc/core/routes.go AddUnsafeRoutes:
# dial_seeds, dial_peers, unsafe_flush_mempool; registered only when
# config.rpc.unsafe) ------------------------------------------------------


def _addr_list(v) -> list:
    """Accept a JSON array (POST) or the URI forms '["a","b"]' /
    'a,b' (GET params arrive as plain strings)."""
    if v is None:
        return []
    if isinstance(v, str):
        s = v.strip()
        if s.startswith("["):
            import json as _json

            return [str(x) for x in _json.loads(s)]
        return [a.strip() for a in s.split(",") if a.strip()]
    return [str(x) for x in v]


def dial_seeds(env, seeds=None) -> Dict[str, Any]:
    if not env.switch:
        raise RPCError(-32603, "p2p switch not available")
    addrs = _addr_list(seeds)
    env.switch.dial_peers_async(addrs, persistent=False)
    return {"log": f"dialing seeds: {addrs}"}


def dial_peers(env, peers=None, persistent=None) -> Dict[str, Any]:
    if not env.switch:
        raise RPCError(-32603, "p2p switch not available")
    addrs = _addr_list(peers)
    env.switch.dial_peers_async(
        addrs, persistent=str(persistent).lower() in ("true", "1")
    )
    return {"log": f"dialing peers: {addrs}"}


def unsafe_flush_mempool(env) -> Dict[str, Any]:
    env.mempool.flush()
    return {}


def unsafe_disconnect_peers(env) -> Dict[str, Any]:
    """Drop every peer connection (e2e 'disconnect' perturbation; the
    reference does this at the docker network layer)."""
    import asyncio as _a

    sw = env.switch
    if not sw:
        raise RPCError(-32603, "p2p switch not available")
    peers = list(sw.peers.values())
    for p in peers:
        _a.ensure_future(sw._remove_peer(p, None))
    return {"log": f"disconnected {len(peers)} peers"}


UNSAFE_ROUTES = {
    "dial_seeds": dial_seeds,
    "dial_peers": dial_peers,
    "unsafe_flush_mempool": unsafe_flush_mempool,
    "unsafe_disconnect_peers": unsafe_disconnect_peers,
}

ROUTES = {
    "health": health,
    "dump_tasks": dump_tasks,
    "status": status,
    "fleet_status": fleet_status,
    "net_info": net_info,
    "genesis": genesis,
    "genesis_chunked": genesis_chunked,
    "blockchain": blockchain,
    "block": block,
    "block_by_hash": block_by_hash,
    "header": header,
    "header_by_hash": header_by_hash,
    "commit": commit,
    "block_results": block_results,
    "validators": validators,
    "consensus_state": consensus_state,
    "dump_consensus_state": dump_consensus_state,
    "consensus_params": consensus_params,
    "unconfirmed_txs": unconfirmed_txs,
    "num_unconfirmed_txs": num_unconfirmed_txs,
    "check_tx": check_tx,
    "broadcast_tx_async": broadcast_tx_async,
    "broadcast_tx_sync": broadcast_tx_sync,
    "broadcast_tx_commit": broadcast_tx_commit,
    "broadcast_evidence": broadcast_evidence,
    "abci_info": abci_info,
    "abci_query": abci_query,
    "tx": tx,
    "tx_search": tx_search,
    "block_search": block_search,
}
