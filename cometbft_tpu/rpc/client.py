"""RPC clients (reference rpc/client/http + /local).

HTTPClient: JSON-RPC over HTTP POST with typed helpers that decode the
lossless `*_b64` fields back into framework types — what the light
client provider and statesync state provider consume. Also supports
WebSocket event subscriptions."""

from __future__ import annotations

import asyncio
import base64
import itertools
import json
from typing import Any, AsyncIterator, Dict, Optional

import aiohttp

from .. import types as T
from ..utils import codec


class RPCClientError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"[{code}] {message} {data}".strip())
        self.code = code
        self.message = message
        self.data = data


class HTTPClient:
    def __init__(self, base_url: str, timeout_s: float = 10.0):
        if not base_url.startswith("http"):
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout = aiohttp.ClientTimeout(total=timeout_s)
        self._session: Optional[aiohttp.ClientSession] = None
        self._ids = itertools.count(1)

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(timeout=self.timeout)
        return self._session

    async def close(self) -> None:
        if self._session and not self._session.closed:
            # bounded (ASY110): aiohttp session close can park on
            # connector teardown; never let it hang the caller's stop
            try:
                await asyncio.wait_for(self._session.close(), 5.0)
            except asyncio.TimeoutError:
                pass

    async def call(self, method: str, **params) -> Dict[str, Any]:
        sess = await self._sess()
        req = {
            "jsonrpc": "2.0",
            "id": next(self._ids),
            "method": method,
            "params": {k: v for k, v in params.items() if v is not None},
        }
        async with sess.post(self.base_url + "/", json=req) as resp:
            body = await resp.json()
        if body.get("error"):
            e = body["error"]
            raise RPCClientError(
                e.get("code", -1), e.get("message", ""), e.get("data", "")
            )
        return body["result"]

    # --- typed helpers --------------------------------------------------

    async def status(self) -> Dict[str, Any]:
        return await self.call("status")

    async def block(self, height: Optional[int] = None) -> Dict[str, Any]:
        return await self.call(
            "block", height=str(height) if height else None
        )

    async def block_decoded(self, height: Optional[int] = None) -> T.Block:
        res = await self.block(height)
        return codec.decode_block(base64.b64decode(res["block_b64"]))

    async def commit_decoded(self, height: Optional[int] = None):
        """(Header, Commit) decoded from the lossless payload."""
        res = await self.call(
            "commit", height=str(height) if height else None
        )
        hdr = codec.decode_header(base64.b64decode(res["header_b64"]))
        cm = codec.decode_commit(base64.b64decode(res["commit_b64"]))
        return hdr, cm

    async def validators_decoded(
        self, height: Optional[int] = None
    ) -> T.ValidatorSet:
        res = await self.call(
            "validators",
            height=str(height) if height else None,
            per_page="100",
        )
        return codec.decode_validator_set(
            base64.b64decode(res["validator_set_b64"])
        )

    async def broadcast_tx_sync(self, tx: bytes) -> Dict[str, Any]:
        return await self.call(
            "broadcast_tx_sync", tx=base64.b64encode(tx).decode()
        )

    async def broadcast_tx_commit(self, tx: bytes) -> Dict[str, Any]:
        return await self.call(
            "broadcast_tx_commit", tx=base64.b64encode(tx).decode()
        )

    async def abci_query(
        self, path: str, data: bytes, height: int = 0, prove: bool = False
    ) -> Dict[str, Any]:
        return await self.call(
            "abci_query",
            path=path,
            data=data.hex(),
            height=str(height),
            prove=prove,
        )

    # --- websocket subscription -----------------------------------------

    async def subscribe(
        self, query: str
    ) -> AsyncIterator[Dict[str, Any]]:
        """Async iterator of matching events."""
        sess = await self._sess()
        ws = await sess.ws_connect(self.base_url + "/websocket")
        await ws.send_json(
            {
                "jsonrpc": "2.0",
                "id": next(self._ids),
                "method": "subscribe",
                "params": {"query": query},
            }
        )
        first = json.loads((await ws.receive()).data)
        if first.get("error"):
            await ws.close()
            raise RPCClientError(-1, str(first["error"]))

        async def gen():
            try:
                async for msg in ws:
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        break
                    body = json.loads(msg.data)
                    if body.get("result"):
                        yield body["result"]
            finally:
                await ws.close()

        return gen()
