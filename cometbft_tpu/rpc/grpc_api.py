"""Legacy gRPC broadcast API (reference rpc/grpc/api.go).

Two unary methods — Ping (liveness) and BroadcastTx (CheckTx + await
inclusion, the BroadcastTxCommit semantics) — served without codegen:
the generic-handler + hand-rolled deterministic proto pattern the ABCI
gRPC transport already uses (abci/server.py GRPCServer). Runs beside
the JSON-RPC server when ``rpc.grpc_laddr`` is configured (reference
config GRPCListenAddress).

Wire shapes (field numbers are the contract):
  RequestBroadcastTx  {1: tx bytes}
  ResponseBroadcastTx {1: check_tx {1: code, 3: log},
                       2: tx_result {1: code, 3: log},
                       3: hash hex string, 4: height varint}
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..utils import proto
from . import core

PING_METHOD = "/cometbft.rpc.grpc.BroadcastAPI/Ping"
BROADCAST_METHOD = "/cometbft.rpc.grpc.BroadcastAPI/BroadcastTx"


class GRPCBroadcastServer:
    """Node-side server; ``loop`` is the node's asyncio loop (the
    broadcast path awaits the tx inclusion event on it, while gRPC
    serves from its own thread pool)."""

    def __init__(
        self,
        env,
        addr: str,
        loop: asyncio.AbstractEventLoop,
        timeout_s: float = 10.0,
    ):
        self.env = env
        self.addr = addr
        self.loop = loop
        self.timeout_s = timeout_s
        self._server = None
        self.port: Optional[int] = None

    def start(self) -> None:
        import grpc

        env, loop, timeout_s = self.env, self.loop, self.timeout_s

        def ping(request: bytes, context) -> bytes:
            return b""

        def broadcast(request: bytes, context) -> bytes:
            m = proto.parse(request)
            tx = proto.get1(m, 1, b"")
            fut = asyncio.run_coroutine_threadsafe(
                core.broadcast_tx_commit(env, tx=tx, timeout_s=timeout_s),
                loop,
            )
            try:
                # small grace over the coroutine's own deadline; on
                # expiry CANCEL the future so the height-keyed
                # CommitWaiterMap entry inside broadcast_tx_commit is
                # released (rpc/fanout.py — this API rides the same
                # one-subscription waiter plane as the JSON-RPC route,
                # so N concurrent gRPC broadcasts cost one dict entry
                # each, not one bus predicate each)
                res = fut.result(timeout_s + 5.0)
            except Exception as e:
                fut.cancel()
                context.abort(grpc.StatusCode.INTERNAL, str(e))
            check = res.get("check_tx") or {}
            txr = res.get("tx_result") or {}
            out = proto.field_message(
                1,
                proto.field_varint(1, int(check.get("code") or 0))
                + proto.field_string(3, str(check.get("log") or "")),
            )
            out += proto.field_message(
                2,
                proto.field_varint(1, int(txr.get("code") or 0))
                + proto.field_string(3, str(txr.get("log") or "")),
            )
            out += proto.field_string(3, str(res.get("hash") or ""))
            out += proto.field_varint(4, int(res.get("height") or 0))
            return out

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == PING_METHOD:
                    return grpc.unary_unary_rpc_method_handler(ping)
                if details.method == BROADCAST_METHOD:
                    return grpc.unary_unary_rpc_method_handler(broadcast)
                return None

        from concurrent.futures import ThreadPoolExecutor

        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=2), handlers=(Handler(),)
        )
        host, _, port = self.addr.rpartition(":")
        self.port = self._server.add_insecure_port(
            f"{host or '127.0.0.1'}:{port}"
        )
        if not self.port:
            raise RuntimeError(
                f"gRPC broadcast API failed to bind {self.addr}"
            )
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)


class GRPCBroadcastClient:
    """Reference StartGRPCClient analog (rpc/grpc/client_server.go)."""

    def __init__(self, addr: str):
        import grpc

        self._ch = grpc.insecure_channel(addr)
        ident = lambda b: b  # noqa: E731 - raw-bytes serializers
        self._ping = self._ch.unary_unary(
            PING_METHOD, request_serializer=ident,
            response_deserializer=ident,
        )
        self._broadcast = self._ch.unary_unary(
            BROADCAST_METHOD, request_serializer=ident,
            response_deserializer=ident,
        )

    def ping(self) -> None:
        self._ping(b"", timeout=5.0)

    def broadcast_tx(self, tx: bytes, timeout: float = 30.0) -> dict:
        raw = self._broadcast(
            proto.field_bytes(1, tx), timeout=timeout
        )
        m = proto.parse(raw)
        check = proto.parse(proto.get1(m, 1, b""))
        txr = proto.parse(proto.get1(m, 2, b""))
        return {
            "check_tx": {
                "code": proto.get1(check, 1, 0),
                "log": proto.get1(check, 3, b"").decode(),
            },
            "tx_result": {
                "code": proto.get1(txr, 1, 0),
                "log": proto.get1(txr, 3, b"").decode(),
            },
            "hash": proto.get1(m, 3, b"").decode(),
            "height": proto.get1(m, 4, 0),
        }

    def close(self) -> None:
        self._ch.close()
