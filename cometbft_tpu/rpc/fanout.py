"""Outbound fan-out plane: one-pass event delivery + height-keyed
commit waiters (ROADMAP item 4, the throughput half of the outbound
serving plane; PR 6 bounded the queues — the safety half).

Three structural fixes over the per-subscriber shape this replaces:

- **FanoutHub** — websocket subscribers are grouped by query shape
  (the query string). Each committed block/tx event is flattened to
  query attributes ONCE per event and JSON-encoded ONCE per matching
  group; every member socket then gets a frame spliced from the
  shared payload plus its pre-rendered subscription-id prefix — N
  subscribers over G shapes pay G serializations, not N. The old
  shape (one bus subscription + one pump task + one ``send_json``
  per subscriber) serialized the same block N times and evaluated N
  predicates per publish.
- **CommitWaiterMap** — ``broadcast_tx_commit`` used to open a bus
  subscription per in-flight RPC, each adding a predicate lambda
  evaluated on EVERY publish. Now ONE sync bus listener resolves
  waiters by a dict lookup on the tx hash, so publish cost is O(1)
  in the number of in-flight commit RPCs — and lossless: a bounded
  subscription queue could shed the one Tx event a waiter needs
  under a >queue-size publish burst (a 2048+-tx block), turning a
  successful commit into a false RPC timeout.
- Per-subscriber overflow keeps the shed-and-count semantics of
  ``types/events.py``: a subscriber that stops draining sheds NEW
  frames (counted on its own bounded queue, aggregated under the
  ``rpc.fanout`` registry entry → ``cometbft_queue_dropped_total``)
  while publishers and every other subscriber stay unaffected.

Spans: ``fanout.deliver`` (one event through attrs → group encodes →
member enqueues) rides the PR 4 span→metrics bridge and is
budget-gated (tools/span_budgets.toml, bench ``rpcfanout`` leg).
"""

from __future__ import annotations

import asyncio
import json
import traceback
from typing import Any, Dict, List, Optional, Set

from ..obs.queues import InstrumentedQueue
from ..types import events as ev
from ..types.events import SUBSCRIPTION_QUEUE_SIZE
from ..utils.tasks import spawn
from . import encoding as enc

# bounded wait for a cancelled writer/drain task to unwind (ASY110):
# a closing socket must not leak a mid-send task into loop teardown,
# and a wedged send must not hang the unsubscribe path either
DETACH_WAIT_S = 2.0


def _event_attrs(e: ev.Event) -> Dict[str, list]:
    """Flatten an Event into query-matchable attributes, mirroring the
    reference's composite keys (tm.event + abci event attributes).
    Computed ONCE per event by the hub, shared across every group."""
    attrs: Dict[str, list] = {"tm.event": [e.type_]}
    for k, v in e.attrs.items():
        attrs.setdefault(f"tm.{k}", []).append(str(v))
    if e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
        attrs["tx.height"] = [str(e.data.get("height", ""))]
        if "hash" in e.attrs:
            attrs["tx.hash"] = [e.attrs["hash"].upper()]
        flat = e.data.get("events_flat")
        if flat is not None:
            # the finalize lane already flattened the attributes once
            # (state/native_finalize.py) — read the shared form
            for type_, kvis in flat:
                for k, v, _ in kvis:
                    attrs.setdefault(f"{type_}.{k}", []).append(v)
            return attrs
        result = e.data.get("result")
        from ..abci.types import attr_kvi

        for evt in getattr(result, "events", []) or []:
            for a in evt.attributes:
                k, v, _ = attr_kvi(a)
                attrs.setdefault(f"{evt.type_}.{k}", []).append(v)
    return attrs


def _event_json(e: ev.Event) -> Dict[str, Any]:
    if e.type_ == ev.EVENT_NEW_BLOCK and isinstance(e.data, dict):
        return {
            "type": "tendermint/event/NewBlock",
            "value": {"block": enc.block_json(e.data["block"])},
        }
    if e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
        return {
            "type": "tendermint/event/Tx",
            "value": {
                "TxResult": {
                    "height": str(e.data["height"]),
                    "index": e.data["index"],
                    "tx": enc.b64(e.data["tx"]),
                    "result": enc.tx_result_json(e.data["result"]),
                }
            },
        }
    return {"type": f"tendermint/event/{e.type_}", "value": {}}


async def _reap_task(task: Optional[asyncio.Future]) -> None:
    """Cancel + await a task with a bound, swallowing ITS
    cancellation but propagating the caller's (PR 10 discipline)."""
    if task is None or task.done():
        return
    task.cancel()
    try:
        # gather(return_exceptions) absorbs the task's own
        # CancelledError; wait_for bounds a send wedged in a dead
        # socket; our own cancellation still propagates
        await asyncio.wait_for(
            asyncio.gather(task, return_exceptions=True), DETACH_WAIT_S
        )
    except asyncio.TimeoutError:
        pass


class FanoutSubscriber:
    """One websocket subscription: a bounded frame queue + a writer
    task. The queue keeps the types/events.py shed-and-count contract
    per subscriber; the writer is the only place this subscriber's
    socket speed matters."""

    __slots__ = ("ws", "sub_id", "query_str", "queue", "task", "_prefix")

    def __init__(
        self,
        ws,
        sub_id,
        query_str: str,
        queue_size: int = SUBSCRIPTION_QUEUE_SIZE,
    ):
        self.ws = ws
        self.sub_id = sub_id
        self.query_str = query_str
        self.queue: InstrumentedQueue = InstrumentedQueue(
            queue_size, name="rpc.fanout.sub"
        )
        self.task: Optional[asyncio.Future] = None
        # the only per-subscriber bytes in a frame: the JSON-RPC
        # envelope with this subscription's id, rendered once here so
        # delivery is a string splice, never a serialization
        self._prefix = (
            '{"jsonrpc": "2.0", "id": ' + json.dumps(sub_id) + ', "result": '
        )

    def offer(self, payload: str) -> bool:
        """Enqueue a frame spliced from the group-shared payload;
        shed-and-count when this subscriber has stopped draining."""
        try:
            self.queue.put_nowait(self._prefix + payload + "}")
            return True
        except asyncio.QueueFull:
            self.queue.count_drop()
            return False


class _Group:
    """Subscribers sharing one query shape: one parse, one match per
    event, one serialization per matching event."""

    __slots__ = ("query_str", "query", "members")

    def __init__(self, query_str: str, query):
        self.query_str = query_str
        self.query = query
        self.members: Set[FanoutSubscriber] = set()


class FanoutHub:
    """One bus subscription fanned out to every websocket subscriber
    in one serialization pass per (event, query shape)."""

    def __init__(self, bus, tracer=None):
        self._bus = bus
        self.tracer = tracer
        self._groups: Dict[str, _Group] = {}
        self._sub = None  # the ONE bus Subscription
        self._drain_task: Optional[asyncio.Future] = None
        self.encodes = 0  # JSON serializations (one per event×group)
        self.delivered = 0  # frames enqueued to subscriber queues
        self.dropped = 0  # frames shed by stalled subscribers

    # --- membership ---------------------------------------------------

    def attach(self, ws, query_str: str, query, sub_id) -> FanoutSubscriber:
        g = self._groups.get(query_str)
        if g is None:
            g = _Group(query_str, query)
            self._groups[query_str] = g
        sub = FanoutSubscriber(ws, sub_id, query_str)
        g.members.add(sub)
        sub.task = spawn(self._writer(sub), name="fanout-writer")
        if self._drain_task is None:
            self._sub = self._bus.subscribe()
            self._drain_task = spawn(self._drain(), name="fanout-drain")
        return sub

    async def detach(self, sub: FanoutSubscriber) -> None:
        """Remove + await the writer (bounded): after this returns no
        task of this subscription can still be mid-send."""
        g = self._groups.get(sub.query_str)
        if g is not None:
            g.members.discard(sub)
            if not g.members:
                self._groups.pop(sub.query_str, None)
        await _reap_task(sub.task)
        sub.task = None
        if not self._groups:
            await self._stop_drain()

    async def detach_all(self, subs) -> None:
        subs = list(subs)
        for sub in subs:
            g = self._groups.get(sub.query_str)
            if g is not None:
                g.members.discard(sub)
                if not g.members:
                    self._groups.pop(sub.query_str, None)
        # concurrent reaps (each wait_for-bounded internally): one
        # DETACH_WAIT_S bounds the whole batch, not per wedged writer
        await asyncio.gather(*(_reap_task(s.task) for s in subs))
        for sub in subs:
            sub.task = None
        if not self._groups:
            await self._stop_drain()

    async def close(self) -> None:
        tasks = [
            s.task
            for g in self._groups.values()
            for s in g.members
            if s.task is not None
        ]
        self._groups.clear()
        t, self._drain_task = self._drain_task, None
        sub, self._sub = self._sub, None
        if sub is not None:
            sub.unsubscribe()
        if t is not None:
            tasks.append(t)
        if tasks:
            # concurrent reaps: each _reap_task is wait_for-bounded at
            # DETACH_WAIT_S internally, so the gather bounds the WHOLE
            # close at DETACH_WAIT_S (not per wedged writer)
            await asyncio.gather(  # bftlint: disable=ASY110 — each reap is wait_for-bounded, so the gather bounds the whole close
                *(_reap_task(task) for task in tasks)
            )

    async def _stop_drain(self) -> None:
        t, self._drain_task = self._drain_task, None
        sub, self._sub = self._sub, None
        if sub is not None:
            sub.unsubscribe()
        await _reap_task(t)

    # --- delivery -----------------------------------------------------

    async def _drain(self) -> None:
        while True:
            event = await self._sub.queue.get()
            try:
                self._deliver(event)
            except Exception:
                # one malformed event must not kill delivery for all
                traceback.print_exc()

    def _deliver(self, event: ev.Event) -> None:
        groups = [g for g in self._groups.values() if g.members]
        if not groups:
            return
        tracer = self.tracer
        span = (
            tracer.span("fanout.deliver", type=event.type_)
            if tracer is not None and tracer.enabled
            else None
        )
        attrs = _event_attrs(event)  # ONCE per event
        ejson = None  # lazy: only events someone matches pay encoding
        n_groups = n_subs = 0
        for g in groups:
            if not g.query.matches(attrs):
                continue
            if ejson is None:
                ejson = _event_json(event)
            payload = json.dumps(
                {"query": g.query_str, "data": ejson, "events": attrs}
            )
            self.encodes += 1
            n_groups += 1
            for sub in g.members:
                if sub.offer(payload):
                    self.delivered += 1
                    n_subs += 1
                else:
                    self.dropped += 1
        if span is not None:
            span.set(groups=n_groups, subs=n_subs)
            span.end()

    async def _writer(self, sub: FanoutSubscriber) -> None:
        try:
            while True:
                frame = await sub.queue.get()
                await sub.ws.send_str(frame)
        except (asyncio.CancelledError, ConnectionError):
            pass
        except Exception:
            traceback.print_exc()

    # --- obs ----------------------------------------------------------

    def queue_stats(self) -> Optional[dict]:
        """Aggregate subscriber backpressure for the obs registry
        (rpc.fanout): depth summed, watermark = worst subscriber,
        drops hub-wide AND MONOTONIC (``self.dropped`` counts every
        shed ever — summing per-member queues would make the counter
        regress when a shedding subscriber detaches, which breaks
        both Prometheus counter semantics and the chaos storm's
        before/after delta). Same convention as events.subs (no
        ``maxsize``: aggregates must not trip the health route's
        full-queue check against a summed depth)."""
        subs = [s for g in self._groups.values() for s in g.members]
        depth = hwm = enqueued = 0
        for s in subs:
            q = s.queue
            depth += q.qsize()
            hwm = max(hwm, q.high_watermark)
            enqueued += q.enqueued
        return {
            "depth": depth,
            "high_watermark": hwm,
            "enqueued": enqueued,
            "dropped": self.dropped,
            "subscribers": len(subs),
            "groups": len(self._groups),
            "encodes": self.encodes,
            "subscriber_maxsize": SUBSCRIPTION_QUEUE_SIZE,
        }


class CommitWaiterMap:
    """Height-keyed commit waiters behind ONE sync bus listener.

    ``register`` parks a future under the tx hash (hex); the listener
    resolves it by dict lookup when the Tx event for that hash is
    published at height commit. Publish cost no longer scales with
    in-flight ``broadcast_tx_commit`` RPCs (each used to add its own
    predicate subscription evaluated on every publish — rpc/core.py
    pre-ISSUE-15); the gRPC broadcast API rides the same map.

    A sync listener rather than a subscription deliberately: a
    bounded subscription queue sheds NEW events when full, and a shed
    Tx event here is not a dropped frame but a waiter that never
    resolves — a committed tx reported as an RPC timeout. The
    listener is O(1) per publish (type check + dict membership) and
    hands resolution to the loop via ``call_soon_threadsafe`` (the
    loop's ready queue, not a bounded asyncio.Queue)."""

    def __init__(self, bus):
        self._bus = bus
        self._waiters: Dict[str, Set[asyncio.Future]] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._listening = False
        self.resolved = 0

    def _ensure(self) -> None:
        if not self._listening:
            self._loop = asyncio.get_running_loop()
            self._bus.add_sync_listener(self._on_publish)
            self._listening = True

    def _on_publish(self, event) -> None:
        """Publish-path hook (any thread): one type check + one dict
        membership probe; resolution always runs on the loop, where
        ``_waiters`` is mutated. register-before-submit gives the
        happens-before that makes the cross-thread read safe."""
        if event.type_ != ev.EVENT_TX:
            return
        key = event.attrs.get("hash")
        if not key or key not in self._waiters:
            return
        loop = self._loop
        if loop is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(self._resolve, key, event)
            except RuntimeError:
                pass  # loop torn down mid-publish (shutdown race)

    def _resolve(self, key: str, event) -> None:
        futs = self._waiters.pop(key, None)
        if not futs:
            return
        for f in futs:
            # a waiter that timed out/cancelled between lookup
            # and resolution is skipped, never errored
            if not f.done():
                self.resolved += 1
                f.set_result(event)

    def register(self, tx_hash_hex: str) -> asyncio.Future:
        """Park a waiter BEFORE submitting the tx (same ordering the
        per-tx subscription had: a commit can never race past)."""
        self._ensure()
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(tx_hash_hex, set()).add(fut)
        return fut

    def unregister(self, tx_hash_hex: str, fut: asyncio.Future) -> None:
        s = self._waiters.get(tx_hash_hex)
        if s is not None:
            s.discard(fut)
            if not s:
                self._waiters.pop(tx_hash_hex, None)

    def size(self) -> int:
        return sum(len(s) for s in self._waiters.values())

    async def close(self) -> None:
        if self._listening:
            self._bus.remove_sync_listener(self._on_publish)
            self._listening = False
        for s in self._waiters.values():
            for f in s:
                if not f.done():
                    f.cancel()
        self._waiters.clear()
