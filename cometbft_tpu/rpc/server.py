"""JSON-RPC 2.0 server over HTTP + WebSocket (reference
rpc/jsonrpc/server/): POST bodies, GET URI params, and a `/websocket`
endpoint with subscribe/unsubscribe event streaming backed by the
node's EventBus through the outbound fan-out plane (rpc/fanout.py —
one serialization pass per event × query shape, not per
subscriber)."""

from __future__ import annotations

import asyncio
import json
import traceback
from typing import Any, Dict, Optional

from aiohttp import WSMsgType, web

from ..utils.pubsub_query import parse as parse_query
from . import core
from .env import Environment
from .fanout import FanoutHub, _event_attrs, _event_json  # noqa: F401
# _event_attrs/_event_json re-exported for compat: they lived here
# before the fan-out plane (tests and the bench baseline import them)


def _rpc_response(id_, result=None, error=None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        out["error"] = error
    else:
        out["result"] = result
    return out


def _rpc_error(code: int, message: str, data: str = "") -> Dict[str, Any]:
    e: Dict[str, Any] = {"code": code, "message": message}
    if data:
        e["data"] = data
    return e


class RPCServer:
    def __init__(self, env: Environment):
        self.env = env
        # outbound fan-out plane: ONE bus subscription, one
        # serialization per event × query shape (docs/PERF.md)
        self.fanout = FanoutHub(
            env.event_bus, tracer=getattr(env, "tracer", None)
        )
        self.app = web.Application()
        self.app.router.add_post("/", self._handle_post)
        self.app.router.add_get("/websocket", self._handle_ws)
        self.app.router.add_get("/{method}", self._handle_get)
        self._runner: Optional[web.AppRunner] = None
        self._site = None
        self.listen_addr = ""

    # --- lifecycle ----------------------------------------------------

    async def start(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        for p in ("tcp://", "http://"):
            if host.startswith(p):
                host = host[len(p):]
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host or "127.0.0.1", int(port))
        await self._site.start()
        srv_sockets = self._site._server.sockets  # noqa: SLF001
        h, p = srv_sockets[0].getsockname()[:2]
        self.listen_addr = f"{h}:{p}"

    def _unsafe_enabled(self) -> bool:
        cfg = getattr(self.env, "config", None)
        return bool(cfg and getattr(cfg.rpc, "unsafe", False))

    async def stop(self) -> None:
        if self._runner:
            # bounded (ASY110): aiohttp cleanup waits on open
            # websocket handlers — a stuck subscriber must not wedge
            # node shutdown
            try:
                await asyncio.wait_for(self._runner.cleanup(), 5.0)
            except asyncio.TimeoutError:
                pass
        # fan-out plane after the handlers: their exit paths detach
        # cleanly; close() reaps whatever a breached cleanup left
        try:
            await asyncio.wait_for(self.fanout.close(), 5.0)
        except asyncio.TimeoutError:
            pass

    # --- dispatch -----------------------------------------------------

    async def _call(self, method: str, params: Dict[str, Any]):
        fn = core.ROUTES.get(method)
        if fn is None and self._unsafe_enabled():
            fn = core.UNSAFE_ROUTES.get(method)
        if fn is None:
            raise core.RPCError(-32601, f"method {method!r} not found")
        res = fn(self.env, **params)
        if asyncio.iscoroutine(res):
            res = await res
        return res

    async def _handle_post(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except asyncio.CancelledError:
            raise
        except (ValueError, UnicodeDecodeError):
            return web.json_response(
                _rpc_response(None, error=_rpc_error(-32700, "parse error"))
            )
        batch = body if isinstance(body, list) else [body]
        out = []
        for req in batch:
            id_ = req.get("id")
            try:
                result = await self._call(
                    req.get("method", ""), req.get("params") or {}
                )
                out.append(_rpc_response(id_, result))
            except core.RPCError as e:
                out.append(
                    _rpc_response(id_, error=_rpc_error(e.code, str(e), e.data))
                )
            except TypeError as e:
                out.append(
                    _rpc_response(id_, error=_rpc_error(-32602, str(e)))
                )
            except asyncio.CancelledError:
                raise  # server stop cancels in-flight handlers
            except Exception as e:
                traceback.print_exc()
                out.append(
                    _rpc_response(
                        id_, error=_rpc_error(-32603, f"internal: {e}")
                    )
                )
        payload = out if isinstance(body, list) else out[0]
        return web.json_response(payload)

    async def _handle_get(self, request: web.Request) -> web.Response:
        method = request.match_info["method"]
        params = {k: v for k, v in request.query.items()}
        # strip the reference's quoted-string URI convention
        for k, v in params.items():
            if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                params[k] = v[1:-1]
        try:
            result = await self._call(method, params)
            return web.json_response(_rpc_response(-1, result))
        except core.RPCError as e:
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(e.code, str(e), e.data))
            )
        except TypeError as e:
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(-32602, str(e)))
            )
        except asyncio.CancelledError:
            raise  # server stop cancels in-flight handlers
        except Exception as e:
            traceback.print_exc()
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(-32603, f"internal: {e}"))
            )

    # --- websocket subscriptions ---------------------------------------

    async def _handle_ws(self, request: web.Request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        # query string -> FanoutSubscriber: the hub owns the bus
        # subscription + delivery; this handler only manages
        # membership for this socket's lifetime
        subs: Dict[str, object] = {}

        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    req = json.loads(msg.data)
                except Exception:
                    await ws.send_json(
                        _rpc_response(
                            None, error=_rpc_error(-32700, "parse error")
                        )
                    )
                    continue
                id_ = req.get("id")
                method = req.get("method", "")
                params = req.get("params") or {}
                if method == "subscribe":
                    qs = str(params.get("query", ""))
                    if qs in subs:
                        # reference errors on duplicate subscriptions;
                        # silently replacing would leak the old one
                        await ws.send_json(
                            _rpc_response(
                                id_,
                                error=_rpc_error(
                                    -32603, "already subscribed"
                                ),
                            )
                        )
                        continue
                    try:
                        q = parse_query(qs)
                    except ValueError as e:
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(-32602, str(e))
                            )
                        )
                        continue
                    subs[qs] = self.fanout.attach(ws, qs, q, id_)
                    await ws.send_json(_rpc_response(id_, {}))
                elif method == "unsubscribe":
                    qs = str(params.get("query", ""))
                    sub = subs.pop(qs, None)
                    if sub is not None:
                        # awaits the cancelled writer (bounded): no
                        # mid-send task may outlive the subscription
                        await self.fanout.detach(sub)
                    await ws.send_json(_rpc_response(id_, {}))
                elif method == "unsubscribe_all":
                    await self.fanout.detach_all(subs.values())
                    subs.clear()
                    await ws.send_json(_rpc_response(id_, {}))
                else:
                    try:
                        result = await self._call(method, params)
                        await ws.send_json(_rpc_response(id_, result))
                    except core.RPCError as e:
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(e.code, str(e))
                            )
                        )
                    except asyncio.CancelledError:
                        raise  # server stop cancels the ws handler
                    except Exception as e:
                        traceback.print_exc()
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(-32603, str(e))
                            )
                        )
        finally:
            # handler exit (socket closed / server cleanup): detach
            # AND await every writer task bounded — fire-and-forget
            # cancel here used to leak mid-send tasks into loop
            # teardown (ASY110)
            await self.fanout.detach_all(subs.values())
        return ws
