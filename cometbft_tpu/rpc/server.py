"""JSON-RPC 2.0 server over HTTP + WebSocket (reference
rpc/jsonrpc/server/): POST bodies, GET URI params, and a `/websocket`
endpoint with subscribe/unsubscribe event streaming backed by the
node's EventBus and the pubsub query language."""

from __future__ import annotations

import asyncio
import json
import traceback
from typing import Any, Dict, Optional

from aiohttp import WSMsgType, web

from ..types import events as ev
from ..utils.pubsub_query import parse as parse_query
from . import core
from . import encoding as enc
from .env import Environment


def _rpc_response(id_, result=None, error=None) -> Dict[str, Any]:
    out: Dict[str, Any] = {"jsonrpc": "2.0", "id": id_}
    if error is not None:
        out["error"] = error
    else:
        out["result"] = result
    return out


def _rpc_error(code: int, message: str, data: str = "") -> Dict[str, Any]:
    e: Dict[str, Any] = {"code": code, "message": message}
    if data:
        e["data"] = data
    return e


def _event_attrs(e: ev.Event) -> Dict[str, list]:
    """Flatten an Event into query-matchable attributes, mirroring the
    reference's composite keys (tm.event + abci event attributes)."""
    attrs: Dict[str, list] = {"tm.event": [e.type_]}
    for k, v in e.attrs.items():
        attrs.setdefault(f"tm.{k}", []).append(str(v))
    if e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
        attrs["tx.height"] = [str(e.data.get("height", ""))]
        if "hash" in e.attrs:
            attrs["tx.hash"] = [e.attrs["hash"].upper()]
        result = e.data.get("result")
        from ..abci.types import attr_kvi

        for evt in getattr(result, "events", []) or []:
            for a in evt.attributes:
                k, v, _ = attr_kvi(a)
                attrs.setdefault(f"{evt.type_}.{k}", []).append(v)
    return attrs


def _event_json(e: ev.Event) -> Dict[str, Any]:
    if e.type_ == ev.EVENT_NEW_BLOCK and isinstance(e.data, dict):
        return {
            "type": "tendermint/event/NewBlock",
            "value": {"block": enc.block_json(e.data["block"])},
        }
    if e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
        return {
            "type": "tendermint/event/Tx",
            "value": {
                "TxResult": {
                    "height": str(e.data["height"]),
                    "index": e.data["index"],
                    "tx": enc.b64(e.data["tx"]),
                    "result": enc.tx_result_json(e.data["result"]),
                }
            },
        }
    return {"type": f"tendermint/event/{e.type_}", "value": {}}


class RPCServer:
    def __init__(self, env: Environment):
        self.env = env
        self.app = web.Application()
        self.app.router.add_post("/", self._handle_post)
        self.app.router.add_get("/websocket", self._handle_ws)
        self.app.router.add_get("/{method}", self._handle_get)
        self._runner: Optional[web.AppRunner] = None
        self._site = None
        self.listen_addr = ""

    # --- lifecycle ----------------------------------------------------

    async def start(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        for p in ("tcp://", "http://"):
            if host.startswith(p):
                host = host[len(p):]
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host or "127.0.0.1", int(port))
        await self._site.start()
        srv_sockets = self._site._server.sockets  # noqa: SLF001
        h, p = srv_sockets[0].getsockname()[:2]
        self.listen_addr = f"{h}:{p}"

    def _unsafe_enabled(self) -> bool:
        cfg = getattr(self.env, "config", None)
        return bool(cfg and getattr(cfg.rpc, "unsafe", False))

    async def stop(self) -> None:
        if self._runner:
            # bounded (ASY110): aiohttp cleanup waits on open
            # websocket handlers — a stuck subscriber must not wedge
            # node shutdown
            try:
                await asyncio.wait_for(self._runner.cleanup(), 5.0)
            except asyncio.TimeoutError:
                pass

    # --- dispatch -----------------------------------------------------

    async def _call(self, method: str, params: Dict[str, Any]):
        fn = core.ROUTES.get(method)
        if fn is None and self._unsafe_enabled():
            fn = core.UNSAFE_ROUTES.get(method)
        if fn is None:
            raise core.RPCError(-32601, f"method {method!r} not found")
        res = fn(self.env, **params)
        if asyncio.iscoroutine(res):
            res = await res
        return res

    async def _handle_post(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except asyncio.CancelledError:
            raise
        except (ValueError, UnicodeDecodeError):
            return web.json_response(
                _rpc_response(None, error=_rpc_error(-32700, "parse error"))
            )
        batch = body if isinstance(body, list) else [body]
        out = []
        for req in batch:
            id_ = req.get("id")
            try:
                result = await self._call(
                    req.get("method", ""), req.get("params") or {}
                )
                out.append(_rpc_response(id_, result))
            except core.RPCError as e:
                out.append(
                    _rpc_response(id_, error=_rpc_error(e.code, str(e), e.data))
                )
            except TypeError as e:
                out.append(
                    _rpc_response(id_, error=_rpc_error(-32602, str(e)))
                )
            except asyncio.CancelledError:
                raise  # server stop cancels in-flight handlers
            except Exception as e:
                traceback.print_exc()
                out.append(
                    _rpc_response(
                        id_, error=_rpc_error(-32603, f"internal: {e}")
                    )
                )
        payload = out if isinstance(body, list) else out[0]
        return web.json_response(payload)

    async def _handle_get(self, request: web.Request) -> web.Response:
        method = request.match_info["method"]
        params = {k: v for k, v in request.query.items()}
        # strip the reference's quoted-string URI convention
        for k, v in params.items():
            if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                params[k] = v[1:-1]
        try:
            result = await self._call(method, params)
            return web.json_response(_rpc_response(-1, result))
        except core.RPCError as e:
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(e.code, str(e), e.data))
            )
        except TypeError as e:
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(-32602, str(e)))
            )
        except asyncio.CancelledError:
            raise  # server stop cancels in-flight handlers
        except Exception as e:
            traceback.print_exc()
            return web.json_response(
                _rpc_response(-1, error=_rpc_error(-32603, f"internal: {e}"))
            )

    # --- websocket subscriptions ---------------------------------------

    async def _handle_ws(self, request: web.Request):
        ws = web.WebSocketResponse()
        await ws.prepare(request)
        subs: Dict[str, tuple] = {}  # query string -> (Subscription, task)

        async def pump(query_str: str, sub, sub_id):
            try:
                while True:
                    event = await sub.queue.get()
                    attrs = _event_attrs(event)
                    if not sub.query_obj.matches(attrs):
                        continue
                    await ws.send_json(
                        _rpc_response(
                            sub_id,
                            {
                                "query": query_str,
                                "data": _event_json(event),
                                "events": attrs,
                            },
                        )
                    )
            except (asyncio.CancelledError, ConnectionError):
                pass
            except Exception:
                traceback.print_exc()

        try:
            async for msg in ws:
                if msg.type != WSMsgType.TEXT:
                    continue
                try:
                    req = json.loads(msg.data)
                except Exception:
                    await ws.send_json(
                        _rpc_response(
                            None, error=_rpc_error(-32700, "parse error")
                        )
                    )
                    continue
                id_ = req.get("id")
                method = req.get("method", "")
                params = req.get("params") or {}
                if method == "subscribe":
                    qs = str(params.get("query", ""))
                    if qs in subs:
                        # reference errors on duplicate subscriptions;
                        # silently replacing would leak the old one
                        await ws.send_json(
                            _rpc_response(
                                id_,
                                error=_rpc_error(
                                    -32603, "already subscribed"
                                ),
                            )
                        )
                        continue
                    try:
                        q = parse_query(qs)
                    except ValueError as e:
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(-32602, str(e))
                            )
                        )
                        continue
                    sub = self.env.event_bus.subscribe()
                    sub.query_obj = q
                    task = asyncio.create_task(pump(qs, sub, id_))
                    subs[qs] = (sub, task)
                    await ws.send_json(_rpc_response(id_, {}))
                elif method == "unsubscribe":
                    qs = str(params.get("query", ""))
                    pair = subs.pop(qs, None)
                    if pair:
                        pair[0].unsubscribe()
                        pair[1].cancel()
                    await ws.send_json(_rpc_response(id_, {}))
                elif method == "unsubscribe_all":
                    for sub, task in subs.values():
                        sub.unsubscribe()
                        task.cancel()
                    subs.clear()
                    await ws.send_json(_rpc_response(id_, {}))
                else:
                    try:
                        result = await self._call(method, params)
                        await ws.send_json(_rpc_response(id_, result))
                    except core.RPCError as e:
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(e.code, str(e))
                            )
                        )
                    except asyncio.CancelledError:
                        raise  # server stop cancels the ws handler
                    except Exception as e:
                        traceback.print_exc()
                        await ws.send_json(
                            _rpc_response(
                                id_, error=_rpc_error(-32603, str(e))
                            )
                        )
        finally:
            for sub, task in subs.values():
                sub.unsubscribe()
                task.cancel()
        return ws
