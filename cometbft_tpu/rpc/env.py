"""RPC Environment: the node internals the route handlers read
(reference rpc/core/env.go + node/node.go:754-788 ConfigureRPC)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Environment:
    chain_id: str = ""
    block_store: object = None
    state_store: object = None
    mempool: object = None
    evidence_pool: object = None
    consensus_state: object = None  # may be None (inspect mode)
    event_bus: object = None
    proxy: object = None  # AppConns
    genesis: object = None
    tx_indexer: object = None
    block_indexer: object = None
    switch: object = None  # p2p switch, may be None
    node_info: object = None
    privval_pubkey: object = None
    config: object = None
    mempool_reactor: object = None  # for app-mempool local submission
    # runtime health plane handles (obs/, docs/OBS.md); may be None
    # (inspect mode / watchdog disabled)
    loop_watchdog: object = None
    queues: object = None  # obs.QueueRegistry
    # () -> light.serving.VerifiedHeaderCache | None, read lazily:
    # the node creates its shared header cache when statesync (or a
    # co-resident serving plane) first needs it, which can be after
    # this Environment was built
    light_header_cache_fn: object = None
    # outbound fan-out plane (rpc/fanout.py, ISSUE 15)
    tracer: object = None  # node trace ring (fanout.* spans)
    indexer_service: object = None  # batched per-height index drain
    # storage lifecycle plane (store/retention.py): health verdict +
    # status surfacing; may be None (inspect mode)
    retention: object = None
    # serving-fleet plane (cometbft_tpu/fleet, docs/FLEET.md): the
    # SessionRouter when this node fronts a fleet (fleet_status route,
    # health fleet verdict); replica_lag_fn () -> int reports how far
    # THIS node's served height trails the committee head when it runs
    # as a follower replica (status/health replica_lag_heights)
    fleet_router: object = None
    replica_lag_fn: object = None
    # height-keyed commit waiters, shared by broadcast_tx_commit AND
    # the gRPC broadcast API: lazily built so inspect-mode envs never
    # subscribe (field, not ctor arg — see commit_waiters())
    _commit_waiters: object = None

    def commit_waiters(self):
        """The ONE CommitWaiterMap for this env (one lossless sync
        bus listener total, O(1) publish cost in in-flight commit
        RPCs)."""
        if self._commit_waiters is None:
            from .fanout import CommitWaiterMap

            self._commit_waiters = CommitWaiterMap(self.event_bus)
        return self._commit_waiters

    async def close(self) -> None:
        """Release env-owned background plumbing (the commit-waiter
        drain); bounded (ASY110), safe to call twice."""
        import asyncio

        cw = self._commit_waiters
        self._commit_waiters = None
        if cw is not None:
            try:
                await asyncio.wait_for(cw.close(), 5.0)
            except asyncio.TimeoutError:
                pass

    def submit_tx(self, tx: bytes):
        """CheckTx + (app-mempool) gossip: RPC broadcast entry point
        (synchronous direct path; the async routes prefer
        submit_tx_async below)."""
        r = self.mempool_reactor
        if r is not None and hasattr(r, "submit_local"):
            return r.submit_local(tx)
        return self.mempool.check_tx(tx)

    def _ingest(self):
        """The mempool reactor's running ingest queue, or None."""
        ing = getattr(self.mempool_reactor, "ingest", None)
        return ing if ing is not None and ing.running else None

    async def submit_tx_async(self, tx: bytes):
        """Broadcast entry for async routes: enqueue on the mempool
        ingest plane (batched CheckTx, event loop never blocks) and
        await the verdict; degrade to the direct path off-loop when
        the plane isn't running (nop/app mempool, inspect mode)."""
        import asyncio

        ing = self._ingest()
        if ing is not None:
            return await ing.submit(tx)
        return await asyncio.to_thread(self.submit_tx, tx)

    def submit_tx_nowait(self, tx: bytes) -> None:
        """Fire-and-forget broadcast (broadcast_tx_async route)."""
        ing = self._ingest()
        if ing is not None:
            # a full queue DROPS the tx (counted by the queue): that
            # is the overload backpressure the bounded queue exists
            # for — spawning direct-check tasks here would grow
            # unboundedly on exactly the flood being shed
            ing.submit_nowait(tx)
            return
        import asyncio

        from ..utils.tasks import spawn

        spawn(
            asyncio.to_thread(self.submit_tx, tx),
            name="broadcast-tx-async",
        )

    @classmethod
    def from_node(cls, node) -> "Environment":
        p = node.parts
        return cls(
            chain_id=node.genesis.chain_id,
            block_store=p.block_store,
            state_store=p.state_store,
            mempool=p.mempool,
            evidence_pool=p.evpool,
            consensus_state=p.cs,
            event_bus=p.event_bus,
            proxy=p.proxy,
            genesis=node.genesis,
            tx_indexer=getattr(p, "tx_indexer", None),
            block_indexer=getattr(p, "block_indexer", None),
            switch=node.switch,
            node_info=node.node_info,
            privval_pubkey=(
                p.privval.pub_key() if p.privval is not None else None
            ),
            config=node.config,
            mempool_reactor=node.mempool_reactor,
            loop_watchdog=getattr(node, "loop_watchdog", None),
            queues=getattr(node, "queues", None),
            light_header_cache_fn=lambda: getattr(
                node, "light_header_cache", None
            ),
            tracer=p.tracer,
            indexer_service=getattr(p, "indexer_service", None),
            retention=getattr(p, "retention", None),
        )
