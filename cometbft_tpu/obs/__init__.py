"""Runtime health plane (docs/OBS.md).

The connective tissue between the tracing plane (PR 4) and every perf
regression gate: the tracer says how long a span took, this package
says *why* the tail is slow and *whether* it is allowed to be.

Four coordinated pieces:

- **LoopWatchdog** (obs/watchdog.py) — per-node event-loop scheduling
  lag measured by a monotonic heartbeat task, plus a **flight
  recorder**: when the loop stalls past a threshold, a monitor thread
  snapshots every thread's frame and every asyncio task's stack INTO
  THE TRACE RING as instant events, so the offending stack appears
  right next to the stalled spans in Perfetto.
- **SamplingProfiler** (obs/profiler.py) — stdlib sampling profiler
  (sys._current_frames at a configurable Hz) with folded-stack
  output; attached to bench runs and chaos violation dumps.
- **InstrumentedQueue / QueueRegistry** (obs/queues.py) —
  backpressure telemetry for every bounded queue in the hot planes:
  depth, high watermark, unified shed/drop counters.
- **span budgets** (obs/budget.py) — declarative per-span-kind
  p95/p99 budgets in tools/span_budgets.toml, evaluated by
  ``trace summarize --budget``, enforced in chaos runs and recorded
  in bench JSON.
"""

from .budget import evaluate_budgets, format_verdicts, load_budgets
from .profiler import SamplingProfiler
from .queues import InstrumentedGate, InstrumentedQueue, QueueRegistry
from .shutdown import ShutdownGuard
from .watchdog import LoopWatchdog

__all__ = [
    "InstrumentedGate",
    "InstrumentedQueue",
    "LoopWatchdog",
    "QueueRegistry",
    "SamplingProfiler",
    "ShutdownGuard",
    "evaluate_budgets",
    "format_verdicts",
    "load_budgets",
]
