"""Span-budget engine: declarative p95/p99 latency budgets per span
kind, evaluated against trace summaries.

Budgets live in a checked-in TOML (tools/span_budgets.toml):

    [budget."consensus.step"]
    p95_ms = 2000.0
    p99_ms = 15000.0
    min_count = 10       # skip kinds with too few samples to judge

    [budget."wal.fsync"]
    p99_ms = 400.0

Evaluation runs over the exact summary shape trace/summary.summarize
produces ({node: {span: {count, p50_ms, p95_ms, p99_ms, ...}}}), one
verdict row per (node, span, metric). Consumers:

- ``python -m cometbft_tpu.trace summarize --budget [FILE]`` — prints
  the verdict table, exits 2 on any violation;
- chaos runs (chaos/net.run_schedule budget_file=...) — a violation
  dumps the traces and fails the run's exit code;
- ``bench.py --trace`` — verdicts embedded per config in the result
  JSON, the regression gate future perf PRs diff against.

Budgets gate *recorded seeds on this box*: numbers carry the ±30%
run-to-run variance headroom the bench memos document, so a pass is
reproducible and a failure means a real regression, not noise.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

try:
    import tomllib
except ImportError:  # pragma: no cover - py<3.11: same-API backport
    try:
        import tomli as tomllib
    except ImportError:
        tomllib = None

# metrics a budget entry may bound, in report order
_METRICS = ("p50_ms", "p95_ms", "p99_ms", "max_ms")

DEFAULT_BUDGET_PATH = os.path.join("tools", "span_budgets.toml")


def default_budget_file(repo_root: Optional[str] = None) -> str:
    """Anchored on the PACKAGE location, not the cwd: the --budget
    default must resolve no matter where the CLI is invoked from (a
    cwd-relative miss would surface as a bogus 'budget evaluation
    failed' violation in chaos reports)."""
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(root, DEFAULT_BUDGET_PATH)


def load_budgets(path: str) -> Dict[str, dict]:
    """{span_kind: {p95_ms: float, ..., min_count: int}} from TOML."""
    if tomllib is None:  # pragma: no cover - no TOML reader tier
        raise RuntimeError("no tomllib/tomli available to read budgets")
    with open(path, "rb") as f:
        raw = tomllib.load(f)
    out: Dict[str, dict] = {}
    for span, entry in (raw.get("budget") or {}).items():
        if not isinstance(entry, dict):
            raise ValueError(f"budget.{span!r}: expected a table")
        known = set(_METRICS) | {"min_count"}
        bad = set(entry) - known
        if bad:
            raise ValueError(
                f"budget.{span!r}: unknown keys {sorted(bad)} "
                f"(allowed: {sorted(known)})"
            )
        out[span] = dict(entry)
    return out


def evaluate_budgets(
    summary: Dict[str, dict], budgets: Dict[str, dict]
) -> List[dict]:
    """One verdict row per (node, span, metric) that a budget bounds.

    Rows: {node, span, metric, actual_ms, budget_ms, count, ok}.
    Span kinds below their ``min_count`` (default 1) are skipped —
    a 2-sample p99 is an anecdote, not a tail."""
    rows: List[dict] = []
    for node in sorted(summary):
        kinds = summary[node]
        for span, budget in sorted(budgets.items()):
            stats = kinds.get(span)
            if stats is None or span == "_counters":
                continue
            count = int(stats.get("count", 0))
            if count < int(budget.get("min_count", 1)):
                continue
            for metric in _METRICS:
                limit = budget.get(metric)
                if limit is None:
                    continue
                actual = float(stats.get(metric, 0.0))
                rows.append(
                    {
                        "node": node,
                        "span": span,
                        "metric": metric,
                        "actual_ms": actual,
                        "budget_ms": float(limit),
                        "count": count,
                        "ok": actual <= float(limit),
                    }
                )
    return rows


def budgets_ok(verdicts: List[dict]) -> bool:
    return all(v["ok"] for v in verdicts)


def format_verdicts(verdicts: List[dict]) -> str:
    """Aligned verdict table; violations first so they can't scroll
    away in CI logs."""
    if not verdicts:
        return "no span kinds matched a budget (nothing evaluated)"
    hdr = (
        f"{'verdict':<8} {'node':<10} {'span':<30} {'metric':<8} "
        f"{'actual ms':>10} {'budget ms':>10} {'count':>7}"
    )
    lines = [hdr]
    for v in sorted(verdicts, key=lambda v: (v["ok"], v["node"], v["span"])):
        lines.append(
            f"{'OK' if v['ok'] else 'OVER':<8} {v['node']:<10} "
            f"{v['span']:<30} {v['metric']:<8} "
            f"{v['actual_ms']:>10.3f} {v['budget_ms']:>10.3f} "
            f"{v['count']:>7}"
        )
    n_over = sum(1 for v in verdicts if not v["ok"])
    lines.append(
        f"budget verdict: "
        + (
            "PASS" if n_over == 0
            else f"FAIL ({n_over}/{len(verdicts)} over budget)"
        )
    )
    return "\n".join(lines)
