"""Stdlib sampling profiler with folded-stack (flamegraph) output.

A background daemon thread captures ``sys._current_frames()`` at a
configurable Hz and aggregates whole stacks into a
``{folded_stack: count}`` dict, where a folded stack is the
semicolon-joined ``module:func`` chain outermost-first — exactly the
"collapsed" format flamegraph.pl / speedscope / inferno consume.

Why not cProfile: its tracing hook attaches per-thread (the calling
thread here would just be sleeping) and its overhead on a GIL-bound
2-vCPU box distorts the very tails we are attributing. Sampling at
the default ~50 Hz costs well under 1% (the bench ingest leg asserts
<3% headroom, bench.py); each sample walks every thread's frames
once, bounded depth, no allocation beyond the counter dict.

Used by: ``bench.py`` (attached automatically, folded profile embedded
in the result JSON), chaos runs (profile.folded written beside the
trace dumps on violation), and the pprof-style debug server.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional

_DEFAULT_HZ = 47.0  # off the round 50 so it never beats with timers
_MAX_DEPTH = 40


def _fold(frame, depth: int = _MAX_DEPTH) -> str:
    """Outermost-first module:func;module:func;... for one frame."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < depth:
        code = f.f_code
        mod = code.co_filename.rsplit("/", 1)[-1]
        if mod.endswith(".py"):
            mod = mod[:-3]
        parts.append(f"{mod}:{code.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """start()/stop() or use as a context manager; thread-safe reads.

    ``counts`` maps folded stack -> samples; ``folded()`` renders the
    flamegraph-collapsed text ("stack count" per line, descending)."""

    def __init__(
        self,
        hz: float = _DEFAULT_HZ,
        include_idle: bool = False,
        max_stacks: int = 20_000,
    ) -> None:
        self.hz = max(1.0, hz)
        self.include_idle = include_idle
        self.max_stacks = max_stacks
        self.counts: Dict[str, int] = {}
        self.samples = 0
        self.started_ns = 0
        self.wall_s = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # --- lifecycle ----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "SamplingProfiler":
        if self.running:
            return self
        self._stop.clear()
        self.started_ns = time.monotonic_ns()
        self._thread = threading.Thread(
            target=self._run, name="obs-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None and th is not threading.current_thread():
            th.join(timeout=2.0)
        if self.started_ns:
            self.wall_s = (time.monotonic_ns() - self.started_ns) / 1e9
        return self

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # --- sampling -----------------------------------------------------

    def sample_once(self) -> None:
        """One capture of every thread's stack (public so the overhead
        guard test can bound its cost directly)."""
        own = threading.get_ident()
        counts = self.counts
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            key = _fold(frame)
            if not key:
                continue
            if not self.include_idle:
                # parked threads (the selector idle-wait, Event.wait
                # loops, pool workers waiting for work) are noise at
                # every sample; the RUNNING callbacks are what
                # attribution needs. Judge by the INNERMOST frame.
                leaf = key.rsplit(";", 1)[-1]
                if leaf in (
                    "threading:wait",
                    "selectors:select",
                    "threading:_wait_for_tstate_lock",
                ):
                    continue
            if key in counts:
                counts[key] += 1
            elif len(counts) < self.max_stacks:
                counts[key] = 1
        self.samples += 1

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            with self._lock:
                try:
                    self.sample_once()
                except Exception:
                    # a torn frame read degrades one sample, never
                    # the profiled process
                    continue

    # --- output -------------------------------------------------------

    def folded(self, top: Optional[int] = None) -> str:
        """Flamegraph-collapsed text: one "stack count" per line,
        heaviest first."""
        with self._lock:
            items = sorted(self.counts.items(), key=lambda kv: -kv[1])
        if top is not None:
            items = items[:top]
        return "\n".join(f"{stack} {n}" for stack, n in items)

    def top_lines(self, n: int = 20) -> List[dict]:
        """Heaviest folded stacks as JSON-able rows (bench embeds).
        ``pct`` is the share of recorded THREAD-samples: one capture
        contributes one count per running thread, and several threads
        can share a folded stack, so the capture count is the wrong
        denominator."""
        with self._lock:
            items = sorted(self.counts.items(), key=lambda kv: -kv[1])
            total = max(1, sum(self.counts.values()))
        return [
            {
                "stack": stack,
                "samples": cnt,
                "pct": round(100.0 * cnt / total, 1),
            }
            for stack, cnt in items[:n]
        ]

    def write_folded(self, path: str) -> str:
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            header = (
                f"# {self.samples} samples at {self.hz:g} Hz over "
                f"{self.wall_s:.1f}s\n"
            )
            f.write(header)
            f.write(self.folded())
            f.write("\n")
        return path
