"""Backpressure telemetry: instrumented bounded queues + a per-node
registry.

Every bounded queue in the hot planes (mempool ingest, p2p per-peer
send channels, consensus inbox, event-bus subscribers, blocksync pool
window, parallel-verify dispatch) reports three things the RPC
``health`` route and /metrics need:

- **depth** — current backlog (a queue pinned at depth ~maxsize is
  the upstream cause of every "mysteriously slow" span downstream);
- **high watermark** — worst backlog since start (a queue that
  *touched* its bound under a burst sheds next time);
- **dropped** — unified shed counter: every plane that sheds under
  overload counts it here (``count_drop``), so "are we losing work"
  is one number per queue instead of per-plane conventions.

``InstrumentedQueue`` subclasses ``asyncio.Queue``; ``put()`` funnels
through ``put_nowait`` in CPython, so overriding the latter covers
both entries with two attribute writes and a compare — bounded by the
overhead guard in tests/test_obs.py.

``QueueRegistry`` holds callables, not queues: planes recreate their
queues across start/stop (the ingest queue) or fan out per peer (p2p
send channels), so an entry is a ``stats_fn() -> dict | None``
evaluated at read time.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Callable, Dict, Optional


class InstrumentedQueue(asyncio.Queue):
    """asyncio.Queue + depth/high-watermark/shed telemetry."""

    def __init__(self, maxsize: int = 0, *, name: str = "") -> None:
        super().__init__(maxsize)
        self.name = name
        self.high_watermark = 0
        self.enqueued = 0
        self.dropped = 0

    def put_nowait(self, item) -> None:
        super().put_nowait(item)
        self.enqueued += 1
        n = self.qsize()
        if n > self.high_watermark:
            self.high_watermark = n

    def count_drop(self, n: int = 1) -> None:
        """Callers that shed under overload (QueueFull, overflow
        policies) count the loss here — the unified drop counter."""
        self.dropped += n

    def stats(self) -> dict:
        return {
            "depth": self.qsize(),
            "high_watermark": self.high_watermark,
            "enqueued": self.enqueued,
            "dropped": self.dropped,
            "maxsize": self.maxsize,
        }


class InstrumentedGate:
    """Thread-safe bounded-concurrency gate with the same stats
    contract as InstrumentedQueue (depth = current holders).

    The light-client serving plane admits request work through one of
    these (light/serving.py): ``try_enter`` never blocks — overload is
    a SHED (counted in ``dropped``), not a queue, so a thousand
    stalled sessions can't pile unbounded work behind a slow verify.
    Registered in a QueueRegistry exactly like a queue; ``maxsize``
    keeps the health route's depth>=maxsize overload convention.
    """

    def __init__(self, limit: int, *, name: str = "") -> None:
        if limit < 1:
            raise ValueError("gate limit must be >= 1")
        self.name = name
        self.limit = limit
        self._cond = threading.Condition()
        self._holders = 0
        self.high_watermark = 0
        self.entered = 0
        self.dropped = 0

    def _admit_locked(self) -> None:
        self._holders += 1
        self.entered += 1
        if self._holders > self.high_watermark:
            self.high_watermark = self._holders

    def try_enter(self) -> bool:
        with self._cond:
            if self._holders >= self.limit:
                self.dropped += 1
                return False
            self._admit_locked()
            return True

    def enter(self, timeout: float = 0.0) -> bool:
        """Admit, waiting up to ``timeout`` seconds for a slot (a
        BOUNDED wait absorbs admission bursts without letting work
        pile unbounded); past the timeout the request is shed and
        counted."""
        with self._cond:
            if self._holders < self.limit:
                self._admit_locked()
                return True
            if timeout > 0 and self._cond.wait_for(
                lambda: self._holders < self.limit, timeout=timeout
            ):
                self._admit_locked()
                return True
            self.dropped += 1
            return False

    def exit(self) -> None:
        with self._cond:
            if self._holders > 0:
                self._holders -= 1
            self._cond.notify()

    def count_drop(self, n: int = 1) -> None:
        with self._cond:
            self.dropped += n

    def wait_idle(self, timeout: float) -> bool:
        """Bounded wait for every holder to exit (drain paths: the
        caller stops admitting first, then waits in-flight work out)."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._holders == 0, timeout=timeout
            )

    def depth(self) -> int:
        return self._holders

    def stats(self) -> dict:
        return {
            "depth": self._holders,
            "high_watermark": self.high_watermark,
            "enqueued": self.entered,
            "dropped": self.dropped,
            "maxsize": self.limit,
        }


StatsFn = Callable[[], Optional[dict]]


class QueueRegistry:
    """Named, callback-backed queue stats for one node."""

    def __init__(self) -> None:
        self._entries: Dict[str, StatsFn] = {}

    def register(self, name: str, stats_fn: StatsFn) -> None:
        """``stats_fn`` returns a stats dict (depth required; the
        rest optional) or None when the plane is not running.

        Convention: ``maxsize`` means "this entry is ONE bounded
        queue and depth >= maxsize is an overload condition" — the
        health route flags it degraded. Entries that aggregate
        several queues (p2p.send, events.subs) or whose bound is a
        soft target (blocksync window, verify dispatch) must use a
        different field name (per_channel_maxsize, window_target,
        ...) so a summed depth is never compared to a per-queue
        bound."""
        self._entries[name] = stats_fn

    def register_queue(
        self, name: str, queue_fn: Callable[[], Optional[InstrumentedQueue]]
    ) -> None:
        """Register a queue that may be rebuilt across restarts."""

        def stats() -> Optional[dict]:
            q = queue_fn()
            return None if q is None else q.stats()

        self.register(name, stats)

    def names(self):
        return sorted(self._entries)

    def get(self, name: str) -> Optional[dict]:
        fn = self._entries.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            # a mid-teardown plane must not break a health scrape
            return None

    def snapshot(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for name in self.names():
            s = self.get(name)
            if s is not None:
                out[name] = s
        return out

    def high_watermarks(self) -> Dict[str, int]:
        return {
            name: int(s.get("high_watermark", 0))
            for name, s in self.snapshot().items()
        }

    def total_dropped(self) -> int:
        return sum(
            int(s.get("dropped", 0)) for s in self.snapshot().values()
        )
