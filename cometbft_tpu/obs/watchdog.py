"""Event-loop watchdog + loop-stall flight recorder.

On this GIL-bound 2-vCPU box the dominant tail-latency cause is the
asyncio loop stalling behind one long callback (or a starved thread),
and a stall is invisible in span data: the span that *contains* the
blocking call looks slow, every other span merely queues behind it.

Two cooperating parts per node:

- a **heartbeat task** on the loop wakes every ``interval_s`` and
  measures its own scheduling lag (actual wakeup minus requested —
  the canonical loop-responsiveness metric). Each beat lands on the
  trace ring as a completed ``obs.loop.lag`` span whose duration IS
  the lag, so the span→metrics bridge exports a loop-lag histogram
  for free, and a bounded in-memory window serves p50/p95/p99 to the
  RPC ``health`` route.
- a **monitor thread** (daemon, off-loop) watches the heartbeat's
  last-beat stamp. While a callback blocks the loop the heartbeat
  cannot run, so the stamp goes stale; once it is stale past
  ``stall_s`` the thread fires the **flight recorder** MID-STALL:
  ``sys._current_frames()`` for every thread (the loop thread's frame
  is the offending callback, caught red-handed) plus
  ``asyncio.all_tasks`` stacks, appended to the trace ring as
  ``obs.stall`` / ``obs.stall.tasks`` instants and kept on
  ``self.stalls`` for the health route and the chaos report.

Reading task stacks from another thread is a read-only race the same
way py-spy's sampling is: ``asyncio.all_tasks(loop)`` retries on
concurrent mutation by design, and a torn frame read degrades one
diagnostic line, never the node. The monitor must never *touch* loop
state — it only formats frames.
"""

from __future__ import annotations

import io
import sys
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from ..trace import NOOP as TRACE_NOOP
from ..trace.summary import percentile

_monotonic = time.monotonic
_monotonic_ns = time.monotonic_ns

# frames kept per stack in a flight record (deep enough for the p2p /
# abci call chains, bounded so a record stays a few KB)
_STACK_DEPTH = 25
_MAX_RECORDS = 32
_ARG_TRUNC = 1800  # chars of stack embedded in a trace instant


def _format_frame_stack(frame, depth: int = _STACK_DEPTH) -> List[str]:
    """Innermost-first "pkg/file.py:lineno func" lines for one frame.

    The parent directory is kept so stall attribution
    (analysis/runtime.attribute_frames) can bucket the frame by
    owning subsystem — "wal.py" alone cannot name its plane."""
    out: List[str] = []
    f = frame
    while f is not None and len(out) < depth:
        code = f.f_code
        fname = code.co_filename.replace("\\", "/")
        short = "/".join(fname.rsplit("/", 2)[-2:])
        out.append(f"{short}:{f.f_lineno} {code.co_name}")
        f = f.f_back
    return out


class LoopWatchdog:
    """Per-node loop-lag gauge + stall flight recorder (module doc)."""

    def __init__(
        self,
        tracer=TRACE_NOOP,
        interval_s: float = 0.1,
        stall_s: float = 0.5,
        name: str = "node",
        lag_window: int = 512,
    ) -> None:
        self.tracer = tracer
        self.interval_s = max(0.01, interval_s)
        self.stall_s = max(self.interval_s, stall_s)
        self.name = name
        self._lags: "deque[float]" = deque(maxlen=lag_window)
        self.stalls: "deque[dict]" = deque(maxlen=_MAX_RECORDS)
        self.stall_count = 0
        self._last_stall_t: Optional[float] = None
        self._beat = _monotonic()
        self._loop = None
        self._loop_thread_ident: Optional[int] = None
        self._task = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- lifecycle ----------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        """Must run on the watched loop (captures loop + thread id)."""
        import asyncio

        from ..utils.tasks import spawn

        if self._task is not None:
            return
        self._loop = asyncio.get_running_loop()
        self._loop_thread_ident = threading.get_ident()
        self._beat = _monotonic()
        self._stop.clear()
        self._task = spawn(self._heartbeat(), name=f"loop-watchdog-{self.name}")
        self._thread = threading.Thread(
            target=self._monitor,
            name=f"loopwd-{self.name}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._task = self._task, None
        if t is not None:
            t.cancel()
        th, self._thread = self._thread, None
        if th is not None and th is not threading.current_thread():
            th.join(timeout=2.0)

    # --- heartbeat (on-loop) ------------------------------------------

    def _record_beat(self, lag_s: float, now_ns: int) -> None:
        """Per-beat bookkeeping, split out so the overhead guard test
        can bound it: one deque append + one ring append."""
        self._lags.append(lag_s)
        tr = self.tracer
        if tr.enabled:
            lag_ns = int(lag_s * 1e9)
            # a completed span whose duration IS the scheduling lag:
            # rides the span→metrics bridge into the loop-lag histogram
            tr.complete(
                "obs.loop.lag", now_ns - lag_ns, lag_ns, tid="watchdog"
            )

    async def _heartbeat(self) -> None:
        import asyncio

        interval = self.interval_s
        while True:
            t0 = _monotonic()
            await asyncio.sleep(interval)
            now = _monotonic()
            self._beat = now
            self._record_beat(max(0.0, now - t0 - interval), _monotonic_ns())

    # --- monitor (off-loop daemon thread) -----------------------------

    def _monitor(self) -> None:
        reported = False
        check_s = self.interval_s / 2
        while not self._stop.wait(check_s):
            stale = _monotonic() - self._beat
            if stale > self.interval_s + self.stall_s:
                if not reported:
                    reported = True
                    try:
                        self._flight_record(stale)
                    except Exception:
                        # diagnostics must never take the node down
                        pass
            else:
                reported = False

    def _flight_record(self, stalled_s: float) -> None:
        """MID-STALL snapshot: every thread's frame + every task's
        stack, onto the ring and ``self.stalls``."""
        now_ns = _monotonic_ns()
        names = {t.ident: t.name for t in threading.enumerate()}
        own = threading.get_ident()
        threads: Dict[str, List[str]] = {}
        loop_stack: List[str] = []
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            stack = _format_frame_stack(frame)
            label = names.get(ident, f"tid-{ident}")
            threads[label] = stack
            if ident == self._loop_thread_ident:
                loop_stack = stack
        tasks: List[dict] = []
        try:
            import asyncio

            for task in asyncio.all_tasks(self._loop):
                try:
                    buf = io.StringIO()
                    task.print_stack(limit=8, file=buf)
                    tasks.append(
                        {"name": task.get_name(), "stack": buf.getvalue()}
                    )
                except Exception:
                    continue
        except Exception:
            pass
        record = {
            "node": self.name,
            "stalled_s": round(stalled_s, 3),
            "ts_ns": now_ns,
            "loop_stack": loop_stack,
            "threads": threads,
            "tasks": [t["name"] for t in tasks],
        }
        try:
            # stall attribution (docs/LINT.md "Runtime sanitizer"):
            # name the guilty subsystem, not just the raw stack
            from ..analysis.runtime import attribute_stall

            record["subsystem"] = attribute_stall(record)
        except Exception:
            record["subsystem"] = "unknown"
        self.stalls.append(record)
        self.stall_count += 1
        self._last_stall_t = _monotonic()
        tr = self.tracer
        if tr.enabled:
            # instants land NEXT TO the stalled spans in Perfetto
            tr.instant(
                "obs.stall",
                tid="watchdog",
                stalled_ms=round(stalled_s * 1e3, 1),
                subsystem=record["subsystem"],
                loop_stack=" <- ".join(loop_stack)[:_ARG_TRUNC],
            )
            tr.instant(
                "obs.stall.tasks",
                tid="watchdog",
                tasks="; ".join(
                    t["stack"].strip().replace("\n", " | ")[:200]
                    for t in tasks[:8]
                )[:_ARG_TRUNC],
            )
        from ..utils.log import get_logger

        get_logger("obs.watchdog").error(
            "event loop stalled (flight record captured)",
            node=self.name,
            stalled_s=round(stalled_s, 2),
            subsystem=record["subsystem"],
            loop_stack=" <- ".join(loop_stack[:6]),
        )

    # --- introspection ------------------------------------------------

    def lag_stats(self) -> dict:
        """p50/p95/p99/max scheduling lag (ms) over the sample window
        — the RPC ``health`` payload."""
        lags = sorted(self._lags)
        ms = 1e3

        def p(q: float) -> float:
            return round(percentile(lags, q) * ms, 3)

        return {
            "samples": len(lags),
            "p50_ms": p(0.50),
            "p95_ms": p(0.95),
            "p99_ms": p(0.99),
            "max_ms": round((lags[-1] if lags else 0.0) * ms, 3),
        }

    def last_stall_ago_s(self) -> Optional[float]:
        if self._last_stall_t is None:
            return None
        return _monotonic() - self._last_stall_t


def all_task_stacks(loop=None) -> List[dict]:
    """Every asyncio task's name + formatted stack (the RPC
    ``dump_tasks`` debug payload); safe to call on the loop itself."""
    import asyncio

    out: List[dict] = []
    try:
        tasks = asyncio.all_tasks(loop)
    except RuntimeError:
        return out
    for task in tasks:
        try:
            frames = task.get_stack(limit=_STACK_DEPTH)
            lines: List[str] = []
            for fr in frames:
                lines.extend(
                    traceback.format_stack(fr, limit=1)[0].rstrip()
                    .splitlines()
                )
            out.append({"name": task.get_name(), "stack": lines})
        except Exception:
            continue
    return out
