"""Bounded shutdown: per-stage budgets with stop→cancel→abandon
escalation and a flight-recorder dump on every breach.

The known wedge class this exists for (CHANGES.md PR 7 note): a
graceful ``stop()`` chain awaits some sub-plane's stop that never
returns — a reactor routine swallowing its cancel, a peer drain
waiting on a dead transport, an executor hop that lost its thread —
and the whole process hangs with the loop alive and store fds open.
Nothing times out, nothing reports, the only evidence is a stuck CI
job.

``ShutdownGuard.stage`` turns that into a *diagnosed, bounded*
failure:

1. **stop** — run the stage coroutine under ``asyncio.wait_for`` with
   a per-stage budget;
2. **cancel** — on budget breach, capture a flight record FIRST (the
   hung stage's task stack is still intact mid-hang — exactly like
   the loop watchdog's mid-stall snapshot), then cancel the stage
   task and give it a short grace period to unwind;
3. **abandon** — if the stage ignores its cancel too, leave the task
   behind and move on: later stages (store-handle release, fd close)
   must still run, because a half-stopped node that frees its
   stores can at least be restarted.

Every breach lands on the trace ring as ``obs.shutdown.stall`` (the
hung stage + the offending task/thread stacks) and
``obs.shutdown.tasks`` instants — the same surface the loop
watchdog's stall records use, so chaos dumps and Perfetto show the
wedge next to whatever the node was doing — and is kept on
``guard.stalls`` for reports and tests.
"""

from __future__ import annotations

import asyncio
import io
import sys
import threading
from typing import Awaitable, Dict, List, Optional

from ..trace import NOOP as TRACE_NOOP
from .watchdog import _ARG_TRUNC, _format_frame_stack

# escalation grace after the cancel: a well-behaved stage unwinds in
# microseconds; a stage that needs longer than this to HANDLE its
# cancel is itself part of the wedge class
CANCEL_GRACE_S = 1.0


def shutdown_flight_record(
    stage: str, waited_s: float, task: Optional[asyncio.Task] = None
) -> dict:
    """Mid-hang snapshot of the stage task's stack plus every thread's
    frame (the hang may live off-loop: an executor hop, a locked
    native call). Read-only like the watchdog's recorder — formatting
    frames never touches loop state."""
    record: Dict[str, object] = {
        "stage": stage,
        "waited_s": round(waited_s, 3),
    }
    if task is not None:
        try:
            buf = io.StringIO()
            task.print_stack(limit=12, file=buf)
            record["stage_stack"] = buf.getvalue()
        except Exception:
            record["stage_stack"] = ""
    names = {t.ident: t.name for t in threading.enumerate()}
    own = threading.get_ident()
    threads: Dict[str, List[str]] = {}
    try:
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            threads[names.get(ident, f"tid-{ident}")] = (
                _format_frame_stack(frame)
            )
    except Exception:
        pass
    record["threads"] = threads
    return record


class ShutdownGuard:
    """Runs shutdown stages under bounded budgets (module doc).

    One guard per shutdown; ``stalls`` collects every breached
    stage's flight record, ``clean`` is True iff no stage breached.
    """

    def __init__(
        self,
        tracer=TRACE_NOOP,
        name: str = "node",
        budget_s: float = 5.0,
    ) -> None:
        self.tracer = tracer or TRACE_NOOP
        self.name = name
        self.budget_s = budget_s
        self.stalls: List[dict] = []
        self.abandoned: List[str] = []

    @property
    def clean(self) -> bool:
        return not self.stalls

    async def stage(
        self,
        stage_name: str,
        coro: Awaitable,
        budget_s: Optional[float] = None,
    ) -> bool:
        """Run one shutdown stage bounded. Returns True iff the stage
        completed (or failed fast) within budget; a stage exception
        other than the timeout is swallowed after logging — shutdown
        must always reach its last stage."""
        budget = self.budget_s if budget_s is None else budget_s
        task = asyncio.ensure_future(coro)
        try:
            await asyncio.wait_for(asyncio.shield(task), budget)
            return True
        except asyncio.TimeoutError:
            self._on_breach(stage_name, budget, task)
        except asyncio.CancelledError:
            # our own caller is being cancelled: don't leave the stage
            # task dangling silently
            task.cancel()
            raise
        except Exception as e:
            from ..utils.log import get_logger

            get_logger("obs.shutdown").error(
                "shutdown stage failed", node=self.name,
                stage=stage_name, err=repr(e),
            )
            return True  # failed fast — the stage is over, move on
        # escalation: cancel, short grace, then abandon
        task.cancel()
        try:
            await asyncio.wait_for(
                asyncio.shield(task), CANCEL_GRACE_S
            )
        except asyncio.TimeoutError:
            if not task.done():
                self.abandoned.append(stage_name)
        except asyncio.CancelledError:
            if not task.done():
                # the CALLER was cancelled mid-grace (the stage task
                # would be done if this were our own cancel landing):
                # record the abandonment and propagate — swallowing
                # an outer cancel here would keep running a shutdown
                # its owner just revoked
                self.abandoned.append(stage_name)
                raise
            # else: the stage unwound with our cancel — escalation
            # complete, not an abandonment
        except Exception:
            pass  # unwound with an error: still over
        return False

    def _on_breach(
        self, stage_name: str, budget: float, task: asyncio.Task
    ) -> None:
        record = shutdown_flight_record(stage_name, budget, task)
        record["node"] = self.name
        self.stalls.append(record)
        tr = self.tracer
        if getattr(tr, "enabled", False):
            tr.instant(
                "obs.shutdown.stall",
                tid="shutdown",
                stage=stage_name,
                budget_s=budget,
                stage_stack=str(record.get("stage_stack", ""))[
                    :_ARG_TRUNC
                ],
            )
            tr.instant(
                "obs.shutdown.tasks",
                tid="shutdown",
                threads="; ".join(
                    f"{n}: " + " <- ".join(s[:4])
                    for n, s in list(record["threads"].items())[:8]
                )[:_ARG_TRUNC],
            )
        from ..utils.log import get_logger

        get_logger("obs.shutdown").error(
            "shutdown stage exceeded its budget "
            "(flight record captured; escalating stop→cancel)",
            node=self.name,
            stage=stage_name,
            budget_s=budget,
        )
