"""abci-cli client commands (reference abci/cmd/abci-cli/abci-cli.go):
one-shot requests, an interactive ``console``, and a ``batch`` mode
that executes a piped script of commands — all over one socket ABCI
connection to a running app server (our `abci-server` command, or any
reference-compatible app).

Command language (reference cmdUnimplemented/muxOnCommands):

    echo <msg>
    info
    check_tx 0x00
    finalize_block 0x00 0x01 "some tx"
    prepare_proposal 0x01 ...
    process_proposal 0x01 ...
    commit
    query 0xabcd | "key"
"""

from __future__ import annotations

import shlex
import sys
from typing import List, Optional

from ..abci import types as abci


def string_or_hex_to_bytes(s: str) -> bytes:
    """Reference stringOrHexToBytes (abci-cli.go:764): 0x-prefixed hex
    or a "quoted" string — bare strings are rejected with guidance."""
    if s.lower().startswith("0x"):
        try:
            return bytes.fromhex(s[2:])
        except ValueError:
            raise ValueError(f"error decoding hex argument: {s}") from None
    if len(s) >= 2 and s[0] == '"' and s[-1] == '"':
        return s[1:-1].encode()
    raise ValueError(
        f"invalid string arg: \"{s}\" must be quoted or a hex string"
    )


def _print_response(out, code=None, data=None, log=None, info=None, extra=()):
    if code is not None:
        out.write(f"-> code: {'OK' if code == 0 else code}\n")
    if log:
        out.write(f"-> log: {log}\n")
    if info:
        out.write(f"-> info: {info}\n")
    if data is not None and data != b"":
        try:
            out.write(f"-> data: {data.decode()}\n")
        except UnicodeDecodeError:
            pass
        out.write(f"-> data.hex: 0x{data.hex().upper()}\n")
    for k, v in extra:
        out.write(f"-> {k}: {v}\n")


class AbciCli:
    """Dispatches the command language against a connected client
    (SocketClient or the in-process LocalClient — same interface)."""

    def __init__(self, client, out=None):
        self.client = client
        self.out = out or sys.stdout

    def run_line(self, line: str) -> bool:
        """Execute one command line. Returns False on 'exit'/'quit'."""
        try:
            parts = shlex.split(line, posix=False)
        except ValueError as e:  # e.g. unbalanced quote — keep the REPL
            self.out.write(f"-> error: {e}\n")
            return True
        if not parts:
            return True
        cmd, args = parts[0], parts[1:]
        if cmd in ("exit", "quit"):
            return False
        fn = getattr(self, "do_" + cmd, None)
        if fn is None:
            self.out.write(
                f"-> error: unknown command {cmd!r} (try: echo info "
                "check_tx finalize_block prepare_proposal "
                "process_proposal commit query)\n"
            )
            return True
        try:
            fn(args)
        except Exception as e:
            self.out.write(f"-> error: {e}\n")
        return True

    # --- commands -----------------------------------------------------

    def do_echo(self, args: List[str]) -> None:
        msg = args[0] if args else ""
        got = self.client.echo(msg)
        _print_response(self.out, data=got.encode())

    def do_info(self, args: List[str]) -> None:
        r = self.client.info(abci.RequestInfo())
        _print_response(
            self.out,
            data=(r.data or "").encode(),
            extra=[
                ("version", r.version),
                ("last_block_height", r.last_block_height),
                ("last_block_app_hash", "0x" + r.last_block_app_hash.hex()),
            ],
        )

    def do_check_tx(self, args: List[str]) -> None:
        if len(args) != 1:
            raise ValueError("check_tx takes exactly one tx argument")
        r = self.client.check_tx(
            abci.RequestCheckTx(tx=string_or_hex_to_bytes(args[0]))
        )
        _print_response(self.out, code=r.code, log=r.log)

    def do_finalize_block(self, args: List[str]) -> None:
        txs = [string_or_hex_to_bytes(a) for a in args]
        r = self.client.finalize_block(abci.RequestFinalizeBlock(txs=txs))
        for txr in r.tx_results:
            _print_response(self.out, code=txr.code, log=txr.log)
        _print_response(
            self.out, extra=[("app_hash", "0x" + r.app_hash.hex())]
        )

    def do_prepare_proposal(self, args: List[str]) -> None:
        txs = [string_or_hex_to_bytes(a) for a in args]
        r = self.client.prepare_proposal(
            abci.RequestPrepareProposal(
                txs=txs, max_tx_bytes=10 * 1024 * 1024
            )
        )
        for tx in r.txs:
            _print_response(self.out, extra=[("tx", "0x" + tx.hex())])

    def do_process_proposal(self, args: List[str]) -> None:
        txs = [string_or_hex_to_bytes(a) for a in args]
        r = self.client.process_proposal(
            abci.RequestProcessProposal(txs=txs)
        )
        _print_response(
            self.out,
            extra=[("status", "ACCEPT" if r.is_accepted() else "REJECT")],
        )

    def do_commit(self, args: List[str]) -> None:
        self.client.commit()
        _print_response(self.out, code=0)

    def do_query(self, args: List[str]) -> None:
        if len(args) != 1:
            raise ValueError("query takes exactly one data argument")
        r = self.client.query(
            abci.RequestQuery(data=string_or_hex_to_bytes(args[0]))
        )
        _print_response(
            self.out,
            code=r.code,
            log=r.log,
            extra=[
                ("height", r.height),
                ("key", "0x" + r.key.hex() if r.key else ""),
                ("value", "0x" + r.value.hex() if r.value else ""),
            ],
        )

    # --- modes --------------------------------------------------------

    def console(self, in_stream=None) -> None:
        """Interactive REPL (reference consoleCmd): one connection for
        many commands."""
        in_stream = in_stream or sys.stdin
        while True:
            self.out.write("> ")
            self.out.flush()
            line = in_stream.readline()
            if not line:
                break
            if not self.run_line(line.strip()):
                break

    def batch(self, in_stream=None) -> None:
        """Piped script mode (reference batchCmd)."""
        in_stream = in_stream or sys.stdin
        for line in in_stream:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            self.out.write(f"> {line}\n")
            self.run_line(line)


def run_abci_cli(address: str, command: str, args: List[str],
                 out=None) -> int:
    """Entry for `cometbft-tpu abci-cli`: connect, run, disconnect."""
    from ..abci.socket_client import SocketClient

    client = SocketClient(address)
    cli = AbciCli(client, out=out)
    try:
        if command == "console":
            cli.console()
        elif command == "batch":
            cli.batch()
        else:
            if not cli.run_line(
                " ".join([command] + list(args))
            ):
                return 0
    finally:
        client.close()
    return 0
