"""`python -m cometbft_tpu` — the node CLI (reference
cmd/cometbft/main.go:14-49 command registry).

Commands: init, start, testnet, light, replay, rollback,
reindex-event, reset / unsafe-reset-all, inspect, compact,
gen-node-key, gen-validator, show-node-id, show-validator, version.

Home layout (reference config directory conventions):
  <home>/config/config.toml, genesis.json, node_key.json,
               priv_validator_key.json
  <home>/data/priv_validator_state.json, *.db, cs.wal/
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import signal
import sys

VERSION = "0.1.0"


def _compute_cmd(fn):
    """Marks a subcommand whose execution can reach a jax compute path
    (signature batches / kernels): main() pins the jax platform for
    these; the others never pay the jax import. Tagging at the
    definition site survives renames (vs a name list)."""
    fn._reaches_jax = True
    return fn


def _home(args) -> str:
    return os.path.expanduser(args.home)


def _paths(home: str) -> dict:
    return {
        "config": os.path.join(home, "config"),
        "data": os.path.join(home, "data"),
        "config_toml": os.path.join(home, "config", "config.toml"),
        "genesis": os.path.join(home, "config", "genesis.json"),
        "node_key": os.path.join(home, "config", "node_key.json"),
        "pv_key": os.path.join(home, "config", "priv_validator_key.json"),
        "pv_state": os.path.join(home, "data", "priv_validator_state.json"),
    }


def _load_config(home: str):
    from ..config.config import default_config, load_toml

    p = _paths(home)
    if os.path.exists(p["config_toml"]):
        cfg = load_toml(p["config_toml"])
    else:
        cfg = default_config(home)
    cfg.root_dir = home
    return cfg


# --- init ----------------------------------------------------------------


def cmd_init(args) -> int:
    """Initialise a home dir: config, genesis (this node as sole
    validator), node key, privval key (reference commands/init.go)."""
    from .. import types as T
    from ..config.config import default_config, write_toml
    from ..p2p.key import NodeKey
    from ..privval.file_pv import FilePV
    from ..types.genesis import GenesisDoc

    home = _home(args)
    p = _paths(home)
    os.makedirs(p["config"], exist_ok=True)
    os.makedirs(p["data"], exist_ok=True)

    cfg = default_config(home)
    if not os.path.exists(p["config_toml"]):
        write_toml(cfg, p["config_toml"])
    pv = FilePV.load_or_generate(p["pv_key"], p["pv_state"])
    nk = NodeKey.load_or_gen(p["node_key"])
    if not os.path.exists(p["genesis"]):
        gen = GenesisDoc(
            chain_id=args.chain_id
            or "test-chain-%s" % os.urandom(3).hex(),
            validators=[T.Validator(pv.pub_key(), 10)],
        )
        with open(p["genesis"], "w") as f:
            f.write(gen.to_json())
        print(f"Generated genesis file {p['genesis']}")
    print(f"Initialised node in {home} (node id {nk.node_id})")
    return 0


# --- start ---------------------------------------------------------------


@_compute_cmd
def cmd_start(args) -> int:
    from ..node.node import Node
    from ..p2p.key import NodeKey
    from ..privval.file_pv import FilePV
    from ..types.genesis import GenesisDoc

    home = _home(args)
    p = _paths(home)
    cfg = _load_config(home)
    # config-selectable level, e.g. "info" or "consensus:debug,*:info"
    # (reference libs/log + config log_level)
    try:
        from ..utils.log import set_level

        set_level(cfg.base.log_level)
    except ValueError:
        print(f"invalid log_level {cfg.base.log_level!r}; using info")
    with open(p["genesis"]) as f:
        gen = GenesisDoc.from_json(f.read())
    if cfg.base.priv_validator_laddr:
        from ..privval.signer import RetrySignerClient, SignerClient

        # bounded retries around every sign call: a transient signer
        # hiccup must not become a missed vote (reference
        # privval/retry_signer_client.go)
        pv = RetrySignerClient(
            SignerClient(cfg.base.priv_validator_laddr)
        )
        print(
            f"waiting for remote signer on {pv.listen_addr} ..."
        )
        pv.wait_for_signer()
        pv.pub_key()  # prefetch + cache the validator identity
    else:
        pv = (
            FilePV.load(p["pv_key"], p["pv_state"])
            if os.path.exists(p["pv_key"])
            else None
        )
    nk = NodeKey.load_or_gen(p["node_key"])
    app = None
    if cfg.base.abci == "kvstore-appmem":
        from ..models.kvstore import AppMempoolKVStore

        app = AppMempoolKVStore()

    async def main():
        node = Node(
            cfg, gen, privval=pv, node_key=nk, app=app,
            home=os.path.join(home, "data"),
        )
        await node.start()
        print(
            f"Node {nk.node_id} started: p2p {node.listen_addr}, "
            f"rpc {node.rpc_server.listen_addr if node.rpc_server else '-'}"
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        print("shutting down...")
        await node.stop()

    asyncio.run(main())
    return 0


# --- key/identity helpers ------------------------------------------------


def cmd_gen_node_key(args) -> int:
    from ..p2p.key import NodeKey

    home = _home(args)
    nk = NodeKey.load_or_gen(_paths(home)["node_key"])
    print(nk.node_id)
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p.key import NodeKey

    nk = NodeKey.load(_paths(_home(args))["node_key"])
    print(nk.node_id)
    return 0


def cmd_gen_validator(args) -> int:
    from ..privval.file_pv import FilePV

    p = _paths(_home(args))
    os.makedirs(p["config"], exist_ok=True)
    os.makedirs(p["data"], exist_ok=True)
    pv = FilePV.load_or_generate(p["pv_key"], p["pv_state"])
    print(
        json.dumps(
            {
                "address": pv.pub_key().address().hex().upper(),
                "pub_key": {
                    "type": pv.pub_key().type_,
                    "value": bytes(pv.pub_key()).hex(),
                },
            },
            indent=2,
        )
    )
    return 0


def cmd_show_validator(args) -> int:
    from ..privval.file_pv import FilePV

    p = _paths(_home(args))
    pv = FilePV.load(p["pv_key"], p["pv_state"])
    print(
        json.dumps(
            {
                "type": pv.pub_key().type_,
                "value": bytes(pv.pub_key()).hex(),
            }
        )
    )
    return 0


# --- testnet -------------------------------------------------------------


@_compute_cmd
def cmd_testnet(args) -> int:
    """Generate a multi-node testnet directory tree (reference
    commands/testnet.go)."""
    from .. import types as T
    from ..config.config import default_config, write_toml
    from ..p2p.key import NodeKey
    from ..privval.file_pv import FilePV
    from ..types.genesis import GenesisDoc

    out = os.path.expanduser(args.o)
    n = args.v
    pvs, nks = [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        p = _paths(home)
        os.makedirs(p["config"], exist_ok=True)
        os.makedirs(p["data"], exist_ok=True)
        pvs.append(FilePV.load_or_generate(p["pv_key"], p["pv_state"]))
        nks.append(NodeKey.load_or_gen(p["node_key"]))
    gen = GenesisDoc(
        chain_id=args.chain_id or "testnet-%s" % os.urandom(3).hex(),
        validators=[T.Validator(pv.pub_key(), 10) for pv in pvs],
    )
    base_p2p = args.starting_port
    peers = ",".join(
        f"{nks[i].node_id}@127.0.0.1:{base_p2p + 2 * i}" for i in range(n)
    )
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        p = _paths(home)
        cfg = default_config(home)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{base_p2p + 2 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{base_p2p + 2 * i + 1}"
        cfg.p2p.persistent_peers = ",".join(
            pr
            for j, pr in enumerate(peers.split(","))
            if j != i
        )
        cfg.base.moniker = f"node{i}"
        write_toml(cfg, p["config_toml"])
        with open(p["genesis"], "w") as f:
            f.write(gen.to_json())
    print(f"Wrote {n}-node testnet to {out} (chain {gen.chain_id})")
    return 0


# --- maintenance ---------------------------------------------------------


def cmd_reset(args, all_: bool = False) -> int:
    """Delete data (blocks/state/WAL) and reset privval height state
    (reference commands/reset.go). unsafe-reset-all also removes the
    address book."""
    from ..privval.file_pv import FilePV

    home = _home(args)
    p = _paths(home)
    data = p["data"]
    if os.path.isdir(data):
        for name in os.listdir(data):
            if name == "priv_validator_state.json":
                continue
            full = os.path.join(data, name)
            shutil.rmtree(full, ignore_errors=True) if os.path.isdir(
                full
            ) else os.remove(full)
    if os.path.exists(p["pv_key"]):
        pv = FilePV.load(p["pv_key"], p["pv_state"])
        pv.last = type(pv.last)()  # zero sign-state
        pv.save_state()
    print(f"Reset data in {data}")
    return 0


def cmd_rollback(args) -> int:
    from ..state.rollback import rollback_state
    from ..state.store import Store as StateStore
    from ..store.block_store import BlockStore
    from ..utils import kv

    home = _home(args)
    cfg = _load_config(home)
    data = os.path.join(home, "data")
    block_db = kv.open_kv("sqlite", os.path.join(data, "blockstore.db"))
    state_db = kv.open_kv("sqlite", os.path.join(data, "state.db"))
    st = rollback_state(
        StateStore(state_db), BlockStore(block_db), remove_block=args.hard
    )
    print(
        f"Rolled back state to height {st.last_block_height} "
        f"(app_hash {st.app_hash.hex()[:16]})"
    )
    block_db.close()
    state_db.close()
    return 0


def cmd_compact(args) -> int:
    import sqlite3

    home = _home(args)
    data = os.path.join(home, "data")
    n = 0
    for name in os.listdir(data) if os.path.isdir(data) else []:
        if name.endswith(".db"):
            con = sqlite3.connect(os.path.join(data, name))
            con.execute("VACUUM")
            con.close()
            n += 1
    print(f"Compacted {n} sqlite databases")
    return 0


def cmd_reindex_event(args) -> int:
    """Rebuild tx/block indexes from stored blocks + finalize
    responses (reference commands/reindex_event.go)."""
    from ..state.execution import decode_finalize_response
    from ..state.indexer import (
        LAST_INDEXED_KEY,
        BlockIndexer,
        TxIndexer,
        _enc_height,
    )
    from ..state.store import Store as StateStore
    from ..store.block_store import BlockStore
    from ..utils import kv

    home = _home(args)
    data = os.path.join(home, "data")
    block_db = kv.open_kv("sqlite", os.path.join(data, "blockstore.db"))
    state_db = kv.open_kv("sqlite", os.path.join(data, "state.db"))
    index_db = kv.open_kv("sqlite", os.path.join(data, "tx_index.db"))
    bs, ss = BlockStore(block_db), StateStore(state_db)
    txi, bli = TxIndexer(index_db), BlockIndexer(index_db)
    start = args.start_height or bs.base()
    end = args.end_height or bs.height()
    count = 0
    for h in range(start, end + 1):
        blk = bs.load_block(h)
        raw = ss.load_finalize_block_response(h)
        if blk is None or raw is None:
            continue
        resp = decode_finalize_response(raw)
        # ONE atomic batch per height — rows + the idx:last marker —
        # exactly the live IndexerService flush shape (ISSUE 15), so
        # a killed reindex resumes where it stopped
        sets = []
        for i, tx in enumerate(blk.data.txs):
            if i < len(resp.tx_results):
                sets.extend(txi.tx_sets(h, i, tx, resp.tx_results[i]))
        sets.extend(bli.block_sets(h, resp.events))
        # marker advances CONTIGUOUSLY only (same contract as the
        # live flush): an explicit --start-height above idx:last+1
        # must not jump the marker over never-indexed heights, or
        # IndexerService.replay() would skip them forever. A gap
        # that lies entirely below the store base is pruned —
        # unindexable — so jumping it is safe (replay's anchored
        # walk does the same).
        last = txi.last_indexed_height()
        if last >= h - 1 or bs.base() >= h:
            sets.append(
                (LAST_INDEXED_KEY, _enc_height(max(last, h)))
            )
        index_db.write_batch(sets)
        count += 1
    print(f"Reindexed {count} blocks [{start},{end}]")
    for db in (block_db, state_db, index_db):
        db.close()
    return 0


@_compute_cmd
def cmd_replay(args) -> int:
    """Re-execute stored blocks against a fresh app instance via the
    handshake replay path (reference commands/replay.go)."""
    from ..node.inprocess import build_node
    from ..types.genesis import GenesisDoc

    home = _home(args)
    p = _paths(home)
    cfg = _load_config(home)
    with open(p["genesis"]) as f:
        gen = GenesisDoc.from_json(f.read())
    parts = build_node(
        gen, None, config=cfg, home=os.path.join(home, "data")
    )
    print(
        f"Replayed to height {parts.state.last_block_height} "
        f"(app_hash {parts.state.app_hash.hex()[:16]})"
    )
    return 0


def cmd_inspect(args) -> int:
    """Read-only RPC over the data dirs of a stopped node (reference
    inspect/inspect.go:32)."""
    from ..rpc.env import Environment
    from ..rpc.server import RPCServer
    from ..state.store import Store as StateStore
    from ..store.block_store import BlockStore
    from ..types import events as ev
    from ..types.genesis import GenesisDoc
    from ..utils import kv

    home = _home(args)
    p = _paths(home)
    cfg = _load_config(home)
    data = os.path.join(home, "data")
    with open(p["genesis"]) as f:
        gen = GenesisDoc.from_json(f.read())
    env = Environment(
        chain_id=gen.chain_id,
        block_store=BlockStore(
            kv.open_kv("sqlite", os.path.join(data, "blockstore.db"))
        ),
        state_store=StateStore(
            kv.open_kv("sqlite", os.path.join(data, "state.db"))
        ),
        event_bus=ev.EventBus(),
        genesis=gen,
        config=cfg,
    )

    async def main():
        srv = RPCServer(env)
        await srv.start(args.rpc_laddr)
        print(f"Inspect RPC serving on {srv.listen_addr} (read-only)")
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await srv.stop()

    asyncio.run(main())
    return 0


@_compute_cmd
def cmd_light(args) -> int:
    """Light client daemon: bisection-verify new headers from a
    primary against witnesses (reference cmd light + light/proxy)."""
    from ..light import SEQUENTIAL, SKIPPING, Client, TrustOptions
    from ..light.http_provider import HTTPProvider

    primary = HTTPProvider(args.chain_id, args.primary)
    witnesses = [
        HTTPProvider(args.chain_id, w)
        for w in (args.witnesses.split(",") if args.witnesses else [])
        if w
    ]
    store = None
    if args.dir:
        # persistent trust store (reference light home db): a
        # restarted daemon resumes from its last VERIFIED header —
        # the CLI trust root only seeds an empty store
        from ..light.store import DBLightStore
        from ..utils.kv import open_kv

        os.makedirs(os.path.expanduser(args.dir), exist_ok=True)
        store = DBLightStore(
            open_kv(
                "sqlite",
                os.path.join(
                    os.path.expanduser(args.dir), "light.db"
                ),
            ),
            args.chain_id,
        )
    cli = Client(
        args.chain_id,
        TrustOptions(
            period_ns=int(args.trust_period_h * 3600 * 1e9),
            height=args.trust_height,
            hash=bytes.fromhex(args.trust_hash),
        ),
        primary=primary,
        witnesses=witnesses,
        store=store,
        verification_mode=(
            SEQUENTIAL if args.sequential else SKIPPING
        ),
    )
    if args.laddr:
        # proxy mode (the reference command's primary role): serve
        # light-verified RPC — including proof-checked abci_query/tx —
        # while tracking the head in the background
        import asyncio

        from ..light.proxy import LightProxy

        async def serve():
            proxy = LightProxy(cli, args.primary)
            addr = args.laddr
            for pfx in ("tcp://", "http://"):
                if addr.startswith(pfx):
                    addr = addr[len(pfx):]
            await proxy.start(addr)
            print(
                f"light proxy for {args.chain_id} on "
                f"{proxy.listen_addr} (primary {args.primary})"
            )
            try:
                while True:
                    try:
                        await asyncio.to_thread(cli.update)
                    except asyncio.CancelledError:
                        raise  # ctrl-C path below handles shutdown
                    except Exception as e:
                        # a transient primary hiccup must not tear the
                        # proxy daemon down; log and keep polling
                        print(f"light update failed (retrying): {e!r}")
                    await asyncio.sleep(args.interval_s)
            except (KeyboardInterrupt, asyncio.CancelledError):
                pass
            finally:
                await proxy.stop()
            return 0

        try:
            return asyncio.run(serve()) or 0
        except KeyboardInterrupt:
            return 0

    import time as _t

    print(f"light client tracking {args.chain_id} via {args.primary}")
    try:
        while True:
            lb = cli.update()
            if lb is not None:
                print(
                    f"verified height {lb.height} "
                    f"hash {lb.hash().hex()[:16]}"
                )
            _t.sleep(args.interval_s)
    except KeyboardInterrupt:
        return 0


def cmd_signer(args) -> int:
    """Run a remote signer daemon serving this home dir's validator
    key to a node (the reference ecosystem's tmkms role)."""
    from ..privval.file_pv import FilePV
    from ..privval.signer import SignerServer

    p = _paths(_home(args))
    pv = FilePV.load(p["pv_key"], p["pv_state"])
    server = SignerServer(pv, args.address)

    async def main():
        print(
            f"signer for {pv.pub_key().address().hex()[:16]} "
            f"dialing {args.address}"
        )
        while True:
            try:
                await server.serve()
            except (
                ConnectionError,
                OSError,
                EOFError,  # IncompleteReadError: node closed mid-handshake
                asyncio.TimeoutError,
            ) as e:
                print(f"connection lost ({e}); retrying in 1s")
            await asyncio.sleep(1.0)

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_abci_server(args) -> int:
    """Host the example kvstore app out-of-process (the reference
    abci-cli's `kvstore` server command, abci/cmd/abci-cli): a node
    configured with proxy_app = this address drives it over the
    socket/grpc ABCI protocol."""
    from ..models.kvstore import KVStoreApplication

    app = KVStoreApplication(
        persist_path=os.path.join(_home(args), "data", "kvstore.json")
        if args.persist
        else None
    )
    if args.transport == "grpc":
        from ..abci.server import GRPCServer

        server = GRPCServer(app, args.address)
        server.start()
        print(f"abci grpc server on port {server.port}")
        try:
            import time as _t

            while True:
                _t.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return 0

    from ..abci.server import ABCIServer

    server = ABCIServer(app, args.address)

    async def main():
        await server.start()
        print(f"abci socket server on {server.listen_addr}")
        await asyncio.Event().wait()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_abci_cli(args) -> int:
    """Client side of the reference abci-cli (abci/cmd/abci-cli):
    echo/info/check_tx/... one-shots, interactive `console`, and piped
    `batch` scripts against a running ABCI server."""
    from .abci_cli import run_abci_cli

    return run_abci_cli(args.address, args.abci_cmd, args.abci_args)


@_compute_cmd
def cmd_bootstrap_state(args) -> int:
    """Offline statesync: light-verify state at a height and seed the
    stores so `start` goes straight to blocksync (reference
    node.BootstrapState, node/node.go:161-280)."""
    from ..node.bootstrap import bootstrap_state
    from ..types.genesis import GenesisDoc

    home = _home(args)
    cfg = _load_config(home)
    with open(_paths(home)["genesis"]) as f:
        gen = GenesisDoc.from_json(f.read())
    h = bootstrap_state(cfg, gen, os.path.join(home, "data"),
                        height=args.height or None)
    print(f"bootstrapped state at height {h}")
    return 0


def cmd_debug(args) -> int:
    """`debug dump` / `debug kill` (reference
    cmd/cometbft/commands/debug/): archive a live node's status,
    net_info, consensus dump, and profiles; kill additionally
    SIGKILLs the node process after the dump."""
    from ..utils.debug import collect_debug_dump

    path = collect_debug_dump(
        args.rpc_laddr.replace("tcp://", ""),
        args.output_dir,
        pprof_addr=args.pprof_laddr,
        label=args.debug_cmd,
    )
    print(f"wrote {path}")
    if args.debug_cmd == "kill":
        import signal as _sig

        if args.pid <= 0:
            print("debug kill requires --pid <node pid>", file=sys.stderr)
            return 1
        os.kill(args.pid, _sig.SIGKILL)
        print(f"killed pid {args.pid}")
    return 0


@_compute_cmd
def cmd_load(args) -> int:
    """Timestamped tx load + commit-latency report (reference
    test/loadtime)."""
    import json as _json

    from ..e2e.load import LoadGenerator, latency_report
    from ..rpc.client import HTTPClient

    async def main():
        base = args.rpc_laddr.replace("tcp://", "http://")
        if not base.startswith("http"):
            base = "http://" + base
        cli = HTTPClient(base)
        try:
            st = await cli.status()
            h0 = int(st["sync_info"]["latest_block_height"])
            gen = LoadGenerator(
                cli,
                rate=args.rate,
                connections=args.connections,
                tx_size=args.size,
            )
            res = await gen.run(args.time)
            await asyncio.sleep(2.0)  # let the tail commit
            st = await cli.status()
            h1 = int(st["sync_info"]["latest_block_height"])
            rep = await latency_report(cli, h0 + 1, h1)
            print(
                _json.dumps(
                    {
                        "sent": res.sent,
                        "accepted": res.accepted,
                        "rejected": res.rejected,
                        "send_rate_tx_s": round(res.send_rate, 1),
                        **rep.to_dict(),
                    }
                )
            )
        finally:
            await cli.close()

    asyncio.run(main())
    return 0


def cmd_version(args) -> int:
    print(f"cometbft-tpu v{VERSION}")
    return 0


# --- parser --------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="cometbft-tpu",
        description="TPU-native BFT consensus engine",
    )
    ap.add_argument(
        "--home",
        default=os.environ.get("CMTHOME", "~/.cometbft-tpu"),
        help="node home directory",
    )
    sub = ap.add_subparsers(dest="command")

    p = sub.add_parser("init", help="initialise a node home dir")
    p.add_argument("--chain-id", default="")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("testnet", help="generate a local testnet")
    p.add_argument("--v", type=int, default=4, help="number of validators")
    p.add_argument("--o", default="./mytestnet", help="output directory")
    p.add_argument("--chain-id", default="")
    p.add_argument("--starting-port", type=int, default=26656)
    p.set_defaults(fn=cmd_testnet)

    for name, fn in (
        ("gen-node-key", cmd_gen_node_key),
        ("show-node-id", cmd_show_node_id),
        ("gen-validator", cmd_gen_validator),
        ("show-validator", cmd_show_validator),
        ("version", cmd_version),
        ("compact", cmd_compact),
        ("replay", cmd_replay),
    ):
        p = sub.add_parser(name)
        p.set_defaults(fn=fn)

    p = sub.add_parser("reset", help="delete data, keep keys")
    p.set_defaults(fn=cmd_reset)
    p = sub.add_parser("unsafe-reset-all", help="delete data, keep keys")
    p.set_defaults(fn=lambda a: cmd_reset(a, all_=True))

    p = sub.add_parser("rollback", help="rewind state by one height")
    p.add_argument(
        "--hard", action="store_true", help="also delete the tip block"
    )
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser("reindex-event", help="rebuild tx/block indexes")
    p.add_argument("--start-height", type=int, default=0)
    p.add_argument("--end-height", type=int, default=0)
    p.set_defaults(fn=cmd_reindex_event)

    p = sub.add_parser("inspect", help="read-only RPC over data dirs")
    p.add_argument("--rpc-laddr", default="127.0.0.1:26657")
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("signer", help="remote signer daemon")
    p.add_argument(
        "-a", "--address", required=True,
        help="validator node's priv_validator_laddr to dial",
    )
    p.set_defaults(fn=cmd_signer)

    p = sub.add_parser(
        "bootstrap-state",
        help="seed stores with light-verified state (offline statesync)",
    )
    p.add_argument("--height", type=int, default=0)
    p.set_defaults(fn=cmd_bootstrap_state)

    p = sub.add_parser("debug", help="dump/kill a live node")
    p.add_argument("debug_cmd", choices=("dump", "kill"))
    p.add_argument("--pid", type=int, default=0, help="pid (kill only)")
    p.add_argument("--rpc-laddr", default="127.0.0.1:26657")
    p.add_argument("--pprof-laddr", default="")
    p.add_argument("--output-dir", default=".")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser(
        "load", help="generate tx load and report commit latency"
    )
    p.add_argument("--rpc-laddr", default="127.0.0.1:26657")
    p.add_argument("-r", "--rate", type=float, default=100.0)
    p.add_argument("-c", "--connections", type=int, default=1)
    p.add_argument("-s", "--size", type=int, default=256)
    p.add_argument("-T", "--time", type=float, default=10.0)
    p.set_defaults(fn=cmd_load)

    p = sub.add_parser(
        "abci-server", help="host the kvstore app over socket/grpc ABCI"
    )
    p.add_argument("-a", "--address", default="tcp://127.0.0.1:26658")
    p.add_argument(
        "-t", "--transport", choices=("socket", "grpc"), default="socket"
    )
    p.add_argument(
        "--persist", action="store_true", help="persist app state to home"
    )
    p.set_defaults(fn=cmd_abci_server)

    p = sub.add_parser(
        "abci-cli",
        help="client for a running ABCI app: one-shot, console, batch",
    )
    p.add_argument("-a", "--address", default="tcp://127.0.0.1:26658")
    p.add_argument(
        "abci_cmd",
        choices=(
            "echo", "info", "check_tx", "finalize_block",
            "prepare_proposal", "process_proposal", "commit", "query",
            "console", "batch",
        ),
    )
    p.add_argument("abci_args", nargs="*")
    p.set_defaults(fn=cmd_abci_cli)

    p = sub.add_parser("light", help="light client daemon / proxy")
    p.add_argument("chain_id")
    p.add_argument("-p", "--primary", required=True)
    p.add_argument("-w", "--witnesses", default="")
    p.add_argument("--trust-height", type=int, required=True)
    p.add_argument("--trust-hash", required=True)
    p.add_argument("--trust-period-h", type=float, default=168.0)
    p.add_argument("--interval-s", type=float, default=1.0)
    p.add_argument(
        "--sequential",
        action="store_true",
        help="verify every header in order instead of 9/16 skipping "
        "bisection (reference cmd light --sequential)",
    )
    p.add_argument(
        "--dir",
        default="",
        help="persist the trust store here (light.db); a restart "
        "resumes from the last verified header instead of the CLI "
        "trust root (reference light home dir)",
    )
    p.add_argument(
        "--laddr",
        default="",
        help="serve the light-verified RPC proxy on this address "
        "(headers/commits/validators/blocks verified; abci_query and "
        "tx responses proof-checked against the verified AppHash — "
        "reference `cometbft light` serves :8888)",
    )
    p.set_defaults(fn=cmd_light)

    return ap


def _pin_jax_platform() -> None:
    """Honor JAX_PLATFORMS over ambient site hooks: a sitecustomize
    may force-register a hardware plugin via jax.config at interpreter
    start, which BEATS the env var — an operator (or the e2e runner)
    pinning JAX_PLATFORMS=cpu would still get the plugin backend, and
    on a wedged accelerator the first big verify batch then hangs the
    node forever (observed: e2e late joiners stuck in jax.devices()
    against a dead tunnel). Re-pin the config itself before any
    compute path initializes a backend."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not getattr(args, "fn", None):
        build_parser().print_help()
        return 1
    if getattr(args.fn, "_reaches_jax", False):
        _pin_jax_platform()
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        return 130
    except FileNotFoundError as e:
        print(
            f"Error: {e.filename or e} not found — "
            "did you run `init` in this home dir?",
            file=sys.stderr,
        )
        return 1
    except Exception as e:
        if os.environ.get("CMT_DEBUG"):
            raise
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
