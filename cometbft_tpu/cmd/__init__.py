"""CLI command tree (reference cmd/cometbft/)."""
