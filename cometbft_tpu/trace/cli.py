"""Trace CLI:

    python -m cometbft_tpu.trace dump      FILE_OR_DIR...
    python -m cometbft_tpu.trace convert   FILE_OR_DIR... -o trace.json
    python -m cometbft_tpu.trace summarize FILE_OR_DIR... [--json]
                                           [--by-height]
    python -m cometbft_tpu.trace timeline  FILE_OR_DIR... [-o out.json]
                                           [--json] [--strict]

Inputs are JSONL trace files (one event per line, as written by
trace/export.write_jsonl — chaos dumps, bench --trace, node dumps) or
directories of them. ``convert`` emits Chrome trace-event JSON:
open the output at https://ui.perfetto.dev or chrome://tracing.

``timeline`` is the cross-node view (docs/TRACE.md "Cross-node
timelines"): rings are rebased onto one wall-clock axis via their
``clock.anchor`` events, merged causally ordered (``-o`` writes the
merged Perfetto JSON), and the per-height commit-latency waterfall
is printed — proposal propagation, block-part gossip, time-to-2/3
prevote/precommit, verify, wal, finalize. ``--strict`` exits 3 when
any committed height lacks a complete attribution chain.
"""

from __future__ import annotations

import argparse
import json
import sys

from .export import chrome_trace, read_jsonl, write_chrome
from .summary import (
    format_by_height,
    format_summary,
    summarize,
    summarize_by_height,
)
from .timeline import (
    attribute_heights,
    format_waterfall,
    rebase,
)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m cometbft_tpu.trace")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_dump = sub.add_parser(
        "dump", help="print events as JSON lines, time-ordered"
    )
    p_dump.add_argument("paths", nargs="+")

    p_conv = sub.add_parser(
        "convert", help="convert to Chrome trace JSON (Perfetto)"
    )
    p_conv.add_argument("paths", nargs="+")
    p_conv.add_argument(
        "-o", "--out", help="output file (default: stdout)"
    )

    p_sum = sub.add_parser(
        "summarize",
        help="p50/p95/p99 per span kind per node",
    )
    p_sum.add_argument("paths", nargs="+")
    p_sum.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p_sum.add_argument(
        "--budget",
        nargs="?",
        const="",
        default=None,
        metavar="FILE",
        help="evaluate span budgets (obs/budget.py; default file "
        "tools/span_budgets.toml) and exit 2 on any violation",
    )
    p_sum.add_argument(
        "--by-height",
        action="store_true",
        help="also group height-tagged spans per height "
        "(cross-node aggregate)",
    )

    p_tl = sub.add_parser(
        "timeline",
        help="cross-node causal timeline + per-height "
        "commit-latency waterfall",
    )
    p_tl.add_argument("paths", nargs="+")
    p_tl.add_argument(
        "-o",
        "--out",
        help="write the merged clock-rebased Chrome trace JSON here",
    )
    p_tl.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p_tl.add_argument(
        "--strict",
        action="store_true",
        help="exit 3 if any committed height lacks a complete "
        "attribution chain",
    )

    args = ap.parse_args(argv)
    events = read_jsonl(args.paths)
    if not events:
        print("no trace events found", file=sys.stderr)
        return 1

    if args.cmd == "dump":
        flat = [
            {"node": node, **e}
            for node, evs in events.items()
            for e in evs
        ]
        flat.sort(key=lambda e: e.get("ts_ns", 0))
        try:
            for e in flat:
                print(json.dumps(e))
        except BrokenPipeError:
            # downstream pager/head closed the pipe: a clean exit,
            # not a traceback
            sys.stderr.close()
    elif args.cmd == "timeline":
        rebased, offsets, base_wall = rebase(events)
        heights = attribute_heights(rebased)
        if args.out:
            write_chrome(args.out, rebased)
        unanchored = sorted(n for n, o in offsets.items() if o is None)
        if args.json:
            print(
                json.dumps(
                    {
                        "base_wall_ns": base_wall,
                        "offsets_ns": offsets,
                        "unanchored": unanchored,
                        "events": sum(
                            len(v) for v in rebased.values()
                        ),
                        "heights": {
                            str(h): s for h, s in heights.items()
                        },
                    },
                    indent=2,
                )
            )
        else:
            spread = [o for o in offsets.values() if o is not None]
            if spread:
                print(
                    f"clock anchors: {len(spread)}/{len(offsets)} "
                    f"rings, offset spread "
                    f"{(max(spread) - min(spread)) / 1e6:.3f}ms"
                )
            if unanchored:
                print(
                    "unanchored rings (median offset borrowed): "
                    + ", ".join(unanchored)
                )
            if args.out:
                print(
                    f"wrote {args.out}: "
                    f"{sum(len(v) for v in rebased.values())} events "
                    f"from {len(rebased)} ring(s) — load in "
                    f"ui.perfetto.dev"
                )
            print(format_waterfall(heights))
        if args.strict and (
            not heights
            or any(not s["complete"] for s in heights.values())
        ):
            return 3
    elif args.cmd == "convert":
        if args.out:
            write_chrome(args.out, events)
            n = sum(len(v) for v in events.values())
            print(
                f"wrote {args.out}: {n} events from "
                f"{len(events)} node(s) — load in ui.perfetto.dev"
            )
        else:
            json.dump(chrome_trace(events), sys.stdout)
            print()
    else:  # summarize
        s = summarize(events)
        by_height = (
            summarize_by_height(events) if args.by_height else None
        )
        verdicts = None
        if args.budget is not None:
            # late import: the budget engine pulls tomllib; plain
            # summarize must keep working without it
            from ..obs.budget import (
                budgets_ok,
                default_budget_file,
                evaluate_budgets,
                format_verdicts,
                load_budgets,
            )

            budget_path = args.budget or default_budget_file()
            budgets = load_budgets(budget_path)
            verdicts = evaluate_budgets(s, budgets)
        if args.json:
            doc = dict(s)
            if verdicts is not None or by_height is not None:
                doc = {"summary": s}
                if by_height is not None:
                    doc["by_height"] = {
                        str(h): v for h, v in by_height.items()
                    }
                if verdicts is not None:
                    doc["budget_verdicts"] = verdicts
            print(json.dumps(doc, indent=2))
        else:
            print(format_summary(s))
            if by_height is not None:
                print()
                print(format_by_height(by_height))
            if verdicts is not None:
                print()
                print(format_verdicts(verdicts))
        if verdicts is not None and not budgets_ok(verdicts):
            return 2
    return 0
