"""Span-duration summaries: p50/p95/p99 per span kind per node.

The attribution layer over the raw rings: `summarize` reduces
{node: [events]} to per-span-kind latency stats, `format_summary`
renders the text table the CLI and the chaos smoke print.
"""

from __future__ import annotations

from typing import Dict, List


def percentile(sorted_ns: List[int], q: float) -> float:
    """Nearest-rank-with-interpolation percentile over a pre-sorted
    list (numpy-free: the linter/CI lane imports this)."""
    if not sorted_ns:
        return 0.0
    if len(sorted_ns) == 1:
        return float(sorted_ns[0])
    pos = (len(sorted_ns) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(sorted_ns) - 1)
    frac = pos - lo
    return sorted_ns[lo] * (1.0 - frac) + sorted_ns[hi] * frac


def summarize(events_by_node: Dict[str, List[dict]]) -> Dict:
    """{node: {span_name: {count, p50_ms, p95_ms, p99_ms, max_ms,
    total_ms}}} over complete ("X") events; counter kinds surface
    under "_counters" with their last seen value."""
    out: Dict = {}
    for node in sorted(events_by_node):
        spans: Dict[str, List[int]] = {}
        counters: Dict[str, object] = {}
        for e in events_by_node[node]:
            ph = e.get("ph", "X")
            if ph == "X":
                spans.setdefault(e["name"], []).append(
                    e.get("dur_ns", 0)
                )
            elif ph == "C":
                counters[e["name"]] = (e.get("args") or {}).get("value")
        node_sum: Dict = {}
        for name in sorted(spans):
            ds = sorted(spans[name])
            ms = 1e6
            node_sum[name] = {
                "count": len(ds),
                "p50_ms": round(percentile(ds, 0.50) / ms, 3),
                "p95_ms": round(percentile(ds, 0.95) / ms, 3),
                "p99_ms": round(percentile(ds, 0.99) / ms, 3),
                "max_ms": round(ds[-1] / ms, 3),
                "total_ms": round(sum(ds) / ms, 3),
            }
        if counters:
            node_sum["_counters"] = counters
        out[node] = node_sum
    return out


def summarize_by_height(events_by_node: Dict[str, List[dict]]) -> Dict:
    """{height: {span_name: {count, p50_ms, max_ms, total_ms}}} over
    the complete spans that carry a height arg (``height`` on the
    consensus spans, ``h`` on the compact p2p events), aggregated
    ACROSS nodes — the per-height grouping behind
    ``summarize --by-height`` (docs/TRACE.md)."""
    per_h: Dict[int, Dict[str, List[int]]] = {}
    for events in events_by_node.values():
        for e in events:
            if e.get("ph", "X") != "X":
                continue
            a = e.get("args") or {}
            h = a.get("height", a.get("h"))
            if h in (None, 0):
                continue
            per_h.setdefault(int(h), {}).setdefault(
                e["name"], []
            ).append(e.get("dur_ns", 0))
    ms = 1e6
    out: Dict = {}
    for h in sorted(per_h):
        spans: Dict = {}
        for name in sorted(per_h[h]):
            ds = sorted(per_h[h][name])
            spans[name] = {
                "count": len(ds),
                "p50_ms": round(percentile(ds, 0.50) / ms, 3),
                "max_ms": round(ds[-1] / ms, 3),
                "total_ms": round(sum(ds) / ms, 3),
            }
        out[h] = spans
    return out


def format_by_height(by_height: Dict) -> str:
    """One block per height, aggregated across nodes."""
    if not by_height:
        return "no height-tagged spans found"
    lines: List[str] = []
    hdr = (
        f"{'span':<34} {'count':>7} {'p50ms':>9} {'max ms':>9} "
        f"{'total ms':>10}"
    )
    for h, spans in by_height.items():
        lines.append(f"== height {h} ==")
        lines.append(hdr)
        for name, s in spans.items():
            lines.append(
                f"{name:<34} {s['count']:>7} {s['p50_ms']:>9} "
                f"{s['max_ms']:>9} {s['total_ms']:>10}"
            )
        lines.append("")
    return "\n".join(lines).rstrip()


def format_summary(summary: Dict) -> str:
    """Aligned text table, one block per node."""
    lines: List[str] = []
    hdr = (
        f"{'span':<34} {'count':>7} {'p50ms':>9} {'p95ms':>9} "
        f"{'p99ms':>9} {'max ms':>9} {'total ms':>10}"
    )
    for node, kinds in summary.items():
        lines.append(f"== {node} ==")
        lines.append(hdr)
        for name, s in kinds.items():
            if name == "_counters":
                continue
            lines.append(
                f"{name:<34} {s['count']:>7} {s['p50_ms']:>9} "
                f"{s['p95_ms']:>9} {s['p99_ms']:>9} {s['max_ms']:>9} "
                f"{s['total_ms']:>10}"
            )
        counters = kinds.get("_counters")
        if counters:
            for cname, v in sorted(counters.items()):
                lines.append(f"{cname:<34} last={v}")
        lines.append("")
    return "\n".join(lines).rstrip()
