"""Per-node fixed-size ring-buffer event tracer.

Design constraints (docs/TRACE.md):

- **Preallocated slots** — the ring is a list of fixed-shape slot
  lists created once at construction; appends overwrite slot fields
  in place, so the ring itself never grows or churns slot objects
  after warmup (asserted by tests/test_trace.py).
- **Lock-free single-writer append** — the write cursor is an
  ``itertools.count``, whose ``next()`` is atomic under the GIL, so
  the per-asyncio-loop single writer needs no lock and the rare
  off-loop writers (crypto pool workers appending to the process
  tracer) cannot corrupt the cursor; concurrent writers can only
  ever contend for *different* slots unless the ring has already
  lapped, in which case the older event was due to be overwritten
  anyway.
- **Strict no-op fast path when disabled** — ``span()`` /
  ``instant()`` / ``counter()`` check one attribute and return a
  shared singleton; the hottest call sites may additionally guard on
  ``tracer.enabled`` themselves.
- **Monotonic timestamps only** — ``time.monotonic_ns``; wall-clock
  reads are forbidden in this package (bftlint ASY107): a span whose
  endpoints straddle an NTP step would report negative or garbage
  durations.

Event slot layout (index into the slot list):
    [seq, name, ph, ts_ns, dur_ns, tid, args]
``ph`` follows the Chrome trace-event phase letters: "X" complete
span, "i" instant, "C" counter.
"""

from __future__ import annotations

import itertools
import time
from typing import Callable, Dict, List, Optional

_monotonic_ns = time.monotonic_ns

# slot field indices
_SEQ, _NAME, _PH, _TS, _DUR, _TID, _ARGS = range(7)

_DEFAULT_TID = "main"


class _NoopSpan:
    """Shared do-nothing span: the disabled-tracer fast path and the
    NOOP tracer both hand this out, so call sites never branch."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **args) -> None:
        pass

    def end(self) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _Span:
    """In-flight span; records ONE complete ("X") event on end().
    Usable as a context manager or via manual ``end()`` (the
    consensus step machine closes spans from a different callsite
    than it opens them)."""

    __slots__ = ("_tracer", "_name", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, tid, args, t0) -> None:
        self._tracer = tracer
        self._name = name
        self._tid = tid
        self._args = args
        self._t0 = t0

    def __enter__(self) -> "_Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False

    def set(self, **args) -> None:
        """Attach/overwrite args after the span opened (e.g. a reap
        span learns its tx count at the end)."""
        self._args.update(args)

    def end(self) -> None:
        tr = self._tracer
        if tr is None:
            return  # idempotent: __exit__ after an explicit end()
        self._tracer = None
        t0 = self._t0
        tr._append(
            self._name, "X", t0, _monotonic_ns() - t0, self._tid,
            self._args,
        )


class Tracer:
    """Fixed-size ring of trace events (see module docstring).

    ``observers`` receive every completed span as
    ``fn(name, dur_ns, args)`` — the span→metrics bridge
    (trace/bridge.py) rides this; the list is empty by default so the
    hot path pays one truthiness check.
    """

    __slots__ = (
        "enabled", "name", "_n", "_ring", "_count", "_observers",
        "meta",
    )

    def __init__(
        self, name: str = "node", size: int = 16384,
        enabled: bool = True,
    ) -> None:
        if size < 1:
            raise ValueError("ring size must be >= 1")
        self.name = name
        self.enabled = enabled
        self._n = size
        self._ring: List[list] = [
            [None, None, None, 0, 0, None, None] for _ in range(size)
        ]
        self._count = itertools.count()
        self._observers: List[Callable] = []
        # ring-level metadata set by the BUILDER (node code), never by
        # this plane: the monotonic→wall clock anchor lives here so
        # cross-node timelines can rebase rings from different
        # processes (ASY107 keeps wall-clock reads out of trace/)
        self.meta: Dict = {}

    # --- append paths -------------------------------------------------

    def _append(self, name, ph, ts, dur, tid, args) -> None:
        i = next(self._count)
        s = self._ring[i % self._n]
        s[_SEQ] = i
        s[_NAME] = name
        s[_PH] = ph
        s[_TS] = ts
        s[_DUR] = dur
        s[_TID] = tid or _DEFAULT_TID
        s[_ARGS] = args
        obs = self._observers
        if obs and ph == "X":
            dead = None
            for fn in obs:
                try:
                    fn(name, dur, args)
                except Exception:
                    # a broken observer must never take down the hot
                    # path it observes: drop it after the first failure
                    dead = fn if dead is None else dead
            if dead is not None:
                try:
                    self._observers.remove(dead)
                except ValueError:
                    pass

    def span(self, name: str, tid: Optional[str] = None, **args):
        """Open a span; record happens at ``end()`` / ``__exit__``."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, tid, args, _monotonic_ns())

    def complete(
        self, name: str, ts_ns: int, dur_ns: int,
        tid: Optional[str] = None, **args,
    ) -> None:
        """Record an already-measured complete span (callers that
        timed the work themselves, e.g. the loop watchdog's lag
        beats); observers fire exactly as for span().end()."""
        if not self.enabled:
            return
        self._append(name, "X", ts_ns, dur_ns, tid, args)

    def instant(self, name: str, tid: Optional[str] = None, **args) -> None:
        if not self.enabled:
            return
        self._append(name, "i", _monotonic_ns(), 0, tid, args)

    def instant_at(
        self, name: str, ts_ns: int, tid: Optional[str] = None, **args
    ) -> None:
        """Instant with a caller-supplied monotonic timestamp (the
        p2p stamping plane records send instants at the exact instant
        baked into the wire stamp)."""
        if not self.enabled:
            return
        self._append(name, "i", ts_ns, 0, tid, args)

    def counter(self, name: str, value, tid: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self._append(
            name, "C", _monotonic_ns(), 0, tid, {"value": value}
        )

    # --- observers (span→metrics bridge) ------------------------------

    def add_observer(self, fn: Callable) -> None:
        """fn(name, dur_ns, args) on every completed span."""
        self._observers.append(fn)

    def remove_observer(self, fn: Callable) -> None:
        try:
            self._observers.remove(fn)
        except ValueError:
            pass

    # --- reading ------------------------------------------------------

    def snapshot(self) -> List[Dict]:
        """Events currently in the ring, oldest first. Safe to call
        while writers append (a concurrently-overwritten slot may
        surface a torn event; post-run dumps — the only consumers —
        never race)."""
        out = []
        for s in self._ring:
            if s[_SEQ] is None:
                continue
            args = s[_ARGS]
            out.append(
                {
                    "seq": s[_SEQ],
                    "name": s[_NAME],
                    "ph": s[_PH],
                    "ts_ns": s[_TS],
                    "dur_ns": s[_DUR],
                    "tid": s[_TID],
                    "args": dict(args) if args else {},
                }
            )
        out.sort(key=lambda e: e["seq"])
        return out

    def stats(self) -> Dict:
        events = self.snapshot()
        written = (events[-1]["seq"] + 1) if events else 0
        return {
            "name": self.name,
            "ring": self._n,
            "written": written,
            "dropped": max(0, written - self._n),
        }

    def clear(self) -> None:
        for s in self._ring:
            s[_SEQ] = None
            s[_NAME] = None
            s[_ARGS] = None


# The shared disabled tracer: instrumented classes default to this so
# every call site can do `self.tracer.span(...)` unconditionally.
NOOP = Tracer(name="noop", size=1, enabled=False)
