"""Trace export: JSONL (native dump format) and Chrome trace-event
JSON (loadable in Perfetto / chrome://tracing).

JSONL is one event object per line with the owning node name embedded
(`{"node": ..., "seq": ..., "name": ..., "ph": ..., "ts_ns": ...,
"dur_ns": ..., "tid": ..., "args": {...}}`), so files from different
nodes concatenate and re-split trivially.

The Chrome format maps node → pid and track → tid with "M" metadata
events naming both; complete spans are "X" events (ts/dur in
microseconds — the format's unit), instants "i", counters "C".
Timestamps are monotonic ns shared by every tracer in one process, so
multi-node in-process runs (chaos, LocalNet) land on one aligned
timeline.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List

EventsByNode = Dict[str, List[dict]]


# --- JSONL ---------------------------------------------------------------


def write_jsonl(path: str, node: str, events: Iterable[dict]) -> str:
    """Write one node's events as JSONL; returns the path."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps({"node": node, **e}) + "\n")
    return path


def read_jsonl(paths: Iterable[str]) -> EventsByNode:
    """Load JSONL trace files (or directories of ``*.jsonl``) into
    {node: [events]}; events keep file order (writers emit
    seq-sorted)."""
    out: EventsByNode = {}
    for p in _expand(paths):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                node = e.pop("node", os.path.basename(p))
                out.setdefault(node, []).append(e)
    return out


def _expand(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                os.path.join(p, n)
                for n in sorted(os.listdir(p))
                if n.endswith(".jsonl")
            )
        else:
            out.append(p)
    return out


# --- Chrome trace-event JSON --------------------------------------------


def chrome_trace(events_by_node: EventsByNode) -> dict:
    """Build the Chrome trace-event object (Perfetto-loadable)."""
    te: List[dict] = []
    for pid, node in enumerate(sorted(events_by_node)):
        events = events_by_node[node]
        te.append(
            {
                "ph": "M", "pid": pid, "tid": 0,
                "name": "process_name", "args": {"name": node},
            }
        )
        tids: Dict[str, int] = {}
        for e in events:
            track = e.get("tid") or "main"
            if track not in tids:
                tids[track] = len(tids)
                te.append(
                    {
                        "ph": "M", "pid": pid, "tid": tids[track],
                        "name": "thread_name",
                        "args": {"name": track},
                    }
                )
        for e in events:
            ph = e.get("ph", "X")
            base = {
                "ph": ph,
                "pid": pid,
                "tid": tids[e.get("tid") or "main"],
                "name": e["name"],
                "ts": e["ts_ns"] / 1e3,
                "cat": e["name"].split(".")[0],
                "args": e.get("args") or {},
            }
            if ph == "X":
                base["dur"] = e.get("dur_ns", 0) / 1e3
            elif ph == "i":
                base["s"] = "t"  # thread-scoped instant
            te.append(base)
    return {"traceEvents": te, "displayTimeUnit": "ms"}


def write_chrome(path: str, events_by_node: EventsByNode) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace(events_by_node), f)
    return path
