"""Span→metrics bridge: routes completed span durations into
observer callables (utils/metrics.py NodeMetrics histograms).

Kept deliberately dumb and dependency-free: NodeMetrics registers
`route(span_name, fn)` entries pointing at its own
``Histogram.observe`` closures, then installs the bridge as a tracer
observer (Tracer.add_observer). The tracer side stays metrics-agnostic
and pays one dict lookup per completed span.
"""

from __future__ import annotations

from typing import Callable, Dict


class SpanMetricsBridge:
    """Routes ``(name, dur_ns, args)`` span completions to per-kind
    callables ``fn(dur_s, args)``."""

    __slots__ = ("_routes",)

    def __init__(self) -> None:
        self._routes: Dict[str, Callable] = {}

    def route(self, span_name: str, fn: Callable) -> "SpanMetricsBridge":
        self._routes[span_name] = fn
        return self

    def __call__(self, name: str, dur_ns: int, args: dict) -> None:
        fn = self._routes.get(name)
        if fn is not None:
            fn(dur_ns / 1e9, args)
