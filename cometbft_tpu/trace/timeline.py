"""Cross-node causal timelines (docs/TRACE.md "Cross-node
timelines").

Every ring records monotonic-ns timestamps, which are meaningless
across processes. The node builder (node/inprocess.py
``record_clock_anchor`` — deliberately outside this package, ASY107
bans wall-clock reads in trace/) stamps each ring with ONE
monotonic→wall anchor: a ``clock.anchor`` instant whose ``ts_ns`` is
a monotonic read and whose ``args.wall_ns`` is the wall clock read
back-to-back with it. This module rebases every ring onto the shared
wall axis (then zeroes at the earliest event so Perfetto opens at
t=0), merges them into one causally-ordered view, and computes the
per-height **commit-latency waterfall** from the correlated
send/recv instants the p2p stamping plane (p2p/tracewire.py) and the
consensus attribution marks (consensus/state.py) record:

    proposal propagation -> block-part gossip -> time-to-2/3 prevote
    -> time-to-2/3 precommit -> verify -> wal.fsync -> finalize

Alignment caveat (docs/TRACE.md): anchors are only as good as the
nodes' wall clocks. In-process nets (chaos, LocalNet) share one
clock, so rebased instants are exact; across hosts the residual
error is the NTP skew between them. Rings missing an anchor (ancient
dumps, laps that also outran ``Tracer.meta`` injection) borrow the
median offset of the anchored rings — right for one process, flagged
in the output either way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

ANCHOR_EVENT = "clock.anchor"

EventsByNode = Dict[str, List[dict]]


# --- clock rebasing ------------------------------------------------------


def anchor_offsets(events_by_node: EventsByNode) -> Dict[str, Optional[int]]:
    """{node: wall_ns - mono_ns} from each ring's ``clock.anchor``
    instant; None for rings that never recorded one."""
    out: Dict[str, Optional[int]] = {}
    for node, events in events_by_node.items():
        off = None
        for e in events:
            if e.get("name") == ANCHOR_EVENT:
                wall = (e.get("args") or {}).get("wall_ns")
                if wall is not None:
                    off = int(wall) - int(e["ts_ns"])
                    break
        out[node] = off
    return out


def rebase(
    events_by_node: EventsByNode,
) -> Tuple[EventsByNode, Dict[str, Optional[int]], int]:
    """Rebase every ring onto one shared time axis.

    Returns ``(rebased, offsets, base_wall_ns)``: event copies whose
    ``ts_ns`` is wall-anchored and zeroed at the earliest event
    (stable-sorted by timestamp per node), the per-node raw offsets
    (None marks a ring that borrowed the median), and the wall-ns
    origin the zero corresponds to."""
    offsets = anchor_offsets(events_by_node)
    known = sorted(o for o in offsets.values() if o is not None)
    # same-process fallback: the median anchored offset (0 when no
    # ring is anchored at all — raw monotonic is then the best axis)
    fallback = known[len(known) // 2] if known else 0
    rebased: EventsByNode = {}
    base = None
    for node, events in events_by_node.items():
        off = offsets[node]
        eff = fallback if off is None else off
        evs = [dict(e, ts_ns=e["ts_ns"] + eff) for e in events]
        evs.sort(key=lambda e: e["ts_ns"])  # stable: ties keep order
        rebased[node] = evs
        if evs and (base is None or evs[0]["ts_ns"] < base):
            base = evs[0]["ts_ns"]
    base = base or 0
    for evs in rebased.values():
        for e in evs:
            e["ts_ns"] -= base
    return rebased, offsets, base


def merge_events(rebased: EventsByNode) -> List[dict]:
    """One flat causally-ordered stream: rebased events from every
    ring, each tagged with its node, stable-sorted by timestamp."""
    flat = [
        dict(e, node=node)
        for node in sorted(rebased)
        for e in rebased[node]
    ]
    flat.sort(key=lambda e: e["ts_ns"])  # stable within equal stamps
    return flat


# --- per-height commit-latency attribution -------------------------------


def _harg(e: dict) -> Optional[int]:
    """Height from either arg spelling (spans say ``height``, the
    compact p2p instants say ``h``)."""
    a = e.get("args") or {}
    h = a.get("height", a.get("h"))
    return int(h) if h is not None else None


def attribute_heights(events_by_node: EventsByNode) -> Dict[int, dict]:
    """The per-height commit-latency waterfall over already-rebased
    rings (call ``rebase`` first; raw monotonic input still works for
    single-process dumps).

    A height is attributed when any ring finalized it. Its chain is
    ``complete`` when the proposal send on the proposer correlates to
    an arrival on every other committing node (a proposal/part recv
    or, for catch-up commits, a ``commit_block`` recv) and both
    quorum legs were measured. All ms values are relative to the
    proposal send instant except the per-node quorum durations, which
    are time-from-round-entry as recorded on each node."""
    ms = 1e6
    heights: Dict[int, dict] = {}

    def slot(h: int) -> dict:
        return heights.setdefault(
            h,
            {
                "height": h,
                "proposer": None,
                "proposal_send_ns": None,
                "proposal_recv": {},  # node -> earliest proposal recv
                "part_recv": {},  # node -> earliest block_part recv
                "catchup_recv": {},  # node -> commit_block recv ns
                "proposal_complete": {},  # node -> instant ns
                "quorum_prevote_ms": {},  # node -> dur ms
                "quorum_precommit_ms": {},
                "verify_ms": {},
                "finalize": {},  # node -> {total/persist/wal/apply}
                "committed": [],
            },
        )

    for node, events in events_by_node.items():
        for e in events:
            name = e.get("name")
            if name == "p2p.msg.send":
                a = e.get("args") or {}
                if a.get("kind") == "proposal":
                    h = _harg(e)
                    if h is None:
                        continue
                    s = slot(h)
                    # earliest proposal send = the proposer's own
                    # broadcast (relays come later by causality)
                    if (
                        s["proposal_send_ns"] is None
                        or e["ts_ns"] < s["proposal_send_ns"]
                    ):
                        s["proposal_send_ns"] = e["ts_ns"]
                        s["proposer"] = node
            elif name == "p2p.msg.recv":
                a = e.get("args") or {}
                kind = a.get("kind")
                h = _harg(e)
                if h is None:
                    continue
                if kind in ("proposal", "block_part"):
                    d = slot(h)[
                        "proposal_recv" if kind == "proposal"
                        else "part_recv"
                    ]
                    if node not in d or e["ts_ns"] < d[node]:
                        d[node] = e["ts_ns"]
                elif kind == "commit_block":
                    d = slot(h)["catchup_recv"]
                    if node not in d or e["ts_ns"] < d[node]:
                        d[node] = e["ts_ns"]
            elif name == "consensus.proposal.complete":
                h = _harg(e)
                if h is not None:
                    slot(h)["proposal_complete"][node] = e["ts_ns"]
            elif name in (
                "consensus.quorum.prevote",
                "consensus.quorum.precommit",
            ):
                h = _harg(e)
                if h is None:
                    continue
                key = (
                    "quorum_prevote_ms"
                    if name.endswith("prevote")
                    else "quorum_precommit_ms"
                )
                slot(h)[key][node] = round(e.get("dur_ns", 0) / ms, 3)
            elif name == "consensus.verify":
                h = _harg(e)
                if h is not None:
                    slot(h)["verify_ms"][node] = round(
                        e.get("dur_ns", 0) / ms, 3
                    )
            elif name == "consensus.finalize":
                h = _harg(e)
                if h is None:
                    continue
                a = e.get("args") or {}
                s = slot(h)
                s["finalize"][node] = {
                    "total_ms": round(e.get("dur_ns", 0) / ms, 3),
                    "persist_ms": a.get("persist_ms"),
                    "wal_ms": a.get("wal_ms"),
                    "apply_ms": a.get("apply_ms"),
                }
                s["committed"].append(node)

    # derive the waterfall legs + completeness per committed height
    out: Dict[int, dict] = {}
    for h in sorted(heights):
        s = heights[h]
        if not s["committed"]:
            continue  # gossip about a height nobody (visible) committed
        s["committed"] = sorted(set(s["committed"]))
        send = s["proposal_send_ns"]
        if send is not None:
            s["propagation_ms"] = {
                n: round((t - send) / ms, 3)
                for n, t in sorted(s["proposal_recv"].items())
                if n != s["proposer"]
            }
            s["parts_ms"] = {
                n: round((t - send) / ms, 3)
                for n, t in sorted(s["proposal_complete"].items())
            }
        else:
            s["propagation_ms"] = {}
            s["parts_ms"] = {}
        missing = []
        for n in s["committed"]:
            if n == s["proposer"]:
                continue
            if (
                n not in s["proposal_recv"]
                and n not in s["part_recv"]
                and n not in s["proposal_complete"]
                and n not in s["catchup_recv"]
            ):
                missing.append(n)
        s["missing_arrival"] = missing
        s["complete"] = bool(
            s["proposer"] is not None
            and not missing
            and s["quorum_prevote_ms"]
            and s["quorum_precommit_ms"]
        )
        # the internal correlation keys aren't part of the report
        for k in (
            "proposal_recv", "part_recv", "catchup_recv",
            "proposal_complete",
        ):
            s.pop(k)
        out[h] = s
    return out


def attribution_key(heights: Dict[int, dict]) -> List[tuple]:
    """The deterministic skeleton of an attribution table: per height
    the proposer, the committing nodes and chain completeness — what
    same-seed runs reproduce exactly (latency columns are wall-clock
    and jitter run to run)."""
    return [
        (
            h,
            s["proposer"],
            tuple(s["committed"]),
            s["complete"],
        )
        for h, s in sorted(heights.items())
    ]


def format_waterfall(heights: Dict[int, dict]) -> str:
    """The per-height attribution table chaos_smoke prints: worst
    (max-over-nodes) value per leg, in waterfall order."""
    if not heights:
        return "no committed heights found in the trace"

    def mx(d):
        vals = [v for v in d.values() if v is not None]
        return f"{max(vals):.1f}" if vals else "-"

    hdr = (
        f"{'height':>6} {'proposer':<10} {'prop ms':>8} {'parts ms':>9} "
        f"{'prevote ms':>11} {'precommit ms':>13} {'verify ms':>10} "
        f"{'wal ms':>7} {'final ms':>9} {'nodes':>6} chain"
    )
    lines = [hdr]
    for h in sorted(heights):
        s = heights[h]
        fin = s["finalize"]
        wal = {n: f.get("wal_ms") for n, f in fin.items()}
        tot = {n: f.get("total_ms") for n, f in fin.items()}
        lines.append(
            f"{h:>6} {s['proposer'] or '?':<10} "
            f"{mx(s['propagation_ms']):>8} {mx(s['parts_ms']):>9} "
            f"{mx(s['quorum_prevote_ms']):>11} "
            f"{mx(s['quorum_precommit_ms']):>13} "
            f"{mx(s['verify_ms']):>10} {mx(wal):>7} {mx(tot):>9} "
            f"{len(s['committed']):>6} "
            + ("complete" if s["complete"] else "PARTIAL")
        )
    n_partial = sum(1 for s in heights.values() if not s["complete"])
    lines.append(
        f"attribution: {len(heights)} heights, "
        + (
            "all chains complete"
            if n_partial == 0
            else f"{n_partial} PARTIAL chains"
        )
    )
    return "\n".join(lines)
