"""Always-on low-overhead tracing plane (docs/TRACE.md).

Per-node fixed-size ring-buffer tracers with a span API over the hot
planes (consensus step lifecycle, blocksync windows, crypto batch
verify, mempool, WAL fsync), Chrome trace-event / JSONL export
(Perfetto-loadable) and p50/p95/p99 summaries.

Two tracer scopes:

- **per-node** — built by node/inprocess.build_node when
  ``[instrumentation] trace_enabled`` (default on); carried on
  NodeParts.tracer and attached to the node's consensus state,
  mempool, WAL, blocksync reactor and switch.
- **process-wide** — ``global_tracer()``: the landing zone for
  planes shared across in-process nodes (the crypto parallel-verify
  worker pool). Disabled until the first tracing-enabled node calls
  ``enable_global()``; worker subprocesses never enable it, so the
  pickled chunk path stays no-op there.

Instrumented classes default ``self.tracer`` to the shared ``NOOP``
tracer, so call sites are unconditional and the disabled path is one
attribute check (tests/test_trace.py bounds it).
"""

from .bridge import SpanMetricsBridge
from .export import chrome_trace, read_jsonl, write_chrome, write_jsonl
from .summary import (
    format_summary,
    percentile,
    summarize,
    summarize_by_height,
)
from .timeline import (
    attribute_heights,
    attribution_key,
    format_waterfall,
    merge_events,
    rebase,
)
from .tracer import NOOP, NOOP_SPAN, Tracer

__all__ = [
    "NOOP",
    "NOOP_SPAN",
    "SpanMetricsBridge",
    "Tracer",
    "attribute_heights",
    "attribution_key",
    "chrome_trace",
    "enable_global",
    "format_summary",
    "format_waterfall",
    "global_tracer",
    "merge_events",
    "percentile",
    "read_jsonl",
    "rebase",
    "summarize",
    "summarize_by_height",
    "write_chrome",
    "write_jsonl",
]

# process-wide tracer for cross-node planes (crypto worker pool)
_GLOBAL = Tracer(name="process", size=8192, enabled=False)


def global_tracer() -> Tracer:
    return _GLOBAL


def enable_global(enabled: bool = True) -> Tracer:
    """Flip the process-wide tracer; idempotent (called by every
    tracing-enabled node build)."""
    _GLOBAL.enabled = enabled
    return _GLOBAL
