from .client import Client, TrustOptions, SEQUENTIAL, SKIPPING  # noqa: F401
from .provider import Provider, StoreBackedProvider  # noqa: F401
from .serving import (  # noqa: F401
    CoalescedCommitVerifier,
    LightServingPlane,
    ServingOverloadError,
    VerifiedHeaderCache,
)
from .store import LightStore  # noqa: F401
from .types import LightBlock  # noqa: F401
from . import verifier  # noqa: F401
