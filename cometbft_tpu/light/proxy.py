"""Light client RPC proxy (reference light/proxy/proxy.go + light/rpc):
a local JSON-RPC server whose block/header/commit/validators responses
are LIGHT-VERIFIED before being served — a wallet can point at this
instead of trusting a full node.

Routes proxied with verification: block, header, commit, validators,
status (verified tip). Unverifiable routes (tx submission) pass
through to the primary."""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from ..rpc import encoding as enc
from ..rpc.client import HTTPClient
from ..utils import codec
from .client import Client


class LightProxy:
    def __init__(self, client: Client, primary_url: str):
        self.lc = client
        self.primary = HTTPClient(primary_url)
        self.app = web.Application()
        self.app.router.add_get("/{method}", self._handle)
        self.app.router.add_post("/", self._handle_post)
        self._runner: Optional[web.AppRunner] = None
        self.listen_addr = ""

    async def start(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host or "127.0.0.1", int(port))
        await site.start()
        h, p = site._server.sockets[0].getsockname()[:2]  # noqa: SLF001
        self.listen_addr = f"{h}:{p}"

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        await self.primary.close()

    # --- verified route implementations -------------------------------

    async def _verified_light_block(self, height: Optional[int]):
        """Run the (blocking) light client off-loop."""
        if height is None:
            st = await self.primary.status()
            height = int(st["sync_info"]["latest_block_height"])
        return await asyncio.to_thread(
            self.lc.verify_light_block_at_height, height
        )

    async def _call(self, method: str, params: Dict[str, Any]):
        h = params.get("height")
        h = int(h) if h not in (None, "") else None
        if method == "header":
            lb = await self._verified_light_block(h)
            return {
                "header": enc.header_json(lb.header),
                "header_b64": enc.b64(codec.encode_header(lb.header)),
                "verified": True,
            }
        if method == "commit":
            lb = await self._verified_light_block(h)
            return {
                "signed_header": {
                    "header": enc.header_json(lb.header),
                    "commit": enc.commit_json(lb.commit),
                },
                "header_b64": enc.b64(codec.encode_header(lb.header)),
                "commit_b64": enc.b64(codec.encode_commit(lb.commit)),
                "verified": True,
            }
        if method == "validators":
            lb = await self._verified_light_block(h)
            return {
                "block_height": str(lb.height),
                "validators": [
                    enc.validator_json(v)
                    for v in lb.validator_set.validators
                ],
                "validator_set_b64": enc.b64(
                    codec.encode_validator_set(lb.validator_set)
                ),
                "verified": True,
            }
        if method == "block":
            lb = await self._verified_light_block(h)
            # fetch the full block from the primary, verify its hash
            # against the light-verified header
            res = await self.primary.block(lb.height)
            import base64

            blk = codec.decode_block(base64.b64decode(res["block_b64"]))
            if bytes(blk.hash()) != bytes(lb.header.hash()):
                raise RuntimeError(
                    "primary served a block that does not match the "
                    "verified header"
                )
            res["verified"] = True
            return res
        if method == "status":
            lb = await self._verified_light_block(None)
            return {
                "sync_info": {
                    "latest_block_height": str(lb.height),
                    "latest_block_hash": enc.hexb(lb.hash()),
                    "latest_block_time_ns": str(lb.header.time_ns),
                },
                "verified": True,
            }
        # passthrough (tx submission, queries)
        return await self.primary.call(method, **params)

    # --- http plumbing -------------------------------------------------

    async def _handle(self, request: web.Request) -> web.Response:
        method = request.match_info["method"]
        params = {
            k: v.strip('"') for k, v in request.query.items()
        }
        return await self._respond(method, params, -1)

    async def _handle_post(self, request: web.Request) -> web.Response:
        body = await request.json()
        return await self._respond(
            body.get("method", ""), body.get("params") or {}, body.get("id")
        )

    async def _respond(self, method, params, id_) -> web.Response:
        try:
            result = await self._call(method, params)
            return web.json_response(
                {"jsonrpc": "2.0", "id": id_, "result": result}
            )
        except Exception as e:
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": id_,
                    "error": {"code": -32603, "message": str(e)},
                }
            )
