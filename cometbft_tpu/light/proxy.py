"""Light client RPC proxy (reference light/proxy/proxy.go + light/rpc):
a local JSON-RPC server whose block/header/commit/validators responses
are LIGHT-VERIFIED before being served — a wallet can point at this
instead of trusting a full node.

Routes proxied with verification: block, header, commit, validators,
status (verified tip), abci_query (merkle proof-op chain against the
light-verified AppHash of height+1 — value AND absence responses,
reference light/rpc/client.go:126-187), tx (inclusion proof against
the verified header's data hash, :473) and block_results (tx-results
merkle root against the next trusted header's LastResultsHash,
:382-424). Unverifiable routes (tx submission) pass through to the
primary."""

from __future__ import annotations

import asyncio
import base64
import threading
import time
from typing import Any, Dict, Optional

from aiohttp import web

from ..crypto import merkle
from ..rpc import encoding as enc
from ..rpc.client import HTTPClient, RPCClientError
from ..rpc.core import _bytes_param
from ..utils import codec
from .client import Client
from .serving import LightServingPlane, ServingOverloadError

# JSON-RPC error code for an admission shed (server overloaded,
# request is retryable) — distinct from -32603 internal error so SDK
# retry policies can tell them apart
RPC_OVERLOADED = -32005


class LightProxy:
    def __init__(
        self,
        client: Client,
        primary_url: str,
        *,
        plane: Optional[LightServingPlane] = None,
        max_sessions: int = 1024,
        max_inflight: int = 32,
        tracer=None,
    ):
        self.lc = client
        # the serving plane (light/serving.py): shared verified-header
        # cache + coalesced verification + bounded instrumented
        # admission. A caller-provided plane lets several fronts (the
        # proxy + a statesyncing node) share one cache.
        if plane is None:
            kw = {"tracer": tracer} if tracer is not None else {}
            plane = LightServingPlane(
                [client],
                max_sessions=max_sessions,
                max_inflight=max_inflight,
                **kw,
            )
        else:
            plane.adopt_client(client)
        self.plane = plane
        self.primary = HTTPClient(primary_url)
        self.app = web.Application()
        self.app.router.add_get("/{method}", self._handle)
        self.app.router.add_post("/", self._handle_post)
        self._runner: Optional[web.AppRunner] = None
        self.listen_addr = ""

    async def start(self, addr: str) -> None:
        host, _, port = addr.rpartition(":")
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host or "127.0.0.1", int(port))
        await site.start()
        h, p = site._server.sockets[0].getsockname()[:2]  # noqa: SLF001
        self.listen_addr = f"{h}:{p}"

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
        await self.primary.close()

    # --- verified route implementations -------------------------------

    async def _verified_light_block(self, height: Optional[int]):
        """Run the (blocking) serving plane off-loop: shared cache →
        single-flight → coalesced verification (light/serving.py)."""
        if height is None:
            st = await self.primary.status()
            height = int(st["sync_info"]["latest_block_height"])
        return await asyncio.to_thread(self.plane.serve, height)

    async def _call(self, method: str, params: Dict[str, Any]):
        h = params.get("height")
        h = int(h) if h not in (None, "") else None
        if method == "header":
            lb = await self._verified_light_block(h)
            return {
                "header": enc.header_json(lb.header),
                "header_b64": enc.b64(codec.encode_header(lb.header)),
                "verified": True,
            }
        if method == "commit":
            lb = await self._verified_light_block(h)
            return {
                "signed_header": {
                    "header": enc.header_json(lb.header),
                    "commit": enc.commit_json(lb.commit),
                },
                "header_b64": enc.b64(codec.encode_header(lb.header)),
                "commit_b64": enc.b64(codec.encode_commit(lb.commit)),
                "verified": True,
            }
        if method == "validators":
            lb = await self._verified_light_block(h)
            return {
                "block_height": str(lb.height),
                "validators": [
                    enc.validator_json(v)
                    for v in lb.validator_set.validators
                ],
                "validator_set_b64": enc.b64(
                    codec.encode_validator_set(lb.validator_set)
                ),
                "verified": True,
            }
        if method == "block":
            lb = await self._verified_light_block(h)
            # fetch the full block from the primary, verify its hash
            # against the light-verified header
            res = await self.primary.block(lb.height)
            blk = codec.decode_block(base64.b64decode(res["block_b64"]))
            if bytes(blk.hash()) != bytes(lb.header.hash()):
                raise RuntimeError(
                    "primary served a block that does not match the "
                    "verified header"
                )
            res["verified"] = True
            return res
        if method == "status":
            lb = await self._verified_light_block(None)
            return {
                "sync_info": {
                    "latest_block_height": str(lb.height),
                    "latest_block_hash": enc.hexb(lb.hash()),
                    "latest_block_time_ns": str(lb.header.time_ns),
                },
                "verified": True,
            }
        if method == "abci_query":
            return await self._verified_abci_query(params)
        if method == "tx":
            return await self._verified_tx(params)
        if method == "block_results":
            return await self._verified_block_results(h)
        if method == "consensus_params":
            return await self._verified_consensus_params(h)
        if method == "serving_status":
            # local introspection: sessions, admission gate, cache +
            # coalesce stats (docs/PERF.md "Light-client serving
            # plane") — never touches the primary
            return self.plane.stats()
        # passthrough (tx submission, unverifiable routes)
        return await self.primary.call(method, **params)

    async def _verified_consensus_params(self, height: Optional[int]):
        """Consensus params whose hash must equal the trusted
        header's consensus_hash at that height (reference
        light/rpc/client.go:229-256)."""
        from ..state.state_types import ConsensusParams

        params = {} if height is None else {"height": str(height)}
        res = await self.primary.call("consensus_params", **params)
        h = int(res.get("block_height") or 0)
        if h <= 0:
            raise RuntimeError(
                "primary returned no height for consensus params"
            )
        if height is not None and h != height:
            raise RuntimeError(
                "primary answered for a different height than "
                "requested"
            )
        cp = ConsensusParams.decode(
            base64.b64decode(res.get("params_b64") or "")
        )
        lb = await self._verified_light_block(h)
        if bytes(cp.hash()) != bytes(lb.header.consensus_hash):
            raise RuntimeError(
                "consensus params do not match the trusted header's "
                "consensus hash"
            )
        # serve the dict REBUILT from the verified bytes: the
        # primary's human-readable fields are what a wallet reads,
        # and they must not be independently forgeable next to an
        # honest params_b64
        return {
            "block_height": str(h),
            "params_b64": res.get("params_b64"),
            "consensus_params": cp.to_dict(),
            "verified": True,
        }

    async def _verified_block_results(self, height: Optional[int]):
        """Block results verified against the NEXT trusted header's
        LastResultsHash (reference light/rpc/client.go:382-424): the
        deterministic tx-result subset (code, data, gas, codespace) is
        re-encoded and its merkle root must equal what block
        height+1's header committed to. Without a height, serve the
        block PRECEDING the latest — the latest's results are not
        provable yet. NOTE (as the reference notes): only tx results
        are verifiable; events/finalize data are not part of the
        committed hash."""
        from ..abci import types as abci
        from ..state.execution import results_hash

        if height is None:
            st = await self.primary.status()
            height = int(st["sync_info"]["latest_block_height"]) - 1
        if height <= 0:
            raise RuntimeError(
                "block_results needs a positive provable height"
            )
        res = await self.primary.call(
            "block_results", height=str(height)
        )
        if int(res.get("height") or 0) != height:
            raise RuntimeError(
                "primary returned results for a different height"
            )
        txrs = [
            abci.ExecTxResult(
                code=int(tr.get("code") or 0),
                data=base64.b64decode(tr.get("data") or ""),
                gas_wanted=int(tr.get("gas_wanted") or 0),
                gas_used=int(tr.get("gas_used") or 0),
                codespace=tr.get("codespace") or "",
            )
            for tr in res.get("txs_results") or []
        ]
        lb = await self._verified_light_block(height + 1)
        if results_hash(txrs) != bytes(lb.header.last_results_hash):
            raise RuntimeError(
                "tx results do not match the trusted LastResultsHash"
            )
        res["verified"] = True
        return res

    async def _verified_abci_query(self, params: Dict[str, Any]):
        """ABCI query with merkle proof verification against the
        light-verified AppHash (reference light/rpc/client.go:126-187):
        the primary is forced to prove=true, the proof-op chain must
        land on the AppHash of the light block at height+1 (the header
        that commits the post-height state), and BOTH value and
        absence responses are proven — a primary that tampers with
        either gets rejected, not relayed."""
        params = dict(params)
        params["prove"] = True
        res = await self.primary.call("abci_query", **params)
        resp = res.get("response") or {}
        code = int(resp.get("code") or 0)
        key = base64.b64decode(resp.get("key") or "")
        value = base64.b64decode(resp.get("value") or "")
        # the proof must be for the key the CALLER asked about — a
        # primary substituting another committed key's (genuinely
        # provable) value or absence must be rejected, not relayed
        requested = _bytes_param(params.get("data"))
        if key != requested:
            raise RuntimeError(
                "primary answered for a different key than requested"
            )
        h = int(resp.get("height") or 0)
        if h <= 0:
            raise RuntimeError("primary returned no proof height")
        ops_b64 = resp.get("proof_ops") or ""
        if not ops_b64:
            raise RuntimeError(
                "primary returned no proof ops (app without prove "
                "support cannot be light-verified)"
            )
        ops = merkle.decode_proof_ops(base64.b64decode(ops_b64))
        # the proof lands on the AppHash of height+1, which only exists
        # once the NEXT block commits; at the tip that is up to one
        # block interval away — wait bounded for the chain to advance
        deadline = time.monotonic() + 15.0
        while True:
            st = await self.primary.status()
            if (
                int(st["sync_info"]["latest_block_height"]) >= h + 1
            ):
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"chain did not reach proof height {h + 1}"
                )
            await asyncio.sleep(0.1)
        lb = await self._verified_light_block(h + 1)
        rt = merkle.ProofRuntime()
        # route by CODE, not value truthiness: a key legitimately
        # committed with an EMPTY value still gets a value proof
        if code == 0:
            rt.verify_value(ops, lb.header.app_hash, key, value)
        else:
            rt.verify_absence(ops, lb.header.app_hash, key)
        res["verified"] = True
        return res

    async def _verified_tx(self, params: Dict[str, Any]):
        """Tx lookup with inclusion-proof verification against the
        light-verified header's data hash (reference
        light/rpc/client.go:473)."""
        from ..types.block import tx_hash

        params = dict(params)
        params["prove"] = True
        # the hash param must be present and parseable BEFORE the
        # primary is consulted: without it the identity check below
        # has nothing to bind to, and a primary could return any
        # committed tx with a valid inclusion proof and have it marked
        # verified
        requested = _bytes_param(params.get("hash"))
        if not requested:
            raise RuntimeError(
                "verified tx lookup requires a tx hash param"
            )
        res = await self.primary.call("tx", **params)
        height = int(res.get("height") or 0)
        if height <= 0:
            # height=0 would resolve _verified_light_block to the
            # primary-chosen latest height — reject malformed responses
            raise RuntimeError(
                "primary returned a tx without a positive height"
            )
        proof = res.get("proof") or {}
        if not proof.get("proof_b64"):
            raise RuntimeError("primary returned no tx inclusion proof")
        tx_bytes = base64.b64decode(res.get("tx") or "")
        # the returned tx must BE the one the caller asked about — an
        # inclusion proof for a different (genuinely committed) tx
        # would otherwise verify
        if tx_hash(tx_bytes) != requested:
            raise RuntimeError(
                "primary returned a different tx than requested"
            )
        p = merkle.decode_proof(
            base64.b64decode(proof["proof_b64"])
        )
        lb = await self._verified_light_block(height)
        if not p.verify(lb.header.data_hash, tx_hash(tx_bytes)):
            raise RuntimeError(
                "tx inclusion proof does not verify against the "
                "light-verified header"
            )
        res["verified"] = True
        return res

    # --- http plumbing -------------------------------------------------

    async def _handle(self, request: web.Request) -> web.Response:
        method = request.match_info["method"]
        params = {
            k: v.strip('"') for k, v in request.query.items()
        }
        return await self._respond(method, params, -1)

    async def _handle_post(self, request: web.Request) -> web.Response:
        body = await request.json()
        return await self._respond(
            body.get("method", ""), body.get("params") or {}, body.get("id")
        )

    async def _respond(self, method, params, id_) -> web.Response:
        # each in-flight HTTP request is one serving session: the
        # plane bounds them (max_sessions) and sheds-and-counts past
        # the bound rather than queueing unbounded work
        try:
            session = self.plane.open_session()
        except ServingOverloadError as e:
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": id_,
                    "error": {
                        "code": RPC_OVERLOADED,
                        "message": f"overloaded: {e}",
                    },
                }
            )
        try:
            result = await self._call(method, params)
            return web.json_response(
                {"jsonrpc": "2.0", "id": id_, "result": result}
            )
        except ServingOverloadError as e:
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": id_,
                    "error": {
                        "code": RPC_OVERLOADED,
                        "message": f"overloaded: {e}",
                    },
                }
            )
        except asyncio.CancelledError:
            raise  # server stop cancels in-flight handlers
        except RPCClientError as e:
            # forward the primary's structured error VERBATIM —
            # above all the retention plane's "height pruned
            # (base=N)" verdict (rpc/core.py _pruned_error), whose
            # machine-readable data field a light client uses to
            # redirect the query to an archive node
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": id_,
                    "error": {
                        "code": e.code,
                        "message": e.message,
                        "data": e.data,
                    },
                }
            )
        except Exception as e:
            return web.json_response(
                {
                    "jsonrpc": "2.0",
                    "id": id_,
                    "error": {"code": -32603, "message": str(e)},
                }
            )
        finally:
            session.close()
