"""Light-client serving plane: one full node, thousands of light
clients (ROADMAP item 3; PAPERS.md "Practical Light Clients for
Committee-Based Blockchains").

Before this plane, `light/proxy.py` verified per-request, per-client:
every bisection hop paid its own commit signature verification even
when a thousand sessions asked about the same heights. Three shared
seams fix that:

- **VerifiedHeaderCache** — a TTL'd LRU of per-height VERIFIED
  artifacts shared by every session: light blocks that passed full
  verification (and witness cross-check), plus whole-commit
  verification verdicts keyed by (chain, height, commit key, valset
  hash). Single-flight dedup: N concurrent requests for an unverified
  height trigger exactly ONE verification; the rest wait on the
  flight. Poisoned entries are impossible by construction: the only
  write paths are `get_or_verify` (stores what the verify fn
  returned) and `publish` (called by light.Client strictly AFTER
  verification + witness cross-check, and re-validated here), and
  commit verdicts are recorded only by the coalescing engine after a
  successful batch.

- **CoalescedCommitVerifier** — the cross-client batcher: concurrent
  sessions' skipping-verification hops (verify_non_adjacent's
  trusting + light checks) funnel into ONE lane batch through
  types/validation.verify_commit_jobs_coalesced — i.e. the existing
  crypto/batch + crypto/parallel_verify engine — with
  serial-equivalent verdicts (same error types, same early-break
  collection; asserted in tests and in-bench). Window-batched with
  leader election: the first submitting thread collects followers for
  ``window_s`` then dispatches for everyone.

- **LightServingPlane** — the session layer: bounded concurrent
  sessions + an obs/queues.py InstrumentedGate on in-flight verify
  work, shed-and-count overload behavior (never queue unbounded work
  behind a slow verify), a small pool of verifier Clients all wired
  to the shared cache/engine, and per-request spans
  (``light.serve.request``, ``light.verify.coalesced``,
  ``light.cache.{hit,miss}``) feeding the span→metrics bridge and
  the span budgets (tools/span_budgets.toml).

Sharing contract: a cache/plane may only be shared among clients that
share the same chain AND an equivalent trust policy (same witnesses /
trust root lineage) — the proxy's sessions and a statesyncing node in
the same process qualify (statesync/stateprovider.py wires in).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from typing import Callable, List, Optional

from .. import types as T
from ..obs.queues import InstrumentedGate
from ..trace.tracer import NOOP
from ..utils.log import get_logger
from .types import LightBlock

_log = get_logger("light.serving")

_monotonic = time.monotonic
_monotonic_ns = time.monotonic_ns

DEFAULT_CACHE_ENTRIES = 4096
DEFAULT_CACHE_TTL_S = 600.0
DEFAULT_WINDOW_S = 0.002
DEFAULT_MAX_BATCH = 128
# how long a single-flight follower (or a coalesce submitter) waits
# for its leader before giving up — bounds a wedged leader's blast
# radius to one errored request instead of a thread pile-up
FLIGHT_TIMEOUT_S = 120.0


class ServingOverloadError(Exception):
    """Admission shed: the plane is at its session or in-flight bound.
    Callers surface this as a retryable overload (the proxy maps it to
    a JSON-RPC overload error), never as a verification failure."""


class CachePoisonError(Exception):
    """A publish attempt carried an internally inconsistent block —
    refused (and loudly: this means a caller tried to publish
    something that cannot have passed verification)."""


def commit_key(commit) -> bytes:
    """Stable content key of a commit (memoized on the object — codec
    decode conventions make commits immutable). Two fetches of the
    same commit from different sessions must land on one verdict
    cache entry, so identity is content, not id()."""
    k = getattr(commit, "_serving_key", None)
    if k is None:
        h = hashlib.sha256()
        h.update(commit.height.to_bytes(8, "big", signed=False))
        h.update(commit.round.to_bytes(4, "big", signed=True))
        h.update(bytes(commit.block_id.hash))
        for cs in commit.signatures:
            h.update(bytes([cs.block_id_flag]))
            h.update(bytes(cs.validator_address or b""))
            h.update(
                (cs.timestamp_ns or 0).to_bytes(8, "big", signed=True)
            )
            h.update(bytes(cs.signature or b""))
        k = h.digest()
        try:
            commit._serving_key = k
        except Exception:
            pass  # slots/frozen commit: key just recomputes
    return k


def _valset_key(vals) -> bytes:
    k = getattr(vals, "_serving_key", None)
    if k is None:
        k = bytes(vals.hash())
        try:
            vals._serving_key = k
        except Exception:
            pass
    return k


class _Flight:
    """One in-flight verification: the leader resolves it, followers
    wait on the event."""

    __slots__ = ("event", "block", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.block: Optional[LightBlock] = None
        self.error: Optional[BaseException] = None


class VerifiedHeaderCache:
    """Cross-client TTL'd LRU of verified light blocks + commit
    verdicts for ONE chain. Thread-safe; every lookup counts a hit or
    miss (and, when a tracer is attached, records a zero-duration
    ``light.cache.hit``/``light.cache.miss`` span so the span→metrics
    bridge can export the counters)."""

    def __init__(
        self,
        chain_id: str,
        max_entries: int = DEFAULT_CACHE_ENTRIES,
        ttl_s: float = DEFAULT_CACHE_TTL_S,
        tracer=NOOP,
    ) -> None:
        self.chain_id = chain_id
        self.max_entries = max_entries
        self.ttl_s = ttl_s
        self.tracer = tracer
        self._lock = threading.Lock()
        # height -> (block, verified_at_monotonic); insertion order is
        # maintained fresh-last for LRU eviction
        self._blocks: dict = {}
        # (kind, height, commit_key, valset_key, extra) -> stamp
        self._verdicts: dict = {}
        self._flights: dict = {}
        self.hits = 0
        self.misses = 0
        self.verdict_hits = 0
        self.flight_waits = 0
        self.published = 0
        self.expired = 0

    # --- verified block cache ------------------------------------------

    def _get_locked(self, height: int) -> Optional[LightBlock]:
        ent = self._blocks.get(height)
        if ent is None:
            return None
        lb, stamp = ent
        if self.ttl_s and _monotonic() - stamp > self.ttl_s:
            del self._blocks[height]
            self.expired += 1
            return None
        # LRU touch
        del self._blocks[height]
        self._blocks[height] = (lb, stamp)
        return lb

    def get(self, height: int) -> Optional[LightBlock]:
        """Counting lookup — use at REQUEST entry points only (the
        plane's get_or_verify, a direct client's fast path); internal
        bisection/anchor probes use ``peek`` so one cold plane
        request counts at most two misses (plane probe + client
        entry) and a warm one exactly one hit."""
        with self._lock:
            lb = self._get_locked(height)
            if lb is not None:
                self.hits += 1
            else:
                self.misses += 1
        self.tracer.complete(
            "light.cache.hit" if lb is not None else "light.cache.miss",
            _monotonic_ns(),
            0,
            "light",
            height=height,
        )
        return lb

    def peek(self, height: int) -> Optional[LightBlock]:
        """Lookup WITHOUT counting a hit/miss (internal consumers that
        already counted this request, e.g. the single-flight loop)."""
        with self._lock:
            return self._get_locked(height)

    def latest_before(self, height: int) -> Optional[LightBlock]:
        """Highest verified block strictly below ``height`` — the
        bisection anchor seam: a pooled client starting from a cold
        store picks up the cache's frontier instead of re-walking from
        its trust root."""
        with self._lock:
            best = None
            for h in self._blocks:
                if h < height and (best is None or h > best):
                    best = h
            return self._get_locked(best) if best is not None else None

    def publish(self, lb: LightBlock) -> None:
        """Insert a VERIFIED block. Only light.Client calls this, and
        only after full verification + witness cross-check of the
        enclosing verify_header. Defense in depth: the block must be
        internally consistent (header/commit/valset bind) — an entry
        that fails validate_basic can never enter, whatever the
        caller's bug."""
        try:
            lb.validate_basic(self.chain_id)
        except Exception as e:
            raise CachePoisonError(
                f"refusing to cache inconsistent light block at "
                f"height {lb.height}: {e}"
            )
        with self._lock:
            self._blocks.pop(lb.height, None)
            self._blocks[lb.height] = (lb, _monotonic())
            self.published += 1
            while len(self._blocks) > self.max_entries:
                oldest = next(iter(self._blocks))
                del self._blocks[oldest]

    # --- single flight -------------------------------------------------

    def get_or_verify(
        self, height: int, verify_fn: Callable[[int], LightBlock]
    ) -> LightBlock:
        """Serve ``height`` from the cache, or run ``verify_fn`` ONCE
        no matter how many threads ask concurrently. The leader's
        result is published (verify_fn returning = it verified);
        followers wait on the flight and share verdict AND error."""
        while True:
            got = self.get(height)
            if got is not None:
                return got
            with self._lock:
                # re-check under the lock: a leader may have landed
                # between the get() above and here
                got = self._get_locked(height)
                if got is not None:
                    self.hits += 1
                    return got
                fl = self._flights.get(height)
                if fl is None:
                    fl = _Flight()
                    self._flights[height] = fl
                    leader = True
                else:
                    leader = False
            if leader:
                try:
                    lb = verify_fn(height)
                    if self.peek(height) is None:
                        self.publish(lb)
                    fl.block = lb
                    return lb
                except BaseException as e:
                    fl.error = e
                    raise
                finally:
                    with self._lock:
                        self._flights.pop(height, None)
                    fl.event.set()
            else:
                self.flight_waits += 1
                if not fl.event.wait(FLIGHT_TIMEOUT_S):
                    raise ServingOverloadError(
                        f"verification of height {height} did not "
                        "complete in time (wedged flight)"
                    )
                if fl.error is not None:
                    raise fl.error
                if fl.block is not None:
                    return fl.block
                # leader resolved without a block (cancelled): retry

    # --- commit verdict cache ------------------------------------------

    def check_commit_verdict(self, key: tuple) -> bool:
        with self._lock:
            ent = self._verdicts.get(key)
            if ent is None:
                return False
            if self.ttl_s and _monotonic() - ent > self.ttl_s:
                del self._verdicts[key]
                return False
            self.verdict_hits += 1
            return True

    def record_commit_verdict(self, key: tuple) -> None:
        """Called ONLY by the coalescing engine after the batch
        verified this commit successfully — failures are never
        recorded (a negative verdict must re-verify: the failing lane
        set can differ per caller)."""
        with self._lock:
            self._verdicts.pop(key, None)
            self._verdicts[key] = _monotonic()
            while len(self._verdicts) > self.max_entries:
                del self._verdicts[next(iter(self._verdicts))]

    # --- introspection -------------------------------------------------

    def __len__(self) -> int:
        return len(self._blocks)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._blocks),
                "verdicts": len(self._verdicts),
                "hits": self.hits,
                "misses": self.misses,
                "verdict_hits": self.verdict_hits,
                "flight_waits": self.flight_waits,
                "published": self.published,
                "expired": self.expired,
            }


class _Pending:
    __slots__ = ("job", "key", "error", "event")

    def __init__(self, job, key) -> None:
        self.job = job
        self.key = key
        self.error: Optional[BaseException] = None
        self.event = threading.Event()


class CoalescedCommitVerifier:
    """Thread-facing window batcher over
    types/validation.verify_commit_jobs_coalesced.

    Submitting threads block for their own verdict; all jobs that
    arrive within ``window_s`` of the first (or until ``max_batch``)
    are verified as ONE lane batch through the existing crypto
    dispatch engine. The first submitter is the leader: it sleeps out
    the window on a condition variable (woken early when the batch
    fills), takes the batch, dispatches, and resolves everyone.

    The verdict cache (a VerifiedHeaderCache) short-circuits whole
    commits that any session already verified — the promotion of the
    per-client signature cache into one cross-client verdict per
    (chain, height, commit, valset)."""

    def __init__(
        self,
        chain_id: str,
        signature_cache: Optional[T.SignatureCache] = None,
        verdict_cache: Optional[VerifiedHeaderCache] = None,
        window_s: float = DEFAULT_WINDOW_S,
        max_batch: int = DEFAULT_MAX_BATCH,
        tracer=NOOP,
    ) -> None:
        self.chain_id = chain_id
        self.signature_cache = signature_cache
        self.verdict_cache = verdict_cache
        self.window_s = window_s
        self.max_batch = max_batch
        self.tracer = tracer
        self._cond = threading.Condition()
        self._pending: List[_Pending] = []
        # stats (exported via plane.stats + the span bridge)
        self.submitted = 0
        self.dispatches = 0
        self.jobs_batched = 0
        self.max_batch_seen = 0
        self.verdict_hits = 0

    # --- the verifier-facing API (light/verifier.py engine seam) -------

    def verify_commit_light(
        self, vals, block_id, height: int, commit
    ) -> None:
        key = (
            "light",
            height,
            commit_key(commit),
            _valset_key(vals),
            bytes(block_id.hash),
        )
        if self._verdict_hit(key):
            return
        err = self._submit(
            ("light", vals, block_id, height, commit), key
        )
        if err is not None:
            raise err

    def verify_commit_light_trusting(
        self, vals, commit, trust_level
    ) -> None:
        key = (
            "trusting",
            commit.height,
            commit_key(commit),
            _valset_key(vals),
            (trust_level.numerator, trust_level.denominator),
        )
        if self._verdict_hit(key):
            return
        err = self._submit(
            ("trusting", vals, commit, trust_level), key
        )
        if err is not None:
            raise err

    def _verdict_hit(self, key: tuple) -> bool:
        vc = self.verdict_cache
        if vc is not None and vc.check_commit_verdict(key):
            self.verdict_hits += 1
            return True
        return False

    # --- batching ------------------------------------------------------

    def _submit(self, job, key) -> Optional[BaseException]:
        ent = _Pending(job, key)
        with self._cond:
            self.submitted += 1
            self._pending.append(ent)
            leader = len(self._pending) == 1
            if len(self._pending) >= self.max_batch:
                self._cond.notify_all()
        if leader:
            with self._cond:
                self._cond.wait_for(
                    lambda: len(self._pending) >= self.max_batch,
                    timeout=self.window_s,
                )
                batch, self._pending = self._pending, []
            self._dispatch(batch)
            return ent.error
        if not ent.event.wait(FLIGHT_TIMEOUT_S):
            return ServingOverloadError(
                "coalesced verification did not complete in time"
            )
        return ent.error

    def _dispatch(self, batch: List[_Pending]) -> None:
        t0 = _monotonic_ns()
        try:
            errors = T.verify_commit_jobs_coalesced(
                self.chain_id,
                [e.job for e in batch],
                cache=self.signature_cache,
                priority=T.PRIORITY_LIGHT,
            )
        except BaseException as e:  # engine failure: everyone errors
            errors = [e] * len(batch)
        self.dispatches += 1
        self.jobs_batched += len(batch)
        if len(batch) > self.max_batch_seen:
            self.max_batch_seen = len(batch)
        vc = self.verdict_cache
        for ent, err in zip(batch, errors):
            ent.error = err
            if err is None and vc is not None:
                vc.record_commit_verdict(ent.key)
            ent.event.set()
        self.tracer.complete(
            "light.verify.coalesced",
            t0,
            _monotonic_ns() - t0,
            "light",
            n=len(batch),
        )

    def stats(self) -> dict:
        return {
            "submitted": self.submitted,
            "dispatches": self.dispatches,
            "jobs_batched": self.jobs_batched,
            "max_batch": self.max_batch_seen,
            "verdict_hits": self.verdict_hits,
            "avg_batch": round(
                self.jobs_batched / self.dispatches, 2
            )
            if self.dispatches
            else 0.0,
        }


class Session:
    """One light-client serving session (a connected wallet / SDK).
    Thin: admission happened at open; requests ride the plane."""

    __slots__ = ("plane", "session_id", "requests")

    def __init__(self, plane: "LightServingPlane", session_id: int):
        self.plane = plane
        self.session_id = session_id
        self.requests = 0

    def verified_block(self, height: int) -> LightBlock:
        self.requests += 1
        return self.plane.serve(height, session=self.session_id)

    def close(self) -> None:
        self.plane.close_session(self.session_id)

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LightServingPlane:
    """Bounded, instrumented serving front over a pool of verifier
    Clients sharing one VerifiedHeaderCache + CoalescedCommitVerifier
    + SignatureCache.

    ``clients``: one or more light.Client instances for the SAME
    chain/trust policy (the pool bounds verification concurrency —
    concurrent misses on different heights verify in parallel and
    their signature batches coalesce). Each client is wired to the
    shared seams here (header_cache / verify_engine / signature
    cache)."""

    def __init__(
        self,
        clients: List,
        *,
        max_sessions: int = 1024,
        max_inflight: int = 32,
        admit_timeout_s: float = 0.25,
        cache: Optional[VerifiedHeaderCache] = None,
        window_s: float = DEFAULT_WINDOW_S,
        cache_ttl_s: float = DEFAULT_CACHE_TTL_S,
        coalesce: bool = True,
        tracer=NOOP,
    ) -> None:
        if not clients:
            raise ValueError("serving plane needs >= 1 client")
        self.chain_id = clients[0].chain_id
        self.tracer = tracer
        self.max_sessions = max_sessions
        # identity check, NOT truthiness: the cache defines __len__,
        # so a shared-but-still-empty cache (a fleet booting cold)
        # would read as falsy and silently get replaced by a private
        # one — breaking cross-replica single-flight exactly when it
        # matters most
        self.cache = (
            cache
            if cache is not None
            else VerifiedHeaderCache(
                self.chain_id, ttl_s=cache_ttl_s, tracer=tracer
            )
        )
        # promote the FIRST client's signature cache to the shared one
        self.signature_cache = clients[0].cache
        self.engine = (
            CoalescedCommitVerifier(
                self.chain_id,
                signature_cache=self.signature_cache,
                verdict_cache=self.cache,
                window_s=window_s,
                tracer=tracer,
            )
            if coalesce
            else None
        )
        self._clients = list(clients)
        for c in self._clients:
            self.adopt_client(c)
        self._free: List = list(self._clients)
        self._client_cond = threading.Condition()
        self.gate = InstrumentedGate(max_inflight, name="light.serve")
        self.admit_timeout_s = admit_timeout_s
        self._sessions: dict = {}
        self._session_ids = itertools.count(1)
        self._session_lock = threading.Lock()
        self.sessions_opened = 0
        self.sessions_shed = 0
        self.requests = 0
        self.requests_shed = 0
        self._draining = False

    # --- client pool ---------------------------------------------------

    def adopt_client(self, client) -> None:
        """Wire a Client into the shared seams (idempotent)."""
        client.header_cache = self.cache
        client.verify_engine = self.engine
        client.cache = self.signature_cache
        # serving sessions verify under the LIGHT scheduler class:
        # above catch-up storms, below the live round
        client.priority = T.PRIORITY_LIGHT

    def _checkout(self):
        with self._client_cond:
            if not self._client_cond.wait_for(
                lambda: self._free, timeout=FLIGHT_TIMEOUT_S
            ):
                raise ServingOverloadError(
                    "no verifier client became free in time"
                )
            return self._free.pop()

    def _checkin(self, client) -> None:
        with self._client_cond:
            self._free.append(client)
            self._client_cond.notify()

    # --- sessions ------------------------------------------------------

    def open_session(self) -> Session:
        with self._session_lock:
            if self._draining:
                self.sessions_shed += 1
                self.gate.count_drop()
                raise ServingOverloadError(
                    "serving plane draining; retry another replica"
                )
            if len(self._sessions) >= self.max_sessions:
                self.sessions_shed += 1
                self.gate.count_drop()
                raise ServingOverloadError(
                    f"session bound reached "
                    f"({self.max_sessions}); retry later"
                )
            sid = next(self._session_ids)
            s = Session(self, sid)
            self._sessions[sid] = s
            self.sessions_opened += 1
            return s

    def close_session(self, session_id: int) -> None:
        with self._session_lock:
            self._sessions.pop(session_id, None)

    def active_sessions(self) -> int:
        return len(self._sessions)

    # --- serving -------------------------------------------------------

    def serve(
        self, height: int, session: Optional[int] = None
    ) -> LightBlock:
        """One verified-block request: admission gate -> shared cache
        -> single-flight verification on a pooled client."""
        self.requests += 1
        span = self.tracer.span(
            "light.serve.request", "light", height=height
        )
        with span:
            if self._draining:
                self.requests_shed += 1
                self.gate.count_drop()
                span.set(shed=True)
                raise ServingOverloadError(
                    "serving plane draining; retry another replica"
                )
            if not self.gate.enter(self.admit_timeout_s):
                self.requests_shed += 1
                span.set(shed=True)
                raise ServingOverloadError(
                    "serving plane at its in-flight bound; retry"
                )
            try:
                return self.cache.get_or_verify(height, self._verify)
            finally:
                self.gate.exit()

    def _verify(self, height: int) -> LightBlock:
        client = self._checkout()
        try:
            return client.verify_light_block_at_height(height)
        finally:
            self._checkin(client)

    # --- drain (graceful rotate-out) -----------------------------------

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Stop admitting (new sessions AND new requests shed with the
        standard overload error) and wait — BOUNDED — for every
        in-flight request to resolve. Returns True when the gate went
        idle inside the budget; False means the caller rotates the
        replica out anyway knowing requests are still in flight. Sync
        and thread-safe: the plane is the thread-facing seam, so the
        router calls this via ``asyncio.to_thread`` (ASY110: the wait
        is bounded, never a hang)."""
        self._draining = True
        return self.gate.wait_idle(timeout_s)

    def resume(self) -> None:
        """Re-open admission after a drain (replica rotates back in)."""
        self._draining = False

    # --- introspection -------------------------------------------------

    def register_queues(self, registry) -> None:
        """Expose the admission gate in an obs QueueRegistry."""
        registry.register("light.serve", self.gate.stats)

    def stats(self) -> dict:
        return {
            "draining": self._draining,
            "sessions": self.active_sessions(),
            "sessions_opened": self.sessions_opened,
            "sessions_shed": self.sessions_shed,
            "requests": self.requests,
            "requests_shed": self.requests_shed,
            "admission": self.gate.stats(),
            "cache": self.cache.stats(),
            "coalesce": self.engine.stats()
            if self.engine is not None
            else None,
            "verifier_pool": len(self._clients),
        }
