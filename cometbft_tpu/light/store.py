"""Trusted light block stores (reference light/store/db).

``LightStore`` is the in-memory form (embedded clients, tests);
``DBLightStore`` persists the trust roots to a KV backend so a light
daemon restarted from its home dir resumes from its last verified
header instead of re-trusting the CLI arguments (the reference light
command backs its store with a db under the light home,
cmd/cometbft/commands/light.go:187)."""

from __future__ import annotations

from typing import Dict, Optional

from ..utils import kv, proto
from .types import LightBlock


class LightStore:
    def __init__(self):
        self._by_height: Dict[int, LightBlock] = {}

    def save(self, lb: LightBlock) -> None:
        self._by_height[lb.height] = lb

    def get(self, height: int) -> Optional[LightBlock]:
        return self._by_height.get(height)

    def latest(self) -> Optional[LightBlock]:
        if not self._by_height:
            return None
        return self._by_height[max(self._by_height)]

    def latest_before(self, height: int) -> Optional[LightBlock]:
        hs = [h for h in self._by_height if h < height]
        return self._by_height[max(hs)] if hs else None

    def lowest(self) -> Optional[LightBlock]:
        if not self._by_height:
            return None
        return self._by_height[min(self._by_height)]

    def prune(self, keep: int) -> list:
        """Drop all but the ``keep`` highest roots; returns the
        removed heights (subclasses delete their durable copies of
        EXACTLY these, so the policies can never diverge)."""
        if len(self._by_height) <= keep:
            return []
        doomed = sorted(self._by_height)[:-keep]
        for h in doomed:
            del self._by_height[h]
        return doomed

    def __len__(self) -> int:
        return len(self._by_height)


def _encode_light_block(lb: LightBlock) -> bytes:
    from ..utils import codec

    return (
        proto.field_message(1, codec.encode_header(lb.header))
        + proto.field_message(2, codec.encode_commit(lb.commit))
        + proto.field_message(
            3, codec.encode_validator_set(lb.validator_set)
        )
    )


def _decode_light_block(b: bytes) -> LightBlock:
    from ..utils import codec

    m = proto.parse(b)
    return LightBlock(
        header=codec.decode_header(proto.get1(m, 1, b"")),
        commit=codec.decode_commit(proto.get1(m, 2, b"")),
        validator_set=codec.decode_validator_set(proto.get1(m, 3, b"")),
    )


class DBLightStore(LightStore):
    """LightStore persisted to a KV backend: the in-memory index stays
    authoritative for reads (light stores hold at most pruning_size
    headers), the KV holds the durable copy, loaded once at open.
    Keys: ``L:<hex chain_id>:<height BE64>`` — hex keeps the prefix
    unambiguous for chain ids containing ':'. Saves auto-prune to
    ``pruning_size`` like the reference's db store (light/store/db
    SaveLightBlock, default 1000)."""

    def __init__(self, db: kv.KV, chain_id: str, pruning_size: int = 1000):
        super().__init__()
        self.db = db
        self.pruning_size = pruning_size
        self._prefix = (
            b"L:" + chain_id.encode().hex().encode() + b":"
        )
        for k, v in self.db.iter_prefix(self._prefix):
            lb = _decode_light_block(v)
            if lb.header.chain_id != chain_id:
                continue  # defense in depth vs foreign records
            self._by_height[lb.height] = lb

    def _key(self, height: int) -> bytes:
        return self._prefix + height.to_bytes(8, "big")

    def save(self, lb: LightBlock) -> None:
        super().save(lb)
        self.db.set(self._key(lb.height), _encode_light_block(lb))
        if self.pruning_size and len(self._by_height) > self.pruning_size:
            self.prune(self.pruning_size)

    def prune(self, keep: int) -> list:
        doomed = super().prune(keep)
        for h in doomed:
            self.db.delete(self._key(h))
        return doomed
