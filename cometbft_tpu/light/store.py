"""Trusted light block store (reference light/store/db)."""

from __future__ import annotations

from typing import Dict, Optional

from .types import LightBlock


class LightStore:
    def __init__(self):
        self._by_height: Dict[int, LightBlock] = {}

    def save(self, lb: LightBlock) -> None:
        self._by_height[lb.height] = lb

    def get(self, height: int) -> Optional[LightBlock]:
        return self._by_height.get(height)

    def latest(self) -> Optional[LightBlock]:
        if not self._by_height:
            return None
        return self._by_height[max(self._by_height)]

    def latest_before(self, height: int) -> Optional[LightBlock]:
        hs = [h for h in self._by_height if h < height]
        return self._by_height[max(hs)] if hs else None

    def lowest(self) -> Optional[LightBlock]:
        if not self._by_height:
            return None
        return self._by_height[min(self._by_height)]

    def prune(self, keep: int) -> None:
        if len(self._by_height) <= keep:
            return
        for h in sorted(self._by_height)[:-keep]:
            del self._by_height[h]

    def __len__(self) -> int:
        return len(self._by_height)
