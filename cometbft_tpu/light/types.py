"""Light client types: SignedHeader + LightBlock (reference types/light.go)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..types.block import Commit, Header
from ..types.validator_set import ValidatorSet


@dataclass
class LightBlock:
    header: Header
    commit: Commit
    validator_set: ValidatorSet

    @property
    def height(self) -> int:
        return self.header.height

    def hash(self) -> bytes:
        return self.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        if self.header.chain_id != chain_id:
            raise ValueError("light block from wrong chain")
        if self.commit.height != self.header.height:
            raise ValueError("commit height != header height")
        if self.commit.block_id.hash != self.header.hash():
            raise ValueError("commit is not for this header")
        if self.validator_set.hash() != self.header.validators_hash:
            raise ValueError("validator set does not match header")
