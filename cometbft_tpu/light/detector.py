"""Divergence detection against witness providers (reference light/detector.go).

After verifying a header from the primary, compare it against every
witness at the same height. A mismatching witness either proves a
light-client attack (evidence is built and reported to all providers)
or is itself lying (dropped by the caller's policy).
"""

from __future__ import annotations

import time
from typing import List

from ..evidence.types import LightClientAttackEvidence
from .provider import ProviderError
from .types import LightBlock


class DivergenceError(Exception):
    def __init__(self, witness_idx: int, evidence):
        super().__init__(f"witness {witness_idx} diverged")
        self.witness_idx = witness_idx
        self.evidence = evidence


def check_against_witnesses(client, verified: LightBlock) -> None:
    bad: List[int] = []
    for i, w in enumerate(client.witnesses):
        try:
            wlb = w.light_block(verified.height)
        except ProviderError:
            continue
        if wlb.hash() == verified.hash():
            continue
        # divergence: build LCA evidence from the witness's block against
        # our last trusted common header
        common = client.store.latest_before(verified.height)
        ev = LightClientAttackEvidence(
            conflicting_block=wlb,
            common_height=common.height if common else verified.height - 1,
            total_voting_power=verified.validator_set.total_voting_power(),
            timestamp_ns=time.time_ns(),
        )
        for p in [client.primary] + list(client.witnesses):
            try:
                p.report_evidence(ev)
            except Exception:
                pass
        raise DivergenceError(i, ev)
