"""Divergence detection against witness providers (reference light/detector.go).

After verifying a header from the primary, compare it against every
witness at the same height. Outcomes per witness (reference
light/client.go:1098-1185 compareFirstLightBlockWithWitnesses):

- agreement: strikes cleared, witness stays;
- unreachable / no block: a consecutive-failure strike; the witness
  is pruned from rotation after Client.MAX_WITNESS_STRIKES;
- INVALID conflicting block (fails validate_basic or its own commit
  check): the witness is lying in a provable way — removed
  immediately, no evidence (reference errBadWitness);
- VALID conflicting block: a real light-client attack on one side —
  LCA evidence is built and reported to every provider, the diverging
  witness is dropped from rotation, and DivergenceError halts the
  caller (reference ErrConflictingHeaders stops the client; operator
  must decide whom to trust).
"""

from __future__ import annotations

import time
from typing import List

from .. import types as T
from ..evidence.types import LightClientAttackEvidence
from .types import LightBlock


class DivergenceError(Exception):
    def __init__(self, witness_idx: int, evidence):
        super().__init__(f"witness {witness_idx} diverged")
        self.witness_idx = witness_idx
        self.evidence = evidence


class ProposerPrioritiesDivergeError(Exception):
    """Headers agree but the derived proposer priorities do not
    (reference ErrProposerPrioritiesDiverge): priorities are NOT
    committed in the header, so a lying side cannot be attributed —
    the client halts and the operator picks whom to trust."""

    def __init__(self, witness_idx: int):
        super().__init__(
            f"witness {witness_idx} reports identical header but "
            "conflicting proposer priorities"
        )
        self.witness_idx = witness_idx


def _priorities_diverge(a, b) -> bool:
    """Same valset hash is guaranteed by the header match; compare the
    per-validator priorities (address-keyed — ordering is canonical)."""
    pa = {v.address: v.proposer_priority for v in a.validators}
    pb = {v.address: v.proposer_priority for v in b.validators}
    return pa != pb


def check_against_witnesses(client, verified: LightBlock) -> None:
    bad: List[int] = []
    diverged = None  # (idx, evidence)
    for i, w in enumerate(client.witnesses):
        try:
            wlb = w.light_block(verified.height)
        except Exception:
            # unreachable or blockless: benign once, pruned when
            # persistent (reference treats no-response as benign per
            # call; rotation hygiene is the client's strike policy)
            if client.note_witness_failure(w):
                bad.append(i)
            continue
        client.clear_witness_failures(w)
        if wlb.hash() == verified.hash():
            # addresses/powers ARE header-committed: a witness whose
            # valset does not hash to the agreed header's
            # validators_hash is provably lying — remove it (reference
            # errBadWitness), never halt on it. Only a VALID valset
            # with different priorities (the one field the header does
            # not commit) is unattributable and halts.
            if bytes(wlb.validator_set.hash()) != bytes(
                wlb.header.validators_hash
            ):
                bad.append(i)
            elif _priorities_diverge(
                wlb.validator_set, verified.validator_set
            ):
                # clean up staged removals before halting — struck-out
                # witnesses must not survive because a later witness
                # halted the pass
                try:
                    client.remove_witnesses(bad)
                except Exception:
                    pass
                raise ProposerPrioritiesDivergeError(i)
            continue
        # conflicting header: is the witness's block even SELF-valid?
        try:
            wlb.validate_basic(client.chain_id)
            T.verify_commit_light(
                client.chain_id,
                wlb.validator_set,
                wlb.commit.block_id,
                wlb.height,
                wlb.commit,
                cache=client.cache,
                priority=client.priority,
            )
        except Exception:
            # provably bad witness (invalid conflicting block):
            # removed, no evidence — nothing here implicates the
            # primary (reference errBadWitness)
            bad.append(i)
            continue
        # genuine divergence: the detector cannot know which side is
        # attacking, so it builds evidence in BOTH directions against
        # the last trusted common header (reference detector.go
        # evAgainstPrimary / evAgainstWitness): the primary receives
        # the witness's block as the suspect, every witness receives
        # the primary's. An honest full node keeps only the evidence
        # whose conflicting block actually conflicts with its chain
        # (evidence/pool._verify_lca rejects the other).
        common = client.store.latest_before(verified.height)
        common_vals = (
            common.validator_set if common else verified.validator_set
        )
        common_height = (
            common.height if common else verified.height - 1
        )

        def _evidence(conflicting):
            ev = LightClientAttackEvidence(
                conflicting_block=conflicting,
                common_height=common_height,
                total_voting_power=common_vals.total_voting_power(),
                timestamp_ns=time.time_ns(),
            )
            # the byzantine set is DERIVED, and receiving pools
            # re-derive it and reject a mismatch (reference
            # evidence/verify.go:124-136)
            ev.byzantine_validators = ev.byzantine_from(common_vals)
            return ev

        ev_against_primary = _evidence(verified)
        ev_against_witness = _evidence(wlb)
        try:
            client.primary.report_evidence(ev_against_witness)
        except Exception:
            pass
        for p in client.witnesses:
            try:
                p.report_evidence(ev_against_primary)
            except Exception:
                pass
        diverged = (i, ev_against_primary)
        bad.append(i)
        break
    if diverged is not None:
        idx, ev = diverged
        try:
            client.remove_witnesses(bad)
        except Exception:
            # set emptied by the removal: the divergence error is the
            # more actionable signal
            pass
        raise DivergenceError(idx, ev)
    client.remove_witnesses(bad)
