"""Light client: trust-minimized header sync with bisection.

Parity with reference light/client.go: sequential + skipping
verification with the 9/16 bisection split (:29-32), a trusted store of
verified light blocks, witness cross-checking (detector.py), pruning.

The TPU twist: every hop's commit verification lands on the signature
lanes, and the SignatureCache carries overlap between hops — a 50k-
height bisection reverifies only new (validator, height) pairs.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from fractions import Fraction
from typing import List, Optional

from .. import types as T
from . import verifier
from .provider import Provider, ProviderError
from .store import LightStore
from .types import LightBlock
from ..utils.log import get_logger

_log = get_logger("light")

SEQUENTIAL = "sequential"
SKIPPING = "skipping"

# bisection split: 9/16 of the gap (reference light/client.go:29-32)
BISECT_NUM = 9
BISECT_DEN = 16


@dataclass
class TrustOptions:
    period_ns: int
    height: int
    hash: bytes


class LightClientError(Exception):
    pass


class Client:
    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: Optional[List[Provider]] = None,
        store: Optional[LightStore] = None,
        verification_mode: str = SKIPPING,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = 10 * 10**9,
        signature_cache: Optional[T.SignatureCache] = None,
        header_cache=None,
        verify_engine=None,
        priority: Optional[int] = None,
    ):
        self.chain_id = chain_id
        self.trust = trust_options
        self.primary = primary
        self.witnesses = witnesses or []
        # witness lifecycle state: consecutive-failure strikes per
        # provider, and whether the operator configured witnesses at
        # all (an emptied set is then an error, not a silent decay)
        self._witness_strikes: dict = {}
        self._had_witnesses = bool(self.witnesses)
        # identity check, NOT truthiness: an EMPTY persistent store
        # (fresh light home) is falsy via __len__ and `store or ...`
        # would silently discard it
        self.store = LightStore() if store is None else store
        self.mode = verification_mode
        self.trust_level = trust_level
        self.drift = max_clock_drift_ns
        self.cache = signature_cache or T.SignatureCache()
        # cross-client serving seams (light/serving.py): a shared
        # VerifiedHeaderCache of already-verified per-height blocks
        # (consulted before fetching/verifying; published to only
        # AFTER verification + witness cross-check) and a coalescing
        # commit-verify engine concurrent clients batch through
        self.header_cache = header_cache
        self.verify_engine = verify_engine
        # verify-scheduler class for this client's commit checks
        # (crypto/scheduler.py): serving sessions run PRIORITY_LIGHT,
        # the statesync state provider PRIORITY_CATCHUP
        self.priority = priority
        # blocks verified by the CURRENT verify_header call, held back
        # from the shared cache until the witness cross-check passes —
        # a valid-but-forked chain (a light-client attack the detector
        # would halt on) must never be published
        self._publish_pending: list = []
        self.hops = 0  # bisection hop counter (observability)
        # serializes the verify/update entry points: the light proxy
        # runs them from multiple worker threads (background head
        # tracking + concurrent request handlers) against the one
        # unlocked LightStore
        self._lock = threading.RLock()
        self._init_trust()

    def _init_trust(self) -> None:
        lb = self.store.latest()
        if lb is not None:
            # resuming from a persisted store: the CLI trust root must
            # AGREE with what we already trust at that height — a
            # silent override either way would let a typo'd (or
            # forked) root go unnoticed (reference
            # light.go checkTrustedHeaderAgainstOptions). Recovery
            # from a deliberate re-root: clear the light store.
            stored = self.store.get(self.trust.height)
            if stored is not None:
                claimed = bytes(stored.hash())
            else:
                # trust height not retained (bisection pivots +
                # pruning keep a sparse store): fetch the primary's
                # header at that height and ANCHOR it to the persisted
                # trust chain before using it as the comparison basis
                # — an unanchored header would let a colluding primary
                # confirm a mis-rooted configuration (the check exists
                # to catch exactly that). An unreachable primary
                # tolerates with a prominent warning (the daemon
                # resumes from the store and re-dials).
                try:
                    fetched = self.primary.light_block(
                        self.trust.height
                    )
                except Exception:
                    _log.error(
                        "trust-root cross-check SKIPPED: primary "
                        "unreachable and persisted store does not "
                        "retain the trust height",
                        height=self.trust.height,
                    )
                    return
                try:
                    lowest = self.store.lowest()
                    if fetched.height < lowest.height:
                        self._verify_backwards(lowest, fetched)
                    else:
                        anchor = self.store.latest_before(
                            fetched.height
                        )
                        self._verify_skipping(
                            anchor or lowest, fetched, time.time_ns()
                        )
                except verifier.ErrOldHeaderExpired:
                    raise LightClientError(
                        f"cannot confirm the configured trust root: "
                        f"the persisted anchor near height "
                        f"{self.trust.height} is outside the trust "
                        "period (re-root with a fresh height/hash "
                        "after clearing the light store)"
                    )
                except (
                    ProviderError,
                    ConnectionError,
                    OSError,
                    TimeoutError,
                ):
                    _log.error(
                        "trust-root cross-check SKIPPED: could not "
                        "anchor the primary's header to the stored "
                        "chain (provider error)",
                        height=self.trust.height,
                    )
                    return
                except Exception:
                    # any VERIFICATION failure (hash-chain break,
                    # invalid commit/header, valset mismatch — raised
                    # as assorted types by validate_basic and the
                    # commit verifiers) means the primary's header
                    # does NOT anchor: refuse, never skip — skipping
                    # here would let a colluding primary confirm a
                    # mis-rooted config by serving an unverifiable
                    # header
                    raise LightClientError(
                        f"primary's header at trust height "
                        f"{self.trust.height} does not chain to the "
                        "persisted trusted store (primary diverged "
                        "or store corrupt)"
                    )
                claimed = bytes(fetched.hash())
            if claimed != bytes(self.trust.hash):
                raise LightClientError(
                    f"trusted store conflicts with the configured "
                    f"trust root at height {self.trust.height} "
                    "(re-rooting requires clearing the light store)"
                )
            return
        lb = self.primary.light_block(self.trust.height)
        if lb.hash() != self.trust.hash:
            raise LightClientError(
                "trusted hash does not match primary's header"
            )
        lb.validate_basic(self.chain_id)
        # verify the commit is by the block's own valset (2/3)
        T.verify_commit_light(
            self.chain_id,
            lb.validator_set,
            lb.commit.block_id,
            lb.height,
            lb.commit,
            cache=self.cache,
            priority=self.priority,
        )
        self.store.save(lb)

    # --- public API ----------------------------------------------------

    def trusted_light_block(self, height: int = 0) -> Optional[LightBlock]:
        return self.store.latest() if height == 0 else self.store.get(height)

    def verify_light_block_at_height(
        self, height: int, now_ns: Optional[int] = None
    ) -> LightBlock:
        # shared-cache fast path OUTSIDE the client lock: a thousand
        # sessions hitting a cached height must not serialize behind
        # one client's in-flight bisection (light/serving.py)
        if self.header_cache is not None and height:
            cached = self.header_cache.get(height)
            if cached is not None:
                with self._lock:
                    self.store.save(cached)
                return cached
        with self._lock:
            now_ns = now_ns or time.time_ns()
            got = self.store.get(height)
            if got is not None:
                return got
            target = self._primary_block(height)
            return self.verify_header(target, now_ns)

    def update(self, now_ns: Optional[int] = None) -> Optional[LightBlock]:
        """Verify the primary's latest header (reference Client.Update)."""
        with self._lock:
            latest = self._primary_block(0)
            trusted = self.store.latest()
            if trusted is not None and latest.height <= trusted.height:
                return trusted
            return self.verify_header(latest, now_ns or time.time_ns())

    # --- primary lifecycle ---------------------------------------------

    def _primary_block(self, height: int) -> LightBlock:
        """Fetch from the primary, REPLACING it with a responsive
        witness when it fails (reference light/client.go:1000-1016 +
        findNewPrimary :1045): the first witness that serves the
        height is promoted (and leaves the witness rotation); the old
        primary is appended to the BACK of the witness list, where the
        ordinary witness lifecycle (strikes / invalid-conflict
        removal / divergence evidence) judges it from then on — the
        reference's remove-vs-demote split keys on its typed provider
        errors, which our transports collapse into ProviderError, so
        demote-and-let-the-detector-decide is the honest equivalent.

        A primary NOT-FOUND still probes the witnesses (a pruned or
        lagging primary is replaced by a witness that retains the
        height — reference treats ErrLightBlockNotFound as a
        findNewPrimary trigger) but WITHOUT striking them: a query for
        a not-yet-produced height (the proxy serves user-chosen
        heights) must surface to the caller, never burn the witness
        set."""
        from .provider import LightBlockNotFound

        # a height another session already VERIFIED needs no fetch at
        # all — the shared cache is better than any provider (its
        # entries are post-verification, post-cross-check). peek, not
        # get: internal probes of ONE request must not inflate the
        # request-level hit/miss counters the bridge exports
        if self.header_cache is not None and height:
            cached = self.header_cache.peek(height)
            if cached is not None:
                return cached
        try:
            return self.primary.light_block(height)
        except LightBlockNotFound as e:
            primary_err, primary_not_found = e, True
        except Exception as e:
            primary_err, primary_not_found = e, False
        bad = []
        for i, w in enumerate(self.witnesses):
            try:
                lb = w.light_block(height)
            except LightBlockNotFound:
                # this witness lacks the height too: no strike (it may
                # be the caller's future-height poll), but keep
                # probing — a LATER witness may retain it
                continue
            except Exception:
                if not primary_not_found and self.note_witness_failure(
                    w
                ):
                    bad.append(i)
                continue
            old = self.primary
            self.primary = w
            _log.error(
                "replacing primary with a witness",
                height=height,
                reason=(
                    "primary pruned/lags the height"
                    if primary_not_found
                    else "primary unresponsive"
                ),
                primary_error=repr(primary_err),
                remaining_witnesses=len(self.witnesses) - 1,
            )
            # promoted witness leaves the rotation; the demoted
            # primary joins its tail. Removal CANNOT empty the set
            # here (the demotion refills it), so do it directly
            # rather than through remove_witnesses' emptiness check.
            self.witnesses.pop(i)
            self.clear_witness_failures(w)
            self.witnesses.append(old)
            self.remove_witnesses(bad)
            return lb
        self.remove_witnesses(bad)
        if primary_not_found:
            # not an outage: the primary says the height doesn't
            # exist and no witness could serve it either — surface
            # the not-found (a witness's not-found must NOT mask a
            # real primary outage, so only the primary's own
            # classification picks this branch)
            raise primary_err
        raise LightClientError(
            f"primary unreachable and no witness could serve "
            f"height {height} as a replacement"
        ) from primary_err

    def verify_header(self, target: LightBlock, now_ns: int) -> LightBlock:
        existing = self.store.get(target.height)
        if existing is not None:
            if existing.hash() == target.hash():
                return existing
            raise LightClientError(
                "conflicting header for already-trusted height"
            )
        hc = self.header_cache
        if hc is not None:
            # peek: the enclosing request already counted its lookup
            cached = hc.peek(target.height)
            if cached is not None:
                if cached.hash() == target.hash():
                    self.store.save(cached)
                    return cached
                # forked-header detection MUST fire on a cache hit:
                # the primary served a header conflicting with a
                # block another session fully verified (and witness
                # cross-checked) at this height
                raise LightClientError(
                    f"primary's header at height {target.height} "
                    "conflicts with the cross-client verified cache "
                    "(forked or lying primary)"
                )
        self._publish_pending = []
        try:
            out = self._verify_header_inner(target, now_ns)
            if hc is not None:
                # EVERY block this call stages — bisection pivots
                # included — is witness-cross-checked before any of
                # them is published: trusting verification lets a
                # >1/3-colluding fork mint a crypto-valid PIVOT just
                # as easily as a target, and an unchecked pivot in
                # the shared cache would poison every session.
                # _verify_header_inner already cross-checked the
                # target itself; check the rest, THEN publish all.
                for lb in self._publish_pending:
                    if lb is not out:
                        self._cross_check(lb)
                for lb in self._publish_pending:
                    hc.publish(lb)
        finally:
            self._publish_pending = []
        return out

    def _verify_header_inner(
        self, target: LightBlock, now_ns: int
    ) -> LightBlock:
        trusted = self._best_trusted_before(target.height)
        if trusted is None:
            # target below every trusted header: hash-chain walk down
            # from the lowest trusted block (reference light/client.go
            # backwards verification)
            lowest = self.store.lowest()
            if lowest is None:
                raise LightClientError("no trusted state")
            self._verify_backwards(lowest, target)
            self._cross_check(target)
            return target
        if self.mode == SEQUENTIAL:
            self._verify_sequential(trusted, target, now_ns)
        else:
            self._verify_skipping(trusted, target, now_ns)
        self._cross_check(target)
        return target

    # --- verification strategies ---------------------------------------

    def _best_trusted_before(self, height: int) -> Optional[LightBlock]:
        """Bisection anchor: own trusted store, improved by the shared
        cache's frontier when it is closer to the target (a pooled
        serving client with a cold store picks up where ANY session
        left off instead of re-walking from its trust root)."""
        trusted = self.store.latest_before(height)
        if self.header_cache is not None:
            cached = self.header_cache.latest_before(height)
            if cached is not None and (
                trusted is None or cached.height > trusted.height
            ):
                self.store.save(cached)
                trusted = cached
        return trusted

    def _note_verified(self, lb: LightBlock) -> None:
        """Stage a freshly verified block for shared-cache publication
        (held until the enclosing verify_header's cross-check)."""
        self.store.save(lb)
        if self.header_cache is not None:
            self._publish_pending.append(lb)

    def _verify_sequential(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        for h in range(trusted.height + 1, target.height + 1):
            nxt = (
                target
                if h == target.height
                else self._primary_block(h)
            )
            verifier.verify_adjacent(
                self.chain_id,
                trusted,
                nxt,
                nxt.validator_set,
                self.trust.period_ns,
                now_ns,
                self.drift,
                cache=self.cache,
                engine=self.verify_engine,
                priority=self.priority,
            )
            self._note_verified(nxt)
            trusted = nxt
            self.hops += 1

    def _verify_skipping(
        self, trusted: LightBlock, target: LightBlock, now_ns: int
    ) -> None:
        """Bisection: try to jump straight to the target; on
        insufficient trusted overlap, pull an intermediate header at
        9/16 of the gap (reference verifySkipping)."""
        pivots = [target]
        while pivots:
            candidate = pivots[-1]
            if self.header_cache is not None:
                # peek: same request-internal probe as _primary_block
                cached = self.header_cache.peek(candidate.height)
                if cached is not None:
                    if cached.hash() != candidate.hash():
                        # the primary's hop conflicts with a block
                        # another session verified + cross-checked:
                        # fork detection on a cache hit
                        raise LightClientError(
                            f"primary's header at height "
                            f"{candidate.height} conflicts with the "
                            "cross-client verified cache"
                        )
                    self.store.save(cached)
                    trusted = cached
                    pivots.pop()
                    self.hops += 1
                    continue
            try:
                if candidate.height == trusted.height + 1:
                    verifier.verify_adjacent(
                        self.chain_id,
                        trusted,
                        candidate,
                        candidate.validator_set,
                        self.trust.period_ns,
                        now_ns,
                        self.drift,
                        cache=self.cache,
                        engine=self.verify_engine,
                        priority=self.priority,
                    )
                else:
                    trusted_next_vals = self._next_vals(trusted)
                    verifier.verify_non_adjacent(
                        self.chain_id,
                        trusted,
                        trusted_next_vals,
                        candidate,
                        candidate.validator_set,
                        self.trust.period_ns,
                        now_ns,
                        self.drift,
                        self.trust_level,
                        cache=self.cache,
                        engine=self.verify_engine,
                        priority=self.priority,
                    )
                self._note_verified(candidate)
                trusted = candidate
                pivots.pop()
                self.hops += 1
            except verifier.ErrNewValSetCantBeTrusted:
                gap = candidate.height - trusted.height
                pivot_h = trusted.height + gap * BISECT_NUM // BISECT_DEN
                if pivot_h in (trusted.height, candidate.height):
                    raise LightClientError(
                        "bisection cannot make progress"
                    )
                pivots.append(self._primary_block(pivot_h))

    def _verify_backwards(
        self, trusted: LightBlock, target: LightBlock
    ) -> None:
        """Verify a header BELOW the trust root by walking the header
        hash chain down one height at a time: header(h).last_block_id
        must equal hash(header(h-1)) (reference light/client.go
        backwards: no signature checks needed — the chain of hashes is
        anchored at the already-trusted block).

        Each hop additionally enforces what the reference's
        VerifyBackwards (light/verifier.go) does beyond the hash link:
        chain-id match, exact height adjacency, and time monotonicity
        (untrusted.Time strictly before trusted.Time) — a primary must
        not be able to serve hash-chained headers with out-of-order
        times or a foreign chain id.
        """
        cur = trusted
        while cur.height > target.height:
            want = cur.header.last_block_id
            if want is None or not want.hash:
                raise LightClientError(
                    f"header {cur.height} has no last_block_id"
                )
            lower_h = cur.height - 1
            lower = (
                target
                if lower_h == target.height
                else self._primary_block(lower_h)
            )
            if lower.height != lower_h:
                # also exact adjacency: lower_h == cur.height - 1 and
                # LightBlock.height IS header.height
                raise LightClientError("provider returned wrong height")
            if lower.header.chain_id != self.chain_id:
                raise LightClientError(
                    f"header at {lower_h} from wrong chain "
                    f"{lower.header.chain_id!r}"
                )
            if lower.header.time_ns >= cur.header.time_ns:
                raise LightClientError(
                    f"non-monotonic header time at {lower_h}: "
                    f"{lower.header.time_ns} >= {cur.header.time_ns}"
                )
            if lower.hash() != want.hash:
                raise LightClientError(
                    f"header hash chain broken at {lower_h}"
                )
            lower.validate_basic(self.chain_id)
            self.hops += 1
            cur = lower
        self._note_verified(target)

    def _next_vals(self, lb: LightBlock) -> T.ValidatorSet:
        """The valset signing height h+1 (trusted next-vals). For
        non-adjacent trusting verification the trusted block's own
        valset is the standard choice (reference uses trusted
        NextValidators; same set when unchanged, and trusting mode
        tolerates drift up to the trust level)."""
        return lb.validator_set

    # --- witnesses ------------------------------------------------------
    #
    # Lifecycle (reference light/client.go:1019-1185): witnesses that
    # are persistently unresponsive or serve INVALID conflicting
    # blocks are removed from rotation; a configured-with-witnesses
    # client whose witness set empties errors out rather than
    # silently continuing unwitnessed; fresh witnesses can be
    # installed at runtime (add_witness).

    MAX_WITNESS_STRIKES = 3

    def note_witness_failure(self, w) -> bool:
        """Count a consecutive failure; True when the witness has
        struck out and should be removed."""
        n = self._witness_strikes.get(id(w), 0) + 1
        self._witness_strikes[id(w)] = n
        return n >= self.MAX_WITNESS_STRIKES

    def clear_witness_failures(self, w) -> None:
        self._witness_strikes.pop(id(w), None)

    def remove_witnesses(self, indexes) -> None:
        """Drop witnesses by index (descending removal, reference
        removeWitnesses). Raises once the set empties on a client
        that was configured WITH witnesses — an unwitnessed client
        must be an explicit operator choice, never a silent decay."""
        if not indexes:
            return
        for i in sorted(set(indexes), reverse=True):
            w = self.witnesses.pop(i)
            self._witness_strikes.pop(id(w), None)
            _log.error(
                "removing witness from rotation",
                witness=getattr(w, "name", repr(w)),
                remaining=len(self.witnesses),
            )
        if self._had_witnesses and not self.witnesses:
            raise LightClientError(
                "no witnesses remain: every configured witness was "
                "removed (unresponsive or misbehaving); install a "
                "fresh one with add_witness or restart with a new "
                "witness set"
            )

    def add_witness(self, provider) -> None:
        """Install a fresh witness at runtime (reference operators do
        this after witness attrition)."""
        with self._lock:
            self.witnesses.append(provider)
            self._had_witnesses = True

    def _cross_check(self, verified: LightBlock) -> None:
        from .detector import check_against_witnesses

        if self.witnesses:
            check_against_witnesses(self, verified)
        elif self._had_witnesses:
            # the configured witness set has fully decayed (divergence
            # or strikes): continuing to verify UNWITNESSED against a
            # possibly-suspect primary would be exactly the silent
            # decay the lifecycle exists to prevent
            raise LightClientError(
                "no witnesses remain: refusing unwitnessed "
                "verification (install one with add_witness)"
            )

    def prune(self, keep: int = 1000) -> None:
        self.store.prune(keep)
