"""Light client verification (reference light/verifier.go).

- VerifyAdjacent (:92): next header's valset hash must match trusted
  next-valset; verify commit with the new valset (2/3).
- VerifyNonAdjacent (:30): trusted valset must have signed with
  > trust-level (default 1/3) power (VerifyCommitLightTrusting), then
  the new valset with 2/3 (VerifyCommitLight).

Both route through the TPU lane batch + SignatureCache (:57,:72 — the
cache dedups overlapping valsets across bisection hops).
"""

from __future__ import annotations

import time
from fractions import Fraction
from typing import Optional

from .. import types as T
from .types import LightBlock

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


class LightClientError(Exception):
    pass


class ErrOldHeaderExpired(LightClientError):
    pass


class ErrNewValSetCantBeTrusted(LightClientError):
    pass


class ErrInvalidHeader(LightClientError):
    pass


def _header_expired(h, trusting_period_ns: int, now_ns: int) -> bool:
    return h.time_ns + trusting_period_ns <= now_ns


def verify_adjacent(
    chain_id: str,
    trusted: LightBlock,
    untrusted: LightBlock,
    untrusted_vals: T.ValidatorSet,
    trusting_period_ns: int,
    now_ns: Optional[int] = None,
    max_clock_drift_ns: int = 10 * 10**9,
    cache: Optional[T.SignatureCache] = None,
    engine=None,
    priority: Optional[int] = None,
) -> None:
    now_ns = now_ns or time.time_ns()
    if untrusted.height != trusted.height + 1:
        raise ErrInvalidHeader("headers must be adjacent")
    if _header_expired(trusted.header, trusting_period_ns, now_ns):
        raise ErrOldHeaderExpired("trusted header expired")
    _verify_new_header(
        chain_id, trusted, untrusted, now_ns, max_clock_drift_ns
    )
    if untrusted.header.validators_hash != trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            "untrusted validators hash != trusted next validators hash"
        )
    if engine is not None:
        # cross-client coalesce seam (light/serving.py): concurrent
        # sessions' commit checks land in one lane batch, verdicts
        # serial-equivalent (same exception types as the direct call)
        engine.verify_commit_light(
            untrusted_vals,
            untrusted.commit.block_id,
            untrusted.height,
            untrusted.commit,
        )
        return
    T.verify_commit_light(
        chain_id,
        untrusted_vals,
        untrusted.commit.block_id,
        untrusted.height,
        untrusted.commit,
        cache=cache,
        priority=priority,
    )


def verify_non_adjacent(
    chain_id: str,
    trusted: LightBlock,
    trusted_next_vals: T.ValidatorSet,
    untrusted: LightBlock,
    untrusted_vals: T.ValidatorSet,
    trusting_period_ns: int,
    now_ns: Optional[int] = None,
    max_clock_drift_ns: int = 10 * 10**9,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    cache: Optional[T.SignatureCache] = None,
    engine=None,
    priority: Optional[int] = None,
) -> None:
    now_ns = now_ns or time.time_ns()
    if untrusted.height == trusted.height + 1:
        raise ErrInvalidHeader("use verify_adjacent for adjacent headers")
    if _header_expired(trusted.header, trusting_period_ns, now_ns):
        raise ErrOldHeaderExpired("trusted header expired")
    _verify_new_header(
        chain_id, trusted, untrusted, now_ns, max_clock_drift_ns
    )
    if engine is not None:
        try:
            engine.verify_commit_light_trusting(
                trusted_next_vals, untrusted.commit, trust_level
            )
        except T.ErrNotEnoughVotingPower as e:
            raise ErrNewValSetCantBeTrusted(str(e))
        engine.verify_commit_light(
            untrusted_vals,
            untrusted.commit.block_id,
            untrusted.height,
            untrusted.commit,
        )
        return
    try:
        T.verify_commit_light_trusting(
            chain_id,
            trusted_next_vals,
            untrusted.commit,
            trust_level=trust_level,
            cache=cache,
            priority=priority,
        )
    except T.ErrNotEnoughVotingPower as e:
        raise ErrNewValSetCantBeTrusted(str(e))
    T.verify_commit_light(
        chain_id,
        untrusted_vals,
        untrusted.commit.block_id,
        untrusted.height,
        untrusted.commit,
        cache=cache,
        priority=priority,
    )


def _verify_new_header(
    chain_id, trusted, untrusted, now_ns, max_clock_drift_ns
) -> None:
    untrusted.validate_basic(chain_id)
    if untrusted.height <= trusted.height:
        raise ErrInvalidHeader("untrusted height <= trusted height")
    if untrusted.header.time_ns <= trusted.header.time_ns:
        raise ErrInvalidHeader("untrusted time <= trusted time")
    if untrusted.header.time_ns >= now_ns + max_clock_drift_ns:
        raise ErrInvalidHeader("untrusted header from the future")
