"""Light block providers (reference light/provider/).

A provider serves (header, commit, valset) triples by height. The
in-process provider wraps a node's stores (the reference's http
provider hits a full node's RPC — the RPC-backed provider lives in
rpc/client once the server is up)."""

from __future__ import annotations

from typing import Optional

from .. import types as T
from .types import LightBlock


class ProviderError(Exception):
    pass


class LightBlockNotFound(ProviderError):
    pass


class Provider:
    chain_id: str = ""

    def light_block(self, height: int) -> LightBlock:
        """height = 0 means latest."""
        raise NotImplementedError

    def report_evidence(self, ev) -> None:
        raise NotImplementedError


class StoreBackedProvider(Provider):
    """Serves light blocks from a full node's block + state stores."""

    def __init__(self, chain_id, block_store, state_store):
        self.chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.reported = []

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            raise LightBlockNotFound(f"no block meta at {height}")
        commit = self.block_store.load_seen_commit(height)
        if commit is None:
            commit = self.block_store.load_block_commit(height)
        if commit is None:
            raise LightBlockNotFound(f"no commit at {height}")
        vals = self.state_store.load_validators(height)
        if vals is None:
            raise LightBlockNotFound(f"no validators at {height}")
        return LightBlock(
            header=meta.header, commit=commit, validator_set=vals
        )

    def report_evidence(self, ev) -> None:
        self.reported.append(ev)
