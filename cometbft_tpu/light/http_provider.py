"""HTTP light-block provider (reference light/provider/http).

Fetches (header, commit, valset) triples from a full node's RPC using
the lossless `*_b64` payloads, so every hash recomputes exactly.

The light.Client Provider interface is synchronous; HTTP is async. The
provider owns a dedicated background event loop thread and blocks the
calling thread per request — safe from sync code and from OTHER event
loops (never call it from the provider's own loop).

Connection policy: ONE aiohttp session per provider, reused across
every request (rpc/client.HTTPClient keeps its ClientSession alive —
a keep-alive connection per full node, not a TCP handshake per call),
and transient transport failures retry a bounded number of times with
full-jitter exponential backoff (utils/backoff.py) before surfacing.
``LightBlockNotFound`` never retries — a missing height is an answer,
not an outage."""

from __future__ import annotations

import asyncio
import base64
import random
import threading
import time
from typing import Optional

from ..rpc.client import HTTPClient, RPCClientError
from ..utils.backoff import Backoff
from .provider import LightBlockNotFound, Provider, ProviderError
from .types import LightBlock

# transient-failure retry envelope: fast first retry, capped well
# under the per-request timeout so a flaky hop gets several tries
# without turning one light_block call into a multi-minute stall
RETRY_ATTEMPTS = 3
RETRY_BASE_S = 0.05
RETRY_CAP_S = 1.0


class HTTPProvider(Provider):
    def __init__(
        self,
        chain_id: str,
        base_url: str,
        timeout_s: float = 10.0,
        retries: int = RETRY_ATTEMPTS,
        rng: Optional[random.Random] = None,
    ):
        self.chain_id = chain_id
        self.base_url = base_url
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )
        self._thread.start()
        # one HTTPClient = one persistent aiohttp session for the
        # provider's lifetime (closed in close())
        self._client = HTTPClient(base_url, timeout_s=timeout_s)
        self._timeout_s = timeout_s + 5.0
        self._retries = max(1, retries)
        self._rng = rng or random.Random()
        self.retries_used = 0  # observability (tests/metrics)

    def _run(self, coro):
        import concurrent.futures

        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(self._timeout_s)
        except concurrent.futures.TimeoutError:
            # the coroutine is STILL RUNNING on the background loop:
            # cancel it and surface a non-retryable ProviderError —
            # retrying a result-timeout would stack duplicate
            # in-flight RPCs on an already-slow node and multiply the
            # caller's effective deadline by the retry budget
            self._loop.call_soon_threadsafe(fut.cancel)
            raise ProviderError(
                f"rpc timed out after {self._timeout_s:.0f}s"
            )

    def light_block(self, height: int) -> LightBlock:
        backoff = Backoff(
            base_s=RETRY_BASE_S, cap_s=RETRY_CAP_S, rng=self._rng
        )
        last: Optional[Exception] = None
        for attempt in range(self._retries):
            try:
                return self._run(self._light_block(height or None))
            except RPCClientError as e:
                # the node ANSWERED: no-such-height is a verdict, not
                # a transport fault — never retried
                raise LightBlockNotFound(str(e))
            except ProviderError:
                raise
            except Exception as e:
                last = e
                if attempt + 1 < self._retries:
                    self.retries_used += 1
                    time.sleep(backoff.next_delay())
        raise ProviderError(
            f"rpc failure after {self._retries} attempts: {last!r}"
        )

    async def _light_block(self, height: Optional[int]) -> LightBlock:
        hdr, commit = await self._client.commit_decoded(height)
        vals = await self._client.validators_decoded(hdr.height)
        return LightBlock(header=hdr, commit=commit, validator_set=vals)

    def report_evidence(self, ev) -> None:
        try:
            self._run(
                self._client.call(
                    "broadcast_evidence",
                    evidence=base64.b64encode(ev.encode()).decode(),
                )
            )
        except Exception:
            pass

    def close(self) -> None:
        try:
            self._run(self._client.close())
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
