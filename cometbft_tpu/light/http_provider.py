"""HTTP light-block provider (reference light/provider/http).

Fetches (header, commit, valset) triples from a full node's RPC using
the lossless `*_b64` payloads, so every hash recomputes exactly.

The light.Client Provider interface is synchronous; HTTP is async. The
provider owns a dedicated background event loop thread and blocks the
calling thread per request — safe from sync code and from OTHER event
loops (never call it from the provider's own loop)."""

from __future__ import annotations

import asyncio
import base64
import threading
from typing import Optional

from ..rpc.client import HTTPClient, RPCClientError
from .provider import LightBlockNotFound, Provider, ProviderError
from .types import LightBlock


class HTTPProvider(Provider):
    def __init__(self, chain_id: str, base_url: str, timeout_s: float = 10.0):
        self.chain_id = chain_id
        self.base_url = base_url
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, daemon=True
        )
        self._thread.start()
        self._client = HTTPClient(base_url, timeout_s=timeout_s)
        self._timeout_s = timeout_s + 5.0

    def _run(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(self._timeout_s)

    def light_block(self, height: int) -> LightBlock:
        try:
            return self._run(self._light_block(height or None))
        except RPCClientError as e:
            raise LightBlockNotFound(str(e))
        except ProviderError:
            raise
        except Exception as e:
            raise ProviderError(f"rpc failure: {e!r}")

    async def _light_block(self, height: Optional[int]) -> LightBlock:
        hdr, commit = await self._client.commit_decoded(height)
        vals = await self._client.validators_decoded(hdr.height)
        return LightBlock(header=hdr, commit=commit, validator_set=vals)

    def report_evidence(self, ev) -> None:
        try:
            self._run(
                self._client.call(
                    "broadcast_evidence",
                    evidence=base64.b64encode(ev.encode()).decode(),
                )
            )
        except Exception:
            pass

    def close(self) -> None:
        try:
            self._run(self._client.close())
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
