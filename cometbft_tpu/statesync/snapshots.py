"""On-disk snapshot store: node-side snapshot generation + serving
(reference statesync/chunks.go persistence direction + the e2e app's
snapshots/ dir, abci/example/kvstore persisted snapshots).

Until ISSUE 17 the only snapshots in the system were RAM blobs inside
the model app — gone on restart, so a restarted node could never seed
a joiner and ROADMAP item 5(b)'s "statesync only consumes" held. The
``SnapshotStore`` persists chunked app snapshots under
``<home>/snapshots/<height>/``:

    snapshots/
      000000000000200/        (height, zero-padded for sort order)
        meta.json             (height/format/chunks/hash/metadata)
        chunk.0000 chunk.0001 ...

Writes are crash-safe in the store's one direction: chunks land
first, ``meta.json`` is written to a temp file and atomically renamed
LAST — a snapshot without meta.json is garbage a restart sweeps, one
with it is complete and servable. Rotation keeps the newest
``keep_recent`` snapshots. The store is thread-safe (taken from the
retention plane's worker thread, served from reactor to_thread
calls).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
from typing import List, Optional

from ..abci import types as abci

# one chunk file per this many bytes (matches the model app's wire
# chunking so served chunks are byte-identical to the RAM-era ones)
CHUNK_SIZE = 1024


def _hdir(root: str, height: int) -> str:
    return os.path.join(root, f"{height:015d}")


class SnapshotStore:
    """Chunked app snapshots on disk with keep-recent rotation."""

    def __init__(self, root: str, keep_recent: int = 2):
        self.root = root
        self.keep_recent = max(1, int(keep_recent))
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)
        self._sweep_incomplete()

    # --- write side ---------------------------------------------------

    def save(
        self,
        height: int,
        blob: bytes,
        format_: int = 1,
        metadata: bytes = b"",
        chunk_size: int = CHUNK_SIZE,
    ) -> abci.Snapshot:
        """Persist one snapshot: chunks first, meta.json atomically
        last (the completeness marker). Idempotent per height."""
        with self._lock:
            d = _hdir(self.root, height)
            os.makedirs(d, exist_ok=True)
            nchunks = max(1, (len(blob) + chunk_size - 1) // chunk_size)
            for i in range(nchunks):
                part = blob[i * chunk_size : (i + 1) * chunk_size]
                tmp = os.path.join(d, f".chunk.{i:04d}.tmp")
                with open(tmp, "wb") as f:
                    f.write(part)
                os.replace(tmp, os.path.join(d, f"chunk.{i:04d}"))
            meta = {
                "height": height,
                "format": format_,
                "chunks": nchunks,
                "chunk_size": chunk_size,
                "hash": hashlib.sha256(blob).hexdigest(),
                "metadata": metadata.hex(),
            }
            tmp = os.path.join(d, ".meta.json.tmp")
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(d, "meta.json"))
            self._rotate_locked()
            return self._snap_from_meta(meta)

    def _rotate_locked(self) -> None:
        hs = self._heights_locked()
        for h in hs[: -self.keep_recent]:
            shutil.rmtree(_hdir(self.root, h), ignore_errors=True)

    def _sweep_incomplete(self) -> None:
        """Drop half-written snapshot dirs (no meta.json): a crash
        mid-save must never leave an unservable height advertised."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            d = os.path.join(self.root, name)
            if os.path.isdir(d) and not os.path.exists(
                os.path.join(d, "meta.json")
            ):
                shutil.rmtree(d, ignore_errors=True)

    # --- read side ----------------------------------------------------

    def _heights_locked(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.isdigit():
                continue
            if os.path.exists(
                os.path.join(self.root, name, "meta.json")
            ):
                out.append(int(name))
        return sorted(out)

    def heights(self) -> List[int]:
        with self._lock:
            return self._heights_locked()

    def latest_height(self) -> int:
        """Newest complete snapshot height, 0 when none — the
        retention plane's snapshot floor (never prune above it while
        snapshotting is on, or the only bootstrap anchor dies)."""
        hs = self.heights()
        return hs[-1] if hs else 0

    def _meta(self, height: int) -> Optional[dict]:
        try:
            with open(
                os.path.join(_hdir(self.root, height), "meta.json")
            ) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def _snap_from_meta(m: dict) -> abci.Snapshot:
        return abci.Snapshot(
            height=m["height"],
            format=m["format"],
            chunks=m["chunks"],
            hash=bytes.fromhex(m["hash"]),
            metadata=bytes.fromhex(m.get("metadata", "")),
        )

    def list_snapshots(self) -> List[abci.Snapshot]:
        out = []
        for h in self.heights():
            m = self._meta(h)
            if m is not None:
                out.append(self._snap_from_meta(m))
        return out

    def load_chunk(self, height: int, format_: int, index: int) -> bytes:
        m = self._meta(height)
        if m is None or m["format"] != format_ or index >= m["chunks"]:
            return b""
        try:
            with open(
                os.path.join(_hdir(self.root, height), f"chunk.{index:04d}"),
                "rb",
            ) as f:
                return f.read()
        except OSError:
            return b""

    def load_blob(self, height: int) -> Optional[bytes]:
        """The whole snapshot body (restore-side convenience)."""
        m = self._meta(height)
        if m is None:
            return None
        parts = [
            self.load_chunk(height, m["format"], i)
            for i in range(m["chunks"])
        ]
        blob = b"".join(parts)
        if hashlib.sha256(blob).hexdigest() != m["hash"]:
            return None
        return blob

    def disk_bytes(self) -> int:
        total = 0
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    pass
        return total

    def stats(self) -> dict:
        hs = self.heights()
        return {
            "snapshots": len(hs),
            "latest": hs[-1] if hs else 0,
            "oldest": hs[0] if hs else 0,
            "disk_bytes": self.disk_bytes(),
        }
