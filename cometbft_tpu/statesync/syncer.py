"""Snapshot syncer (reference statesync/syncer.go:150,246,327,363).

Flow: collect snapshot advertisements -> pick best (highest height,
light-verified app hash) -> OfferSnapshot to app -> fetch + apply
chunks in order (refetch / sender-rejection honored) -> verify app
Info against the trusted app hash -> return the light-verified State
+ commit for store bootstrap."""

from __future__ import annotations

import asyncio
import random
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..abci import types as abci
from ..utils.backoff import Backoff
from ..utils.log import get_logger
from .chunks import ChunkQueue

_log = get_logger("statesync")

DISCOVERY_SLEEP_S = 0.3
# while the pool is EMPTY, re-broadcast the snapshot request this
# often: advertisements are one-shot per request, so after a rejected
# or timed-out snapshot attempt drains the pool, a syncer that never
# re-asks would idle out the whole discovery window even though its
# peers hold (by now newer) snapshots
REDISCOVERY_INTERVAL_S = 2.0
CHUNK_TIMEOUT_S = 10.0
MAX_CHUNK_FETCHERS = 4
# chunk-request retry backoff (utils/backoff.py full jitter): fast
# first retry, capped well under the chunk timeout so a flaky peer
# gets several tries before the whole snapshot attempt times out
RETRY_BACKOFF_BASE_S = 0.05
RETRY_BACKOFF_CAP_S = 2.0


class SyncError(Exception):
    pass


class SnapshotRejected(SyncError):
    pass


@dataclass(frozen=True)
class SnapshotKey:
    height: int
    format: int
    chunks: int
    hash: bytes


@dataclass
class SnapshotPool:
    """Advertised snapshots and which peers can serve them."""

    snapshots: Dict[SnapshotKey, Set[str]] = field(default_factory=dict)
    # every advertisement ever received (diagnostics: distinguishes
    # "nothing discovered" from "everything rejected")
    discovered_total: int = 0

    def add(self, peer_id: str, snap: abci.Snapshot) -> None:
        key = SnapshotKey(
            snap.height, snap.format, snap.chunks, bytes(snap.hash)
        )
        self.snapshots.setdefault(key, set()).add(peer_id)
        self.discovered_total += 1

    def remove_peer(self, peer_id: str) -> None:
        for peers in self.snapshots.values():
            peers.discard(peer_id)

    def reject(self, key: SnapshotKey) -> None:
        self.snapshots.pop(key, None)

    def best(self) -> Optional[Tuple[SnapshotKey, Set[str]]]:
        live = {
            k: p for k, p in self.snapshots.items() if p
        }
        if not live:
            return None
        key = max(live, key=lambda k: (k.height, k.format))
        return key, live[key]


class Syncer:
    def __init__(
        self,
        proxy,  # AppConns (snapshot + query)
        state_provider,
        request_chunk: Callable,  # async (peer_id, height, format, index) -> Optional[bytes]
        discovery_time_s: float = 5.0,
        chunk_timeout_s: float = CHUNK_TIMEOUT_S,
        rng: Optional[random.Random] = None,
        request_snapshots: Optional[Callable] = None,  # () -> None
    ):
        self.proxy = proxy
        self.provider = state_provider
        self.request_chunk = request_chunk
        self.request_snapshots = request_snapshots
        self.pool = SnapshotPool()
        self.discovery_time_s = discovery_time_s
        self.chunk_timeout_s = chunk_timeout_s
        self.banned_snapshots: Set[bytes] = set()
        # peers that served corrupt/unappliable chunks (the app said
        # RETRY on their chunk, or named them in reject_senders):
        # banned for the rest of THIS sync — mirrors the blocksync
        # pool's peer bans, and like them survives reconnect churn
        self.banned_peers: Set[str] = set()
        self._rng = rng or random.Random()

    # --- entry --------------------------------------------------------

    async def sync_any(self):
        """Try snapshots until one applies. Returns (state, commit)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.discovery_time_s
        last_request = loop.time()  # the caller just broadcast one
        while True:
            pick = self.pool.best()
            if pick is None:
                now = loop.time()
                if now > deadline:
                    raise SyncError(
                        "no viable snapshots discovered in time "
                        f"(advertisements={self.pool.discovered_total}"
                        f", rejected={len(self.banned_snapshots)})"
                    )
                if (
                    self.request_snapshots is not None
                    and now - last_request >= REDISCOVERY_INTERVAL_S
                ):
                    # re-ask: a rejected/timed-out attempt drained the
                    # pool; peers hold (by now newer) snapshots
                    last_request = now
                    self.request_snapshots()
                await asyncio.sleep(DISCOVERY_SLEEP_S)
                continue
            key, peers = pick
            if key.hash in self.banned_snapshots:
                self.pool.reject(key)
                continue
            try:
                result = await self._sync_one(key, peers)
                # shared-verification accounting (light/serving.py):
                # how much of the light-verified restore rode the
                # cross-client header cache vs was verified fresh —
                # the "joining node shares work with light sessions"
                # story made auditable per sync
                stats_fn = getattr(self.provider, "cache_stats", None)
                if stats_fn is not None:
                    try:
                        _log.info(
                            "light-verified restore complete",
                            height=key.height,
                            **stats_fn(),
                        )
                    except Exception:
                        pass
                return result
            except SnapshotRejected as e:
                # logged: a run that ends in "no viable snapshots"
                # after REJECTING offers is a different failure than
                # one that never discovered any — the error text alone
                # cannot tell them apart
                _log.error(
                    "snapshot rejected",
                    height=key.height,
                    err=repr(e),
                )
                self.banned_snapshots.add(key.hash)
                self.pool.reject(key)
            except asyncio.TimeoutError:
                _log.error(
                    "snapshot attempt timed out", height=key.height
                )
                self.pool.reject(key)

    async def _sync_one(self, key: SnapshotKey, peers: Set[str]):
        # light-verify the app hash BEFORE trusting anything the
        # snapshot claims (reference syncer.go:246 Sync)
        app_hash = await asyncio.to_thread(
            self.provider.app_hash, key.height
        )
        snap = abci.Snapshot(
            height=key.height,
            format=key.format,
            chunks=key.chunks,
            hash=key.hash,
        )
        resp = self.proxy.snapshot.offer_snapshot(snap, app_hash)
        if resp.result != abci.OFFER_SNAPSHOT_ACCEPT:
            if resp.result == abci.OFFER_SNAPSHOT_ABORT:
                raise SyncError("app aborted snapshot restore")
            raise SnapshotRejected(f"app rejected snapshot ({resp.result})")

        queue = ChunkQueue(key.chunks)
        fetchers = [
            asyncio.create_task(
                self._fetch_routine(queue, key, list(peers))
            )
            for _ in range(min(MAX_CHUNK_FETCHERS, max(1, len(peers))))
        ]
        try:
            while not queue.done():
                index, chunk, sender = await queue.next(
                    self.chunk_timeout_s
                )
                r = self.proxy.snapshot.apply_snapshot_chunk(
                    index, chunk, sender
                )
                if r.result == abci.APPLY_CHUNK_ACCEPT:
                    # marked BEFORE directives: a reject_senders ban
                    # in the same response must not rewind the chunk
                    # the app just accepted
                    queue.mark_applied(index)
                # app-directed punishment/refetch rides ANY verdict
                # (reference syncer.go:363): a chunk can apply while
                # the app still fingers earlier senders as corrupt
                self._apply_directives(queue, r)
                if r.result == abci.APPLY_CHUNK_ACCEPT:
                    continue
                if r.result == abci.APPLY_CHUNK_RETRY:
                    # the sender served a chunk the app could not
                    # apply: ban it for this sync (all its queued
                    # chunks are suspect too) and refetch elsewhere
                    self._ban_sender(queue, sender, "chunk retry")
                    queue.discard(index)
                    continue
                if r.result in (
                    abci.APPLY_CHUNK_REJECT_SNAPSHOT,
                    abci.APPLY_CHUNK_RETRY_SNAPSHOT,
                ):
                    raise SnapshotRejected("app rejected chunk set")
                raise SyncError(f"chunk apply aborted ({r.result})")
        finally:
            for f in fetchers:
                f.cancel()

        # verify the app landed exactly where the light client says
        info = self.proxy.query.info(abci.RequestInfo())
        if info.last_block_height != key.height:
            raise SnapshotRejected(
                f"app restored to height {info.last_block_height}, "
                f"snapshot was {key.height}"
            )
        if bytes(info.last_block_app_hash) != bytes(app_hash):
            raise SnapshotRejected("app hash mismatch after restore")

        state = await asyncio.to_thread(self.provider.state, key.height)
        commit = await asyncio.to_thread(
            self.provider.commit, key.height
        )
        return state, commit

    def _ban_sender(
        self, queue: ChunkQueue, sender: str, why: str
    ) -> None:
        if not sender or sender in self.banned_peers:
            return
        self.banned_peers.add(sender)
        dropped = queue.discard_sender(sender)
        _log.info(
            "statesync: banned peer serving corrupt chunks",
            peer=sender[:12],
            why=why,
            chunks_discarded=len(dropped),
        )

    def _apply_directives(
        self, queue: ChunkQueue, r: abci.ResponseApplySnapshotChunk
    ) -> None:
        """Honor the app's refetch_chunks / reject_senders fields."""
        for sender in r.reject_senders or ():
            self._ban_sender(queue, sender, "reject_senders")
        for idx in r.refetch_chunks or ():
            queue.discard(idx)

    async def _fetch_routine(
        self, queue: ChunkQueue, key: SnapshotKey, peers: List[str]
    ) -> None:
        i = 0
        # full-jitter exponential backoff per fetcher: a flaky peer
        # retries fast at first, and a thundering re-request herd
        # after a shared failure spreads out (utils/backoff.py)
        backoff = Backoff(
            base_s=RETRY_BACKOFF_BASE_S,
            cap_s=RETRY_BACKOFF_CAP_S,
            rng=self._rng,
        )
        try:
            while not queue.done():
                alive = [
                    p for p in peers if p not in self.banned_peers
                ]
                if not alive:
                    # every peer of this snapshot is banned: nothing
                    # can complete it — let the apply loop time out
                    # and reject the snapshot
                    return
                wanted = sorted(queue.wanted() - set(queue.chunks))
                if not wanted:
                    await asyncio.sleep(0.05)
                    continue
                index = wanted[i % len(wanted)]
                i += 1
                peer = alive[index % len(alive)]
                try:
                    chunk = await asyncio.wait_for(
                        self.request_chunk(
                            peer, key.height, key.format, index
                        ),
                        self.chunk_timeout_s,
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    await asyncio.sleep(backoff.next_delay())
                    continue
                if chunk is not None:
                    backoff.reset()
                    queue.add(index, chunk, peer)
                else:
                    # peer answered "don't have it": back off before
                    # asking the rotation again
                    await asyncio.sleep(backoff.next_delay())
        except asyncio.CancelledError:
            raise
