"""Chunk queue (reference statesync/chunks.go): ordered delivery of
snapshot chunks to the app, with refetch support."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Set


class ChunkQueue:
    def __init__(self, total: int):
        self.total = total
        self.chunks: Dict[int, bytes] = {}
        self.senders: Dict[int, str] = {}
        self.next_index = 0
        # indexes the APP ACCEPTED (syncer marks them): a sender ban
        # must not rewind these — re-applying an accepted chunk the
        # app never asked to refetch corrupts append-style restores
        self.applied: set = set()
        self._available = asyncio.Event()

    def wanted(self) -> Set[int]:
        return {
            i for i in range(self.total) if i not in self.chunks
        }

    def add(self, index: int, chunk: bytes, sender: str) -> bool:
        if index < 0 or index >= self.total or index in self.chunks:
            return False
        self.chunks[index] = chunk
        self.senders[index] = sender
        if index == self.next_index:
            self._available.set()
        return True

    def discard(self, index: int) -> None:
        """App asked for a refetch of this chunk."""
        self.chunks.pop(index, None)
        self.senders.pop(index, None)
        # an explicit refetch of an accepted chunk re-applies it
        self.applied.discard(index)
        if index <= self.next_index:
            self.next_index = min(self.next_index, index)
            self._available.clear()

    def mark_applied(self, index: int) -> None:
        """The app accepted this chunk (syncer calls on ACCEPT)."""
        self.applied.add(index)

    def discard_sender(self, sender: str) -> list:
        """Drop every UNAPPLIED queued chunk served by ``sender`` (it
        just got banned for serving corrupt data — everything it
        delivered and the app has not yet accepted is suspect,
        reference chunks.go DiscardSender). Chunks the app already
        ACCEPTED stay: re-applying them unasked would corrupt
        append-style restores; the app can still name them via
        ``refetch_chunks`` explicitly. Returns the discarded
        indexes."""
        dropped = [
            i
            for i, s in list(self.senders.items())
            if s == sender and i not in self.applied
        ]
        for i in dropped:
            self.discard(i)
        return dropped

    async def next(self, timeout: float = 10.0):
        """(index, chunk, sender) in strict order."""
        while self.next_index not in self.chunks:
            self._available.clear()
            await asyncio.wait_for(self._available.wait(), timeout)
        i = self.next_index
        self.next_index += 1
        if self.next_index in self.chunks:
            self._available.set()
        return i, self.chunks[i], self.senders.get(i, "")

    def done(self) -> bool:
        return self.next_index >= self.total
