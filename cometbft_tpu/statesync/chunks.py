"""Chunk queue (reference statesync/chunks.go): ordered delivery of
snapshot chunks to the app, with refetch support."""

from __future__ import annotations

import asyncio
from typing import Dict, Optional, Set


class ChunkQueue:
    def __init__(self, total: int):
        self.total = total
        self.chunks: Dict[int, bytes] = {}
        self.senders: Dict[int, str] = {}
        self.next_index = 0
        self._available = asyncio.Event()

    def wanted(self) -> Set[int]:
        return {
            i for i in range(self.total) if i not in self.chunks
        }

    def add(self, index: int, chunk: bytes, sender: str) -> bool:
        if index < 0 or index >= self.total or index in self.chunks:
            return False
        self.chunks[index] = chunk
        self.senders[index] = sender
        if index == self.next_index:
            self._available.set()
        return True

    def discard(self, index: int) -> None:
        """App asked for a refetch of this chunk."""
        self.chunks.pop(index, None)
        self.senders.pop(index, None)
        if index <= self.next_index:
            self.next_index = min(self.next_index, index)
            self._available.clear()

    async def next(self, timeout: float = 10.0):
        """(index, chunk, sender) in strict order."""
        while self.next_index not in self.chunks:
            self._available.clear()
            await asyncio.wait_for(self._available.wait(), timeout)
        i = self.next_index
        self.next_index += 1
        if self.next_index in self.chunks:
            self._available.set()
        return i, self.chunks[i], self.senders.get(i, "")

    def done(self) -> bool:
        return self.next_index >= self.total
