"""Statesync: bootstrap a fresh node from application snapshots,
light-client verified (reference statesync/)."""

from .reactor import StateSyncReactor
from .stateprovider import LightClientStateProvider
from .syncer import SyncError, Syncer

__all__ = [
    "StateSyncReactor",
    "Syncer",
    "SyncError",
    "LightClientStateProvider",
]
