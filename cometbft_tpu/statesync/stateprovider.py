"""Light-client-backed state provider (reference
statesync/stateprovider.go:48-125 lightClientStateProvider).

Builds the post-snapshot State entirely from light-verified headers:
the app hash OF height h lives in header h+1, validator sets come from
the verified valset chain, and the commit for h proves the header. All
fetches ride the light client, so a statesyncing node trusts only its
configured (height, hash) root."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from .. import types as T
from ..light import Client, TrustOptions
from ..light.http_provider import HTTPProvider
from ..state.state_types import State


class LightClientStateProvider:
    def __init__(
        self,
        chain_id: str,
        rpc_servers: List[str],
        trust_height: int,
        trust_hash: bytes,
        trust_period_ns: int,
        genesis=None,
        header_cache=None,
        signature_cache=None,
        verify_engine=None,
    ):
        if not rpc_servers:
            raise ValueError("statesync requires at least one RPC server")
        self.chain_id = chain_id
        self.genesis = genesis
        self.primary = HTTPProvider(chain_id, rpc_servers[0])
        self.witnesses = [
            HTTPProvider(chain_id, s) for s in rpc_servers[1:]
        ]
        # shared serving seams (light/serving.py, ROADMAP item 3):
        # a joining node is the ready-made first consumer of the
        # cross-client VerifiedHeaderCache — heights that concurrent
        # light sessions (or an earlier sync attempt) already verified
        # restore without re-paying commit verification, and what THIS
        # sync verifies is published for them (after cross-check)
        self.header_cache = header_cache
        self.client = Client(
            chain_id,
            TrustOptions(
                period_ns=trust_period_ns,
                height=trust_height,
                hash=trust_hash,
            ),
            primary=self.primary,
            witnesses=self.witnesses,
            signature_cache=signature_cache,
            header_cache=header_cache,
            verify_engine=verify_engine,
            # statesync restore is bulk catch-up work: it must never
            # preempt a live round sharing the verify scheduler
            priority=T.PRIORITY_CATCHUP,
        )

    def cache_stats(self) -> dict:
        """Shared-verification observability for the syncer's log."""
        out = {"bisection_hops": self.client.hops}
        if self.header_cache is not None:
            out.update(self.header_cache.stats())
        return out

    def app_hash(self, height: int) -> bytes:
        """App hash AFTER executing block `height` (header h+1)."""
        return self.client.verify_light_block_at_height(
            height + 1
        ).header.app_hash

    def commit(self, height: int) -> T.Commit:
        return self.client.verify_light_block_at_height(height).commit

    def state(self, height: int) -> State:
        """State as of height h, ready for ApplyBlock(h+1)."""
        cur = self.client.verify_light_block_at_height(height)
        nxt = self.client.verify_light_block_at_height(height + 1)
        prev = (
            self.client.verify_light_block_at_height(height - 1)
            if height > 1
            else None
        )
        initial_height = (
            self.genesis.initial_height if self.genesis else 1
        )
        params = (
            self.genesis.consensus_params
            if self.genesis is not None
            else State().consensus_params
        )
        return State(
            chain_id=self.chain_id,
            initial_height=initial_height,
            last_block_height=cur.height,
            last_block_id=nxt.header.last_block_id,
            last_block_time_ns=cur.header.time_ns,
            validators=cur.validator_set,
            next_validators=nxt.validator_set,
            last_validators=prev.validator_set if prev else None,
            # earliest height whose valset this bootstrapped node holds
            # as a FULL record (Store.bootstrap writes h..h+2 full):
            # later pointer records must reference a stored-full height
            last_height_validators_changed=cur.height + 2,
            consensus_params=params,
            last_height_consensus_params_changed=0,
            last_results_hash=nxt.header.last_results_hash,
            app_hash=nxt.header.app_hash,
        )

    def close(self) -> None:
        self.primary.close()
        for w in self.witnesses:
            w.close()
