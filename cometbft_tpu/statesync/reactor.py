"""Statesync reactor: snapshot/chunk wire protocol on channels
0x60/0x61 (reference statesync/reactor.go:21-23) + the node-side sync
entrypoint that bootstraps the stores (reference node/setup.go:560
performStateSync)."""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, Optional

from ..abci import types as abci
from ..p2p.node_info import ChannelDescriptor
from ..p2p.reactor import Reactor
from ..utils import proto
from ..utils.tasks import spawn
from .syncer import Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

MSG_SNAPSHOTS_REQUEST = 0x01
MSG_SNAPSHOTS_RESPONSE = 0x02
MSG_CHUNK_REQUEST = 0x03
MSG_CHUNK_RESPONSE = 0x04

MAX_ADVERTISED_SNAPSHOTS = 10


def _encode_snapshot(s: abci.Snapshot) -> bytes:
    return (
        proto.field_varint(1, s.height)
        + proto.field_varint(2, s.format)
        + proto.field_varint(3, s.chunks)
        + proto.field_bytes(4, s.hash)
        + proto.field_bytes(5, s.metadata)
    )


def _decode_snapshot(b: bytes) -> abci.Snapshot:
    m = proto.parse(b)
    return abci.Snapshot(
        height=proto.get1(m, 1, 0),
        format=proto.get1(m, 2, 0),
        chunks=proto.get1(m, 3, 0),
        hash=proto.get1(m, 4, b""),
        metadata=proto.get1(m, 5, b""),
    )


class StateSyncReactor(Reactor):
    name = "statesync"

    def __init__(self, proxy, enabled: bool = False):
        super().__init__()
        self.proxy = proxy  # AppConns (serves snapshots to peers)
        self.enabled = enabled
        self.syncer: Optional[Syncer] = None
        # pending chunk requests: (peer, height, format, index) -> fut
        self._pending: Dict[tuple, asyncio.Future] = {}
        # retention plane handle (store/retention.py): while a chunk
        # for height H streams to a joiner, H is pinned against
        # pruning (the in-flight-serve floor); None = no plane
        self.retention = None

    def get_channels(self):
        return [
            ChannelDescriptor(SNAPSHOT_CHANNEL, priority=5, max_msg_size=1 << 20),
            ChannelDescriptor(CHUNK_CHANNEL, priority=3, max_msg_size=1 << 22),
        ]

    # --- node-side sync entrypoint --------------------------------------

    async def sync(
        self,
        state_provider,
        state_store,
        block_store,
        discovery_time_s: float = 5.0,
    ):
        """Discover + restore a snapshot, bootstrap the stores, return
        the new State (reference syncer.SyncAny + node bootstrap)."""
        self.syncer = Syncer(
            self.proxy,
            state_provider,
            request_chunk=self._request_chunk,
            discovery_time_s=discovery_time_s,
            # the syncer re-broadcasts while its pool is empty (a
            # rejected/timed-out snapshot must not idle out the whole
            # discovery window when peers hold newer snapshots)
            request_snapshots=self._broadcast_request,
        )
        # ask everyone we know for their snapshots
        self._broadcast_request()
        try:
            state, commit = await self.syncer.sync_any()
        finally:
            # resolve every in-flight chunk wait on the way out
            # (success, failure or CANCELLATION): an abandoned
            # `await fut` in _request_chunk would otherwise hold its
            # fetcher task alive forever — the leaked-task wedge a
            # cancelled chaos scenario exposed in asyncio.run cleanup
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_result(None)
        state_store.bootstrap(state)
        block_store.save_seen_commit(state.last_block_height, commit)
        return state

    def _broadcast_request(self) -> None:
        self.switch.broadcast(
            SNAPSHOT_CHANNEL, bytes([MSG_SNAPSHOTS_REQUEST])
        )

    async def _request_chunk(self, peer_id, height, format_, index):
        peer = self.switch.peers.get(peer_id)
        if peer is None:
            return None
        key = (peer_id, height, format_, index)
        fut = asyncio.get_running_loop().create_future()
        self._pending[key] = fut
        try:
            await peer.send(
                CHUNK_CHANNEL,
                bytes([MSG_CHUNK_REQUEST])
                + struct.pack(">qii", height, format_, index),
            )
            return await fut
        finally:
            self._pending.pop(key, None)

    # --- peers ----------------------------------------------------------

    def add_peer(self, peer) -> None:
        if self.enabled and self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, bytes([MSG_SNAPSHOTS_REQUEST]))

    def remove_peer(self, peer, reason) -> None:
        if self.syncer is not None:
            self.syncer.pool.remove_peer(peer.peer_id)

    # --- wire -----------------------------------------------------------

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        mtype = msg[0]
        body = msg[1:]
        if mtype == MSG_SNAPSHOTS_REQUEST:
            # serving hits the app's snapshot store (disk): off-loop
            # (bftlint ASY108 — receive must never run an ABCI call)
            spawn(
                self._serve_snapshots(peer),
                name="statesync-serve-snapshots",
            )
        elif mtype == MSG_SNAPSHOTS_RESPONSE:
            if self.syncer is not None:
                self.syncer.pool.add(peer.peer_id, _decode_snapshot(body))
        elif mtype == MSG_CHUNK_REQUEST:
            height, format_, index = struct.unpack(">qii", body)
            spawn(
                self._serve_chunk(peer, height, format_, index),
                name="statesync-serve-chunk",
            )
        elif mtype == MSG_CHUNK_RESPONSE:
            height, format_, index, ok = struct.unpack(">qii?", body[:17])
            chunk = body[17:] if ok else None
            fut = self._pending.get((peer.peer_id, height, format_, index))
            if fut is not None and not fut.done():
                fut.set_result(chunk)
        else:
            raise ValueError(f"unknown statesync msg type {mtype}")

    async def _serve_snapshots(self, peer) -> None:
        snaps = await asyncio.to_thread(
            self.proxy.snapshot.list_snapshots
        )
        for snap in (snaps or [])[-MAX_ADVERTISED_SNAPSHOTS:]:
            peer.try_send(
                SNAPSHOT_CHANNEL,
                bytes([MSG_SNAPSHOTS_RESPONSE]) + _encode_snapshot(snap),
            )

    async def _serve_chunk(
        self, peer, height: int, format_: int, index: int
    ) -> None:
        def _load() -> Optional[bytes]:
            ret = self.retention
            if ret is not None:
                # pin the height for the duration of the load: the
                # retention plane must not prune a snapshot a joiner
                # is mid-download on (store/retention.py serve floor)
                with ret.serving(height):
                    return self.proxy.snapshot.load_snapshot_chunk(
                        height, format_, index
                    )
            return self.proxy.snapshot.load_snapshot_chunk(
                height, format_, index
            )

        chunk = await asyncio.to_thread(_load)
        peer.try_send(
            CHUNK_CHANNEL,
            bytes([MSG_CHUNK_RESPONSE])
            + struct.pack(">qii?", height, format_, index, bool(chunk))
            + (chunk or b""),
        )
