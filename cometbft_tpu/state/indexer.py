"""Tx + block event indexing (reference state/txindex/kv/kv.go,
state/indexer/block/kv, and the event-driven IndexerService at
state/txindex/indexer_service.go:29).

KV layout (order-preserving big-endian heights for prefix scans):
  tx:h:<hash>                  -> record(height, index, tx, result)
  tx:a:<key>=<value>:<height8>:<index4> -> tx hash   (attribute index)
  blk:e:<key>=<value>:<height8>         -> b""       (block events)
Search evaluates the pubsub query against the attribute index;
height conditions constrain the scan range."""

from __future__ import annotations

import hashlib
import struct
import threading
from typing import List, Optional, Tuple

from ..abci import types as abci
from ..types import events as ev
from ..utils import kv, proto
from ..utils.pubsub_query import Query


def _enc_record(height: int, index: int, tx: bytes, result) -> bytes:
    from .execution import encode_finalize_response  # noqa: F401

    res_b = _enc_tx_result(result)
    return (
        proto.field_varint(1, height)
        + proto.field_varint(2, index + 1)
        + proto.field_bytes(3, tx)
        + proto.field_bytes(4, res_b)
    )


def _enc_tx_result(r) -> bytes:
    out = (
        proto.field_varint(1, r.code)
        + proto.field_bytes(2, r.data)
        + proto.field_string(3, r.log)
        + proto.field_varint(4, r.gas_wanted)
        + proto.field_varint(5, r.gas_used)
    )
    for e in r.events:
        attrs = b""
        for a in e.attributes:
            k, v, idx = abci.attr_kvi(a)
            attrs += proto.field_bytes(
                2,
                proto.field_string(1, k)
                + proto.field_string(2, v)
                + proto.field_varint(3, 1 if idx else 0),
            )
        out += proto.field_bytes(6, proto.field_string(1, e.type_) + attrs)
    return out


def _dec_tx_result(b: bytes) -> abci.ExecTxResult:
    m = proto.parse(b)
    events = []
    for eb in m.get(6, []):
        em = proto.parse(eb)
        attrs = []
        for ab in em.get(2, []):
            am = proto.parse(ab)
            attrs.append(
                abci.EventAttribute(
                    key=proto.get1(am, 1, b"").decode(),
                    value=proto.get1(am, 2, b"").decode(),
                    index=bool(proto.get1(am, 3, 0)),
                )
            )
        events.append(
            abci.Event(
                type_=proto.get1(em, 1, b"").decode(), attributes=attrs
            )
        )
    return abci.ExecTxResult(
        code=proto.get1(m, 1, 0),
        data=proto.get1(m, 2, b""),
        log=proto.get1(m, 3, b"").decode(),
        gas_wanted=proto.get1(m, 4, 0),
        gas_used=proto.get1(m, 5, 0),
        events=events,
    )


def _attr_key(key: str, value: str, height: int, index: int) -> bytes:
    return (
        b"tx:a:"
        + key.encode()
        + b"="
        + value.encode()
        + b":"
        + struct.pack(">Q", height)
        + struct.pack(">I", index)
    )


class TxIndexer:
    """Indexes txs by hash + event attributes."""

    def __init__(self, db: kv.KV):
        self.db = db
        self._lock = threading.Lock()

    def index_tx(
        self, height: int, index: int, tx: bytes, result: abci.ExecTxResult
    ) -> None:
        h = hashlib.sha256(tx).digest()
        sets = [(b"tx:h:" + h, _enc_record(height, index, tx, result))]
        # implicit attributes (reference: tx.height is always indexed)
        sets.append((_attr_key("tx.height", str(height), height, index), h))
        for e in result.events:
            for a in e.attributes:
                k, v, idx = abci.attr_kvi(a)
                if not idx:
                    continue
                sets.append(
                    (_attr_key(f"{e.type_}.{k}", v, height, index), h)
                )
        with self._lock:
            self.db.write_batch(sets)

    def get(self, tx_hash: bytes):
        raw = self.db.get(b"tx:h:" + tx_hash)
        if raw is None:
            return None
        m = proto.parse(raw)
        return (
            proto.get1(m, 1, 0),
            proto.get1(m, 2, 1) - 1,
            proto.get1(m, 3, b""),
            _dec_tx_result(proto.get1(m, 4, b"")),
        )

    def search(self, q: Query) -> List[Tuple]:
        """Returns [(height, index, tx, result, hash)] matching ALL
        conditions, height/index ordered."""
        # special case: tx.hash = '...' is a point lookup
        for c in q.conditions:
            if c.key == "tx.hash" and c.op == "=":
                h = bytes.fromhex(str(c.value))
                rec = self.get(h)
                return [rec + (h,)] if rec else []
        candidate_hashes: Optional[set] = None
        scans = 0
        for c in q.conditions:
            matches = set()
            if c.op == "=":
                prefix = (
                    b"tx:a:"
                    + c.key.encode()
                    + b"="
                    + self._valstr(c.value).encode()
                    + b":"
                )
                for k, v in self.db.iter_prefix(prefix):
                    matches.add(bytes(v))
            elif c.op == "CONTAINS":
                prefix = b"tx:a:" + c.key.encode() + b"="
                for k, v in self.db.iter_prefix(prefix):
                    # substring-match only the VALUE portion of the
                    # key (tail = value ':' height(8) index(4))
                    if str(c.value).encode() in k[len(prefix):-13]:
                        matches.add(bytes(v))
            else:  # range ops incl. EXISTS: scan the key's entries
                prefix = b"tx:a:" + c.key.encode() + b"="
                for k, v in self.db.iter_prefix(prefix):
                    if c.op == "EXISTS":
                        matches.add(bytes(v))
                        continue
                    try:
                        # key tail = <value> ':' height(8) index(4)
                        val = float(k[len(prefix):-13])
                    except ValueError:
                        continue
                    if (
                        (c.op == "<" and val < c.value)
                        or (c.op == ">" and val > c.value)
                        or (c.op == "<=" and val <= c.value)
                        or (c.op == ">=" and val >= c.value)
                    ):
                        matches.add(bytes(v))
            scans += 1
            candidate_hashes = (
                matches
                if candidate_hashes is None
                else candidate_hashes & matches
            )
            if not candidate_hashes:
                return []
        out = []
        for h in candidate_hashes or ():
            rec = self.get(h)
            if rec:
                out.append(rec + (h,))
        out.sort(key=lambda r: (r[0], r[1]))
        return out

    @staticmethod
    def _valstr(v) -> str:
        if isinstance(v, float) and v == int(v):
            return str(int(v))
        return str(v)


class BlockIndexer:
    """Indexes block-level events by height (reference
    state/indexer/block/kv)."""

    def __init__(self, db: kv.KV):
        self.db = db

    def index_block(self, height: int, events: List[abci.Event]) -> None:
        sets = [
            (
                b"blk:e:block.height="
                + str(height).encode()
                + b":"
                + struct.pack(">Q", height),
                b"",
            )
        ]
        for e in events:
            for a in e.attributes:
                k, v, idx = abci.attr_kvi(a)
                if not idx:
                    continue
                sets.append(
                    (
                        b"blk:e:"
                        + f"{e.type_}.{k}={v}".encode()
                        + b":"
                        + struct.pack(">Q", height),
                        b"",
                    )
                )
        self.db.write_batch(sets)

    def search(self, q: Query) -> List[int]:
        heights: Optional[set] = None
        for c in q.conditions:
            matches = set()
            if c.op == "=":
                prefix = (
                    b"blk:e:"
                    + c.key.encode()
                    + b"="
                    + TxIndexer._valstr(c.value).encode()
                    + b":"
                )
                for k, _ in self.db.iter_prefix(prefix):
                    matches.add(struct.unpack(">Q", k[-8:])[0])
            else:
                prefix = b"blk:e:" + c.key.encode() + b"="
                for k, _ in self.db.iter_prefix(prefix):
                    h = struct.unpack(">Q", k[-8:])[0]
                    if c.op == "EXISTS":
                        matches.add(h)
                        continue
                    try:
                        val = float(k[len(prefix):-9])
                    except ValueError:
                        continue
                    if (
                        (c.op == "<" and val < c.value)
                        or (c.op == ">" and val > c.value)
                        or (c.op == "<=" and val <= c.value)
                        or (c.op == ">=" and val >= c.value)
                    ):
                        matches.add(h)
            heights = matches if heights is None else heights & matches
            if not heights:
                return []
        return sorted(heights or ())


class IndexerService:
    """Event-bus-driven indexing (reference
    state/txindex/indexer_service.go:29,43)."""

    def __init__(self, tx_indexer: TxIndexer, block_indexer: BlockIndexer, event_bus):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.bus = event_bus

    def start(self) -> None:
        self.bus.add_sync_listener(self._on_event)

    def _on_event(self, e: ev.Event) -> None:
        if e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
            self.tx_indexer.index_tx(
                e.data["height"], e.data["index"], e.data["tx"], e.data["result"]
            )
        elif e.type_ == ev.EVENT_NEW_BLOCK and isinstance(e.data, dict):
            blk = e.data["block"]
            self.block_indexer.index_block(
                blk.height, e.data.get("result_events") or []
            )
