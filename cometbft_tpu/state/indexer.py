"""Tx + block event indexing (reference state/txindex/kv/kv.go,
state/indexer/block/kv, and the event-driven IndexerService at
state/txindex/indexer_service.go:29).

KV layout (order-preserving big-endian heights for prefix scans):
  tx:h:<hash>                  -> record(height, index, tx, result)
  tx:a:<key>=<value>:<height8>:<index4> -> tx hash   (attribute index)
  blk:e:<key>=<value>:<height8>         -> b""       (block events)
  idx:last                     -> height8 (last FULLY indexed height)
Search evaluates the pubsub query against the attribute index;
height conditions constrain the scan range.

ISSUE 15 (outbound fan-out plane): ``IndexerService`` no longer
writes the DB inside the bus publish — the sync listener only
ACCUMULATES a height's tx + block events in memory and, once the
height is complete, hands the bundle to a bounded async drain that
flushes everything (rows + the ``idx:last`` marker) in ONE
``db.write_batch`` per height off the consensus hot path. The marker
rides the same atomic batch, so a crash leaves it pointing at the
last fully indexed height and ``replay()`` re-indexes forward
idempotently (keys are deterministic — a re-run overwrites identical
rows, never duplicates them)."""

from __future__ import annotations

import asyncio
import hashlib
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..abci import types as abci
from ..obs.queues import InstrumentedQueue
from ..trace import NOOP as TRACE_NOOP
from ..types import events as ev
from ..utils import kv, proto
from ..utils.pubsub_query import Query
from ..utils.tasks import spawn

# last fully indexed height, written ATOMICALLY with that height's
# rows (crash consistency: the marker can never run ahead of rows,
# and rows without the marker are re-written identically on replay)
LAST_INDEXED_KEY = b"idx:last"
# first retained indexed height (exclusive floor: every row BELOW it
# is pruned), mirroring idx:last's contiguity discipline from the
# other end — the marker advances ATOMICALLY with the delete batch
# that clears everything below it (store/retention.py), so a crash
# mid-prune leaves base <= the true first retained row and a re-prune
# resumes idempotently: no gap, no orphan rows above the marker
INDEX_BASE_KEY = b"idx:base"


def _enc_height(h: int) -> bytes:
    return struct.pack(">Q", h)


def _dec_height(b: Optional[bytes]) -> int:
    return struct.unpack(">Q", b)[0] if b else 0


def _enc_record(
    height: int, index: int, tx: bytes, result, events_enc=None
) -> bytes:
    from .execution import encode_finalize_response  # noqa: F401

    res_b = _enc_tx_result(result, events_enc)
    return (
        proto.field_varint(1, height)
        + proto.field_varint(2, index + 1)
        + proto.field_bytes(3, tx)
        + proto.field_bytes(4, res_b)
    )


def _enc_tx_result(r, events_enc=None) -> bytes:
    out = (
        proto.field_varint(1, r.code)
        + proto.field_bytes(2, r.data)
        + proto.field_string(3, r.log)
        + proto.field_varint(4, r.gas_wanted)
        + proto.field_varint(5, r.gas_used)
    )
    if events_enc is not None:
        # the field-6 payload is byte-identical to the finalize lane's
        # encoded-event bytes (state/native_finalize.py) — reuse them
        # instead of re-walking the attributes
        for eb in events_enc:
            out += proto.field_bytes(6, eb)
        return out
    for e in r.events:
        attrs = b""
        for a in e.attributes:
            k, v, idx = abci.attr_kvi(a)
            attrs += proto.field_bytes(
                2,
                proto.field_string(1, k)
                + proto.field_string(2, v)
                + proto.field_varint(3, 1 if idx else 0),
            )
        out += proto.field_bytes(6, proto.field_string(1, e.type_) + attrs)
    return out


def _dec_tx_result(b: bytes) -> abci.ExecTxResult:
    m = proto.parse(b)
    events = []
    for eb in m.get(6, []):
        em = proto.parse(eb)
        attrs = []
        for ab in em.get(2, []):
            am = proto.parse(ab)
            attrs.append(
                abci.EventAttribute(
                    key=proto.get1(am, 1, b"").decode(),
                    value=proto.get1(am, 2, b"").decode(),
                    index=bool(proto.get1(am, 3, 0)),
                )
            )
        events.append(
            abci.Event(
                type_=proto.get1(em, 1, b"").decode(), attributes=attrs
            )
        )
    return abci.ExecTxResult(
        code=proto.get1(m, 1, 0),
        data=proto.get1(m, 2, b""),
        log=proto.get1(m, 3, b"").decode(),
        gas_wanted=proto.get1(m, 4, 0),
        gas_used=proto.get1(m, 5, 0),
        events=events,
    )


def _attr_key(key: str, value: str, height: int, index: int) -> bytes:
    return (
        b"tx:a:"
        + key.encode()
        + b"="
        + value.encode()
        + b":"
        + struct.pack(">Q", height)
        + struct.pack(">I", index)
    )


class TxIndexer:
    """Indexes txs by hash + event attributes."""

    def __init__(self, db: kv.KV):
        self.db = db
        self._lock = threading.Lock()

    def tx_sets(
        self,
        height: int,
        index: int,
        tx: bytes,
        result: abci.ExecTxResult,
        tx_hash: Optional[bytes] = None,
        events_flat=None,
        events_enc=None,
    ) -> List[Tuple[bytes, bytes]]:
        """The (key, value) rows for one tx — pure, deterministic:
        re-running on the same inputs produces byte-identical rows,
        which is what makes crash replay idempotent.

        ``tx_hash``/``events_flat``/``events_enc`` are the finalize
        lane's precomputed forms (state/native_finalize.py) — byte-
        identical to deriving them here, just not re-derived."""
        h = tx_hash if tx_hash is not None else hashlib.sha256(tx).digest()
        sets = [
            (b"tx:h:" + h, _enc_record(height, index, tx, result, events_enc))
        ]
        # implicit attributes (reference: tx.height is always indexed)
        sets.append((_attr_key("tx.height", str(height), height, index), h))
        if events_flat is not None:
            for type_, kvis in events_flat:
                for k, v, idx in kvis:
                    if not idx:
                        continue
                    sets.append(
                        (_attr_key(f"{type_}.{k}", v, height, index), h)
                    )
            return sets
        for e in result.events:
            for a in e.attributes:
                k, v, idx = abci.attr_kvi(a)
                if not idx:
                    continue
                sets.append(
                    (_attr_key(f"{e.type_}.{k}", v, height, index), h)
                )
        return sets

    def index_tx(
        self, height: int, index: int, tx: bytes, result: abci.ExecTxResult
    ) -> None:
        with self._lock:
            self.db.write_batch(self.tx_sets(height, index, tx, result))

    def last_indexed_height(self) -> int:
        """The crash-consistency marker (``idx:last``): every height
        <= this is FULLY indexed (rows + marker land in one atomic
        batch per height)."""
        return _dec_height(self.db.get(LAST_INDEXED_KEY))

    def base_height(self) -> int:
        """The prune floor (``idx:base``): every row below this
        height is pruned; 0 = nothing ever pruned."""
        return _dec_height(self.db.get(INDEX_BASE_KEY))

    def prune_deletes(self, retain_height: int) -> List[bytes]:
        """Keys of every tx row below ``retain_height`` — pure scan,
        no writes. The ``tx:h:<hash>`` rows are reached through the
        implicit ``tx.height`` attribute rows (every indexed tx has
        one — tx_sets appends it unconditionally), so this never
        parses record values."""
        deletes: List[bytes] = []
        hash_prefix = b"tx:a:tx.height="
        for k, v in self.db.iter_prefix(b"tx:a:"):
            # key tail = <value> ':' height(8) index(4)
            h = struct.unpack(">Q", k[-12:-4])[0]
            if h >= retain_height:
                continue
            deletes.append(k)
            if k.startswith(hash_prefix):
                deletes.append(b"tx:h:" + bytes(v))
        return deletes

    def prune(self, retain_height: int) -> int:
        """Delete tx rows below ``retain_height`` and advance
        ``idx:base`` in the SAME atomic batch; returns keys deleted.
        Prefer ``prune_index`` (module level) when a BlockIndexer
        shares this db — it covers both row families under one
        marker advance."""
        if retain_height <= self.base_height():
            return 0
        deletes = self.prune_deletes(retain_height)
        with self._lock:
            self.db.write_batch(
                [(INDEX_BASE_KEY, _enc_height(retain_height))], deletes
            )
        return len(deletes)

    def get(self, tx_hash: bytes):
        raw = self.db.get(b"tx:h:" + tx_hash)
        if raw is None:
            return None
        m = proto.parse(raw)
        return (
            proto.get1(m, 1, 0),
            proto.get1(m, 2, 1) - 1,
            proto.get1(m, 3, b""),
            _dec_tx_result(proto.get1(m, 4, b"")),
        )

    def search(self, q: Query) -> List[Tuple]:
        """Returns [(height, index, tx, result, hash)] matching ALL
        conditions, height/index ordered."""
        # special case: tx.hash = '...' is a point lookup
        for c in q.conditions:
            if c.key == "tx.hash" and c.op == "=":
                h = bytes.fromhex(str(c.value))
                rec = self.get(h)
                return [rec + (h,)] if rec else []
        candidate_hashes: Optional[set] = None
        scans = 0
        for c in q.conditions:
            matches = set()
            if c.op == "=":
                prefix = (
                    b"tx:a:"
                    + c.key.encode()
                    + b"="
                    + self._valstr(c.value).encode()
                    + b":"
                )
                for k, v in self.db.iter_prefix(prefix):
                    matches.add(bytes(v))
            elif c.op == "CONTAINS":
                prefix = b"tx:a:" + c.key.encode() + b"="
                for k, v in self.db.iter_prefix(prefix):
                    # substring-match only the VALUE portion of the
                    # key (tail = value ':' height(8) index(4))
                    if str(c.value).encode() in k[len(prefix):-13]:
                        matches.add(bytes(v))
            else:  # range ops incl. EXISTS: scan the key's entries
                prefix = b"tx:a:" + c.key.encode() + b"="
                for k, v in self.db.iter_prefix(prefix):
                    if c.op == "EXISTS":
                        matches.add(bytes(v))
                        continue
                    try:
                        # key tail = <value> ':' height(8) index(4)
                        val = float(k[len(prefix):-13])
                    except ValueError:
                        continue
                    if (
                        (c.op == "<" and val < c.value)
                        or (c.op == ">" and val > c.value)
                        or (c.op == "<=" and val <= c.value)
                        or (c.op == ">=" and val >= c.value)
                    ):
                        matches.add(bytes(v))
            scans += 1
            candidate_hashes = (
                matches
                if candidate_hashes is None
                else candidate_hashes & matches
            )
            if not candidate_hashes:
                return []
        out = []
        for h in candidate_hashes or ():
            rec = self.get(h)
            if rec:
                out.append(rec + (h,))
        out.sort(key=lambda r: (r[0], r[1]))
        return out

    @staticmethod
    def _valstr(v) -> str:
        if isinstance(v, float) and v == int(v):
            return str(int(v))
        return str(v)


class BlockIndexer:
    """Indexes block-level events by height (reference
    state/indexer/block/kv)."""

    def __init__(self, db: kv.KV):
        self.db = db

    def block_sets(
        self, height: int, events: List[abci.Event], events_flat=None
    ) -> List[Tuple[bytes, bytes]]:
        """Pure (key, value) rows for one block's events (same
        idempotency contract as TxIndexer.tx_sets). ``events_flat``
        is the finalize lane's once-flattened form when available."""
        sets = [
            (
                b"blk:e:block.height="
                + str(height).encode()
                + b":"
                + struct.pack(">Q", height),
                b"",
            )
        ]
        if events_flat is not None:
            for type_, kvis in events_flat:
                for k, v, idx in kvis:
                    if not idx:
                        continue
                    sets.append(
                        (
                            b"blk:e:"
                            + f"{type_}.{k}={v}".encode()
                            + b":"
                            + struct.pack(">Q", height),
                            b"",
                        )
                    )
            return sets
        for e in events:
            for a in e.attributes:
                k, v, idx = abci.attr_kvi(a)
                if not idx:
                    continue
                sets.append(
                    (
                        b"blk:e:"
                        + f"{e.type_}.{k}={v}".encode()
                        + b":"
                        + struct.pack(">Q", height),
                        b"",
                    )
                )
        return sets

    def index_block(self, height: int, events: List[abci.Event]) -> None:
        self.db.write_batch(self.block_sets(height, events))

    def prune_deletes(self, retain_height: int) -> List[bytes]:
        """Keys of every block-event row below ``retain_height`` —
        pure scan, no writes (height is the key's last 8 bytes)."""
        return [
            k
            for k, _ in self.db.iter_prefix(b"blk:e:")
            if struct.unpack(">Q", k[-8:])[0] < retain_height
        ]

    def prune(self, retain_height: int) -> int:
        """Delete block-event rows below ``retain_height`` and
        advance ``idx:base`` atomically with them; returns keys
        deleted. Prefer ``prune_index`` when a TxIndexer shares this
        db (one marker advance covering both row families)."""
        if retain_height <= _dec_height(self.db.get(INDEX_BASE_KEY)):
            return 0
        deletes = self.prune_deletes(retain_height)
        self.db.write_batch(
            [(INDEX_BASE_KEY, _enc_height(retain_height))], deletes
        )
        return len(deletes)

    def search(self, q: Query) -> List[int]:
        heights: Optional[set] = None
        for c in q.conditions:
            matches = set()
            if c.op == "=":
                prefix = (
                    b"blk:e:"
                    + c.key.encode()
                    + b"="
                    + TxIndexer._valstr(c.value).encode()
                    + b":"
                )
                for k, _ in self.db.iter_prefix(prefix):
                    matches.add(struct.unpack(">Q", k[-8:])[0])
            else:
                prefix = b"blk:e:" + c.key.encode() + b"="
                for k, _ in self.db.iter_prefix(prefix):
                    h = struct.unpack(">Q", k[-8:])[0]
                    if c.op == "EXISTS":
                        matches.add(h)
                        continue
                    try:
                        val = float(k[len(prefix):-9])
                    except ValueError:
                        continue
                    if (
                        (c.op == "<" and val < c.value)
                        or (c.op == ">" and val > c.value)
                        or (c.op == "<=" and val <= c.value)
                        or (c.op == ">=" and val >= c.value)
                    ):
                        matches.add(h)
            heights = matches if heights is None else heights & matches
            if not heights:
                return []
        return sorted(heights or ())


def prune_index(
    tx_indexer: TxIndexer,
    block_indexer: BlockIndexer,
    retain_height: int,
) -> int:
    """Prune BOTH indexers' rows below ``retain_height`` in ONE
    atomic batch carrying the ``idx:base`` advance — the retention
    plane's path (store/retention.py). Crash-safe by construction:
    the marker lands with (never before) the deletes it covers, so a
    crash mid-prune leaves either the old base (deletes retried
    idempotently) or the new base with every covered row gone — no
    gap, no orphan rows. Requires both indexers on the same kv db
    (the node wiring guarantees it; IndexerService checks the same).
    Returns keys deleted."""
    db = tx_indexer.db
    assert getattr(block_indexer, "db", None) is db
    if retain_height <= tx_indexer.base_height():
        return 0
    deletes = tx_indexer.prune_deletes(retain_height)
    deletes += block_indexer.prune_deletes(retain_height)
    with tx_indexer._lock:
        db.write_batch(
            [(INDEX_BASE_KEY, _enc_height(retain_height))], deletes
        )
    return len(deletes)


class HeightBundle:
    """Everything one height needs indexed, sealed once complete.

    ``extras`` maps tx index -> (tx_hash, events_flat, events_enc)
    from the finalize lane's one pass (state/native_finalize.py);
    ``block_events_flat`` is the once-flattened block-event form.
    Both are optional — bundles built by replay or tests lack them
    and the flush derives everything itself, byte-identically."""

    __slots__ = ("height", "txs", "block_events", "extras",
                 "block_events_flat")

    def __init__(
        self,
        height: int,
        txs: list,
        block_events: list,
        extras: Optional[dict] = None,
        block_events_flat=None,
    ):
        self.height = height
        self.txs = txs  # [(index, tx_bytes, ExecTxResult)]
        self.block_events = block_events
        self.extras = extras
        self.block_events_flat = block_events_flat


class IndexerService:
    """Event-bus-driven indexing (reference
    state/txindex/indexer_service.go:29,43) with per-height batched,
    off-hot-path flushing (ISSUE 15).

    The sync listener is now PURE ACCUMULATION: ``EVENT_NEW_BLOCK``
    opens a height bundle (block events + expected tx count from the
    block itself), each ``EVENT_TX`` appends, and the bundle seals
    when the last tx of the height lands — all in-memory, no DB work
    inside ``bus.publish`` (bftlint ASY116 exists to keep it that
    way). Sealed bundles flush from a bounded async drain
    (``start_async``), ONE ``db.write_batch`` per height carrying the
    rows AND the ``idx:last`` marker; without a running loop (CLI
    reindex, sync tests) sealing flushes inline — still one batch per
    height, the pre-ISSUE-15 consistency semantics.

    ``barrier()`` gives RPC index queries read-your-writes over the
    async drain; ``replay()`` closes the crash hole: on restart every
    height past the marker is re-indexed from the stored blocks +
    finalize responses, idempotently."""

    # a drain this deep means indexing itself is the bottleneck; the
    # overflow path flushes off-loop without queueing (never drops)
    QUEUE_SIZE = 256

    def __init__(
        self, tx_indexer: TxIndexer, block_indexer: BlockIndexer, event_bus
    ):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.bus = event_bus
        self.tracer = TRACE_NOOP
        # one atomic batch per height requires both indexers on the
        # SAME kv db (the node wiring); the psql sink (no .db) keeps
        # its per-item API, still moved off the publish path
        db = getattr(tx_indexer, "db", None)
        self._kv_db = (
            db
            if db is not None
            and getattr(block_indexer, "db", None) is db
            and hasattr(tx_indexer, "tx_sets")
            and hasattr(block_indexer, "block_sets")
            else None
        )
        self._pending: Dict[int, dict] = {}
        self._plock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queue: InstrumentedQueue = InstrumentedQueue(
            self.QUEUE_SIZE, name="state.index"
        )
        self._task = None
        self._inflight = 0
        self.sealed_heights = 0
        self.flushed_heights = 0
        self.flush_failures = 0
        self.replayed_heights = 0
        # flushed-but-not-yet-marker-covered heights (out-of-order
        # flushes via the overflow path): the idx:last marker only
        # advances CONTIGUOUSLY, so a crash can never skip a height
        # that was still queued in memory
        self._done_heights: set = set()
        # in-flight overflow-path flushes: stop() must await them or
        # a graceful stop races Node._shutdown's store close and
        # loses the height's rows until the next restart's replay
        self._overflow_tasks: set = set()
        # first height ever sealed live in this process: heights
        # below it can only land via replay()'s anchored walk, so it
        # floors the contiguity check — without it a statesync-
        # restored joiner (marker 0, live heights starting at
        # snapshot+1, the gap pruned) would park every height in
        # _done_heights forever and never advance the marker
        self._first_sealed: Optional[int] = None

    # --- lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Attach the accumulator (build time, loop not required).

        ASY116-sanctioned: the accumulator's only blocking reach is
        the no-running-loop inline degrade in _seal (CLI tools / sync
        embedders — no loop exists to stall in that mode); with a
        loop, sealing hands the bundle to the bounded async drain."""
        self.bus.add_sync_listener(self._on_event)  # bftlint: disable=ASY116 — listener only degrades inline when NO loop is running (CLI embedders)

    async def start_async(self, block_store=None, state_store=None) -> None:
        """Upgrade to the async drain (Node.start): replay any
        crash gap first, then flush sealed bundles off-loop."""
        if block_store is not None and state_store is not None:
            await asyncio.to_thread(self.replay, block_store, state_store)
        self._loop = asyncio.get_running_loop()
        if self._task is None:
            self._task = spawn(self._drain(), name="indexer-flush")

    async def stop(self) -> None:
        """Bounded stop (ASY110): reap the drain, then flush whatever
        was still queued synchronously — a graceful stop loses no
        index rows (a crash is what replay() is for)."""
        t, self._task = self._task, None
        self._loop = None
        if t is not None:
            t.cancel()
            try:
                await asyncio.wait_for(
                    asyncio.gather(t, return_exceptions=True), 2.0
                )
            except asyncio.TimeoutError:
                pass
        while not self._queue.empty():
            await asyncio.to_thread(self._flush, self._queue.get_nowait())
        # overflow-path flushes still in flight write to the same db
        # Node._shutdown is about to close — await them (bounded)
        pending = [t for t in self._overflow_tasks if not t.done()]
        if pending:
            try:
                await asyncio.wait_for(
                    asyncio.gather(*pending, return_exceptions=True), 5.0
                )
            except asyncio.TimeoutError:
                pass

    # --- accumulation (sync listener: in-memory only) ------------------

    def _on_event(self, e: ev.Event) -> None:
        bundle = None
        if e.type_ == ev.EVENT_NEW_BLOCK and isinstance(e.data, dict):
            blk = e.data["block"]
            with self._plock:
                p = self._pending.setdefault(
                    blk.height,
                    {"txs": [], "events": [], "expected": None,
                     "extras": {}, "events_flat": None},
                )
                p["events"] = list(e.data.get("result_events") or [])
                p["events_flat"] = e.data.get("events_flat")
                p["expected"] = len(blk.data.txs)
                bundle = self._maybe_seal_locked(blk.height)
        elif e.type_ == ev.EVENT_TX and isinstance(e.data, dict):
            d = e.data
            with self._plock:
                p = self._pending.setdefault(
                    d["height"],
                    {"txs": [], "events": [], "expected": None,
                     "extras": {}, "events_flat": None},
                )
                p["txs"].append((d["index"], d["tx"], d["result"]))
                if "tx_hash" in d:
                    # the finalize lane's precomputed forms ride the
                    # event data as optional keys (state/execution.py
                    # _fire_events); keyed by index so the sort at
                    # seal time can't misalign them
                    p["extras"][d["index"]] = (
                        d["tx_hash"],
                        d.get("events_flat"),
                        d.get("events_enc"),
                    )
                bundle = self._maybe_seal_locked(d["height"])
        if bundle is not None:
            self._seal(bundle)

    def _maybe_seal_locked(self, height: int) -> Optional[HeightBundle]:
        p = self._pending.get(height)
        if p is None or p["expected"] is None:
            return None
        if len(p["txs"]) < p["expected"]:
            return None
        self._pending.pop(height, None)
        # bound the accumulator: anything older than the sealed
        # height can never complete (its NEW_BLOCK already passed)
        for h in [h for h in self._pending if h < height]:
            self._pending.pop(h, None)
        return HeightBundle(
            height,
            sorted(p["txs"], key=lambda t: t[0]),
            p["events"],
            extras=p.get("extras") or None,
            block_events_flat=p.get("events_flat"),
        )

    def _seal(self, bundle: HeightBundle) -> None:
        self.sealed_heights += 1
        if self._first_sealed is None:
            self._first_sealed = bundle.height
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(self._offer, bundle)
            return
        # no drain running (build-time commits, CLI tools, sync
        # tests): flush inline — one batch per height, and there is
        # no event loop in this mode to stall (the sanctioned reach
        # behind start()'s ASY116 suppression)
        self._flush(bundle)

    def _offer(self, bundle: HeightBundle) -> None:
        try:
            self._queue.put_nowait(bundle)
        except asyncio.QueueFull:
            # overflow of last resort: never drop index rows — flush
            # off-loop immediately (ordering is safe: flushes
            # serialize on _flush_lock and the marker is monotonic)
            self._queue.count_drop()
            t = spawn(
                self._overflow_flush(bundle),
                name="indexer-overflow-flush",
            )
            self._overflow_tasks.add(t)
            t.add_done_callback(self._overflow_tasks.discard)

    async def _overflow_flush(self, bundle: "HeightBundle") -> None:
        try:
            await asyncio.to_thread(self._flush, bundle)
        except asyncio.CancelledError:
            raise
        except Exception:
            # same accounting as _drain: a failed flush must land in
            # the ledger or barrier() burns its full timeout on every
            # index query for the rest of the process
            self.flush_failures += 1
            import traceback

            traceback.print_exc()

    # --- flushing -----------------------------------------------------

    async def _drain(self) -> None:
        while True:
            bundle = await self._queue.get()
            self._inflight += 1
            try:
                await asyncio.to_thread(self._flush, bundle)
            except asyncio.CancelledError:
                raise
            except Exception:
                # one transient DB failure (locked sqlite, disk
                # hiccup) must not kill the drain for the rest of the
                # process — the height stays unmarked, so a restart's
                # replay() re-indexes it; counted so barrier() does
                # not burn its timeout on a height that will not land
                self.flush_failures += 1
                import traceback

                traceback.print_exc()
            finally:
                self._inflight -= 1

    def _flush(self, bundle: HeightBundle, anchored: bool = False) -> None:
        """ONE write_batch per height: every tx row, every block
        event row and the idx:last marker, atomically."""
        with self._flush_lock:
            span = self.tracer.span(
                "fanout.index.flush",
                height=bundle.height,
                n_txs=len(bundle.txs),
            )
            with span:
                if self._kv_db is not None:
                    sets: List[Tuple[bytes, bytes]] = []
                    extras = bundle.extras or {}
                    for i, tx, res in bundle.txs:
                        th, efl, een = extras.get(i) or (None, None, None)
                        sets.extend(
                            self.tx_indexer.tx_sets(
                                bundle.height, i, tx, res,
                                tx_hash=th,
                                events_flat=efl,
                                events_enc=een,
                            )
                        )
                    sets.extend(
                        self.block_indexer.block_sets(
                            bundle.height,
                            bundle.block_events,
                            events_flat=bundle.block_events_flat,
                        )
                    )
                    # marker advances CONTIGUOUSLY only: an
                    # out-of-order flush (overflow path) parks its
                    # height in _done_heights until the gap below it
                    # lands — "every height <= marker is FULLY
                    # indexed" must survive a crash with older
                    # bundles still queued in memory. ``anchored``
                    # (replay: ascending from a floor below which
                    # nothing exists/is unindexed) may jump directly.
                    prev = self.tx_indexer.last_indexed_height()
                    if anchored:
                        marker = max(prev, bundle.height)
                    else:
                        self._done_heights.add(bundle.height)
                        marker = prev
                        # anchor at the first live-sealed height:
                        # anything below it can only arrive via
                        # replay()'s anchored walk, never through
                        # this path — a joiner whose history is
                        # pruned must not wait on it (same rule as
                        # reindex-event's below-base jump)
                        first = self._first_sealed
                        if first is not None and first - 1 > marker:
                            marker = first - 1
                        while marker + 1 in self._done_heights:
                            marker += 1
                            self._done_heights.discard(marker)
                    if marker > prev:
                        sets.append(
                            (LAST_INDEXED_KEY, _enc_height(marker))
                        )
                    self._done_heights -= {
                        h for h in self._done_heights if h <= marker
                    }
                    self._kv_db.write_batch(sets)
                else:
                    # sink indexers (psql): per-item API, but off the
                    # publish path now
                    for i, tx, res in bundle.txs:
                        self.tx_indexer.index_tx(bundle.height, i, tx, res)
                    self.block_indexer.index_block(
                        bundle.height, bundle.block_events
                    )
            self.flushed_heights += 1

    async def barrier(self, timeout_s: float = 5.0) -> None:
        """Wait (bounded) until every height sealed so far has
        flushed: read-your-writes for index queries racing a commit.
        Counter-based (sealed vs flushed), so the window between a
        seal and its bundle landing on the queue can't slip through."""
        if self._loop is None:
            return  # inline mode is always consistent
        target = self.sealed_heights
        deadline = asyncio.get_running_loop().time() + timeout_s
        while (
            self.flushed_heights + self.flush_failures < target
            and asyncio.get_running_loop().time() < deadline
        ):
            await asyncio.sleep(0.005)

    # --- crash replay -------------------------------------------------

    def replay(self, block_store, state_store) -> int:
        """Re-index every height past the idx:last marker from the
        stored blocks + finalize responses (which persist tx AND
        block events since ISSUE 15, state/execution.py). Idempotent:
        deterministic keys mean a partially-written height (crash
        between rows... impossible — batch is atomic — but also a
        marker behind a re-run) just overwrites identical rows."""
        if self._kv_db is None:
            return 0
        from .execution import decode_finalize_response

        last = self.tx_indexer.last_indexed_height()
        top = block_store.height()
        n = 0
        for h in range(max(last + 1, block_store.base()), top + 1):
            blk = block_store.load_block(h)
            raw = state_store.load_finalize_block_response(h)
            if blk is None or raw is None:
                continue
            resp = decode_finalize_response(raw)
            txs = [
                (i, tx, resp.tx_results[i])
                for i, tx in enumerate(blk.data.txs)
                if i < len(resp.tx_results)
            ]
            self.sealed_heights += 1  # keep the barrier's
            # sealed-vs-flushed ledger balanced across replay.
            # anchored: replay walks ascending from a floor below
            # which every height is indexed or absent from the store,
            # so the marker may jump straight to h (a pruned store's
            # base > marker+1 would otherwise park it forever)
            self._flush(HeightBundle(h, txs, resp.events), anchored=True)
            n += 1
        self.replayed_heights += n
        return n

    def queue_stats(self) -> dict:
        """obs registry entry (state.index): the bounded drain's
        backlog; ``dropped`` counts overflow-path flushes (work moved
        off the queue, never lost)."""
        s = self._queue.stats()
        s["flushed_heights"] = self.flushed_heights
        return s
