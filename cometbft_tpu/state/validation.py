"""Block validation against state (reference state/validation.go).

The LastCommit signature check routes through the TPU batch verifier
(types.verify_commit — reference state/validation.go:101-103), with the
fork's last-validated-block cache + block-time tolerance handled by the
executor (reference state/execution.go:44-52).
"""

from __future__ import annotations

from typing import Optional

from .. import types as T
from .state_types import State


def validate_block(
    state: State,
    block: T.Block,
    cache: Optional[T.SignatureCache] = None,
    skip_commit_check: bool = False,
    priority: Optional[int] = None,
) -> None:
    """skip_commit_check: blocksync verified LastCommit already via the
    coalesced batch path (reference blocksync SkipLastCommit flag).
    ``priority``: verify-scheduler class for the LastCommit check —
    the live consensus executor passes PRIORITY_LIVE; replay paths
    default to catch-up."""
    block.validate_basic()
    h = block.header
    if h.chain_id != state.chain_id:
        raise ValueError(f"wrong chain id {h.chain_id}")
    if h.height != state.last_block_height + 1:
        raise ValueError(
            f"wrong height {h.height}, expected {state.last_block_height + 1}"
        )
    if h.last_block_id.key() != state.last_block_id.key():
        raise ValueError("wrong LastBlockID")
    if h.validators_hash != state.validators.hash():
        raise ValueError("wrong ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise ValueError("wrong NextValidatorsHash")
    if h.consensus_hash != state.consensus_params.hash():
        raise ValueError("wrong ConsensusHash")
    if h.app_hash != state.app_hash:
        raise ValueError("wrong AppHash")
    if h.last_results_hash != state.last_results_hash:
        raise ValueError("wrong LastResultsHash")
    if not state.validators.has_address(h.proposer_address):
        raise ValueError("proposer not in validator set")

    # LastCommit: [HOT] batch signature verification on TPU lanes
    if h.height == state.initial_height:
        if block.last_commit is not None and block.last_commit.size() > 0:
            raise ValueError("initial block cannot have LastCommit")
    else:
        if block.last_commit is None:
            raise ValueError("missing LastCommit")
        if block.last_commit.size() != state.last_validators.size():
            raise ValueError("wrong LastCommit size")
        if not skip_commit_check:
            T.verify_commit(
                state.chain_id,
                state.last_validators,
                state.last_block_id,
                h.height - 1,
                block.last_commit,
                cache=cache,
                priority=priority,
            )

    # evidence
    for ev in block.evidence:
        ev.validate_basic()
