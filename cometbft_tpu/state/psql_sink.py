"""PostgreSQL event sink (reference state/indexer/sink/psql).

Streams tx results and block events into relational tables so external
systems can query them with SQL — the reference's psql sink is
write-only (searches still go to the kv indexer or the database
directly; state/indexer/sink/psql/psql.go returns errors for Search*).
Gated on psycopg2 availability exactly as the reference gates on the
postgres conn string: selecting `indexer = "psql"` without the driver
(or without `psql_conn`) fails loudly at node construction.

Schema (created on first connect, mirroring the reference's
schema.sql): blocks(height, chain_id, created_at), tx_results(height,
index, tx_hash, tx_bytes, result, created_at), events(height, tx_hash
nullable, type), attributes(event_id, key, composite_key, value).
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Optional

from ..abci import types as abci

SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
    rowid      BIGSERIAL PRIMARY KEY,
    height     BIGINT NOT NULL,
    chain_id   VARCHAR NOT NULL,
    created_at TIMESTAMPTZ NOT NULL,
    UNIQUE (height, chain_id)
);
CREATE TABLE IF NOT EXISTS tx_results (
    rowid      BIGSERIAL PRIMARY KEY,
    block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
    index      INTEGER NOT NULL,
    created_at TIMESTAMPTZ NOT NULL,
    tx_hash    VARCHAR NOT NULL,
    tx_result  BYTEA NOT NULL,
    UNIQUE (block_id, index)
);
CREATE TABLE IF NOT EXISTS events (
    rowid    BIGSERIAL PRIMARY KEY,
    block_id BIGINT NOT NULL REFERENCES blocks(rowid),
    tx_id    BIGINT NULL REFERENCES tx_results(rowid),
    type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
    event_id      BIGINT NOT NULL REFERENCES events(rowid),
    key           VARCHAR NOT NULL,
    composite_key VARCHAR NOT NULL,
    value         VARCHAR NULL,
    UNIQUE (event_id, key)
);
"""


def available() -> bool:
    try:
        import psycopg2  # noqa: F401

        return True
    except ImportError:
        return False


class PsqlSink:
    """Write-only event sink; interface-compatible with the kv
    indexers where IndexerService needs it (index_tx / index_block).

    Writes run on a dedicated worker thread: IndexerService listeners
    fire synchronously on the node's event loop, and a remote/slow
    Postgres must not stall the commit path (the kv indexer's local
    writes are bounded; network round-trips are not)."""

    def __init__(self, conn_str: str, chain_id: str):
        if not available():
            raise RuntimeError(
                "indexer = 'psql' requires psycopg2 (not installed)"
            )
        if not conn_str:
            raise ValueError("psql indexer requires a connection string")
        import psycopg2

        self.chain_id = chain_id
        self._conn = psycopg2.connect(conn_str)
        with self._conn, self._conn.cursor() as cur:
            cur.execute(SCHEMA)
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=10_000)
        self._worker = threading.Thread(
            target=self._drain, daemon=True, name="psql-sink"
        )
        self._worker.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                fn, args = item
                try:
                    fn(*args)
                except Exception:
                    import traceback

                    traceback.print_exc()
            finally:
                # task_done AFTER the write commits: flush() uses
                # q.join(), so emptiness of the queue alone must not
                # signal completion (the in-flight item counts)
                self._q.task_done()

    def flush(self) -> None:
        """Block until every queued write has committed."""
        self._q.join()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._worker.join(timeout=5.0)
        self._conn.close()

    def _block_rowid(self, cur, height: int) -> int:
        cur.execute(
            "INSERT INTO blocks (height, chain_id, created_at) "
            "VALUES (%s, %s, NOW()) "
            "ON CONFLICT (height, chain_id) DO UPDATE SET height = "
            "EXCLUDED.height RETURNING rowid",
            (height, self.chain_id),
        )
        return cur.fetchone()[0]

    def _insert_events(
        self, cur, block_id: int, tx_id: Optional[int], events
    ) -> None:
        for e in events:
            cur.execute(
                "INSERT INTO events (block_id, tx_id, type) "
                "VALUES (%s, %s, %s) RETURNING rowid",
                (block_id, tx_id, e.type_),
            )
            eid = cur.fetchone()[0]
            for a in e.attributes:
                k, val, _idx = abci.attr_kvi(a)
                cur.execute(
                    "INSERT INTO attributes "
                    "(event_id, key, composite_key, value) "
                    "VALUES (%s, %s, %s, %s) ON CONFLICT DO NOTHING",
                    (eid, k, f"{e.type_}.{k}", val),
                )

    def index_block(self, height: int, events: List[abci.Event]) -> None:
        self._q.put((self._index_block_sync, (height, events)))

    def _index_block_sync(self, height: int, events) -> None:
        with self._conn, self._conn.cursor() as cur:
            bid = self._block_rowid(cur, height)
            self._insert_events(cur, bid, None, events)

    def index_tx(
        self,
        height: int,
        index: int,
        tx: bytes,
        result: abci.ExecTxResult,
    ) -> None:
        self._q.put((self._index_tx_sync, (height, index, tx, result)))

    def _index_tx_sync(self, height, index, tx, result) -> None:
        from .indexer import _enc_tx_result

        with self._conn, self._conn.cursor() as cur:
            bid = self._block_rowid(cur, height)
            cur.execute(
                "INSERT INTO tx_results "
                "(block_id, index, created_at, tx_hash, tx_result) "
                "VALUES (%s, %s, NOW(), %s, %s) "
                "ON CONFLICT (block_id, index) DO UPDATE SET tx_hash = "
                "EXCLUDED.tx_hash RETURNING rowid",
                (
                    bid,
                    index,
                    hashlib.sha256(tx).hexdigest().upper(),
                    _enc_tx_result(result),
                ),
            )
            txid = cur.fetchone()[0]
            self._insert_events(cur, bid, txid, result.events)

    # the reference psql sink is write-only (psql.go Search* -> error)
    def get(self, tx_hash: bytes):
        raise NotImplementedError("psql sink does not support queries")

    def search(self, q):
        raise NotImplementedError("psql sink does not support queries")
