"""Native finalize lane (native/finalize.cpp): loader + portable twin.

One GIL-releasing call per block performs everything the finalize
data path hashes or encodes per-item in Python: per-tx SHA-256, the
``ExecTxResult`` encodes feeding ``LastResultsHash``, the RFC 6962
fold itself, and the ABCI event/attr encoding shared by the stored
finalize response, the indexer bundle and the fan-out payloads
(state/execution.py threads the :class:`FinalizeArtifacts` through
all three consumers — the events are FLATTENED ONCE here, never
re-walked per consumer).

Follows the wirecodec loader discipline exactly (utils/wirecodec.py,
PR 14): built on demand with g++ into ~/.cache/cometbft_tpu
(override with FINALIZE_SO_DIR), ``prewarm()`` kicks the one-time
build on a daemon thread from ``build_node`` so no event loop ever
pays the compile, ``module()`` never blocks a caller on an in-flight
build, and the portable pure-Python path below is byte-identical —
the semantic source of truth and the no-compiler fallback
(differential-tested in tests/test_native_finalize.py).
GRAFT_NATIVE_FINALIZE=0 disables.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sysconfig
import threading
from typing import List, Optional, Sequence, Tuple

from ..abci import types as abci
from ..utils import proto

_SRC = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native",
    "finalize.cpp",
)
_SO = os.path.join(
    os.environ.get(
        "FINALIZE_SO_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "cometbft_tpu"),
    ),
    "_finalize.so",
)

_mod = None
_tried = False
_lock = threading.Lock()


def prewarm():
    """Kick the one-time native build on a daemon thread so no event
    loop ever pays the compile (node/inprocess.build_node calls this
    right next to the wirecodec prewarm). Free once built."""
    if _tried:
        return None
    t = threading.Thread(
        target=module, name="finalize-prewarm", daemon=True
    )
    t.start()
    return t


def module():
    """The extension module, or None (no compiler / disabled).

    Loop-safe by construction (the wirecodec contract): while another
    thread is mid-build the lock acquire is NON-blocking and we
    return None for now — every caller keeps the portable path, and
    the next call after the build finishes gets the module."""
    global _mod, _tried
    if _tried:
        return _mod
    if not _lock.acquire(blocking=False):
        # a build is in flight elsewhere (usually the prewarm
        # thread): fall back rather than park this thread on a
        # multi-second g++ run
        return None
    try:
        if _tried:
            return _mod
        _tried = True
        if os.environ.get("GRAFT_NATIVE_FINALIZE") == "0":
            return None
        try:
            if (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                # one-time lazy native build; loop callers never park
                # here (non-blocking acquire above + build_node
                # prewarm thread) — sanctioned blocking sink
                subprocess.run(  # bftlint: disable=ASY114 — one-time lazy native build; loop callers never park here (non-blocking acquire + prewarm)
                    [
                        "g++",
                        "-O2",
                        "-std=c++17",
                        "-shared",
                        "-fPIC",
                        "-I",
                        sysconfig.get_paths()["include"],
                        _SRC,
                        "-o",
                        _SO,
                        "-ldl",  # sha256 one-shot dlopens libcrypto
                    ],
                    check=True,
                    capture_output=True,
                )
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_finalize", _SO
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:  # pragma: no cover - toolchain-dependent
            _mod = None
        return _mod
    finally:
        _lock.release()


# --- shared flattened form ---------------------------------------------
#
# FlatEvent = (type_str, [(key_str, value_str, index_bool), ...]).
# Built ONCE per event via abci.attr_kvi — the single flatten every
# downstream consumer (stored response, indexer rows, fan-out attrs)
# reads instead of re-walking Event.attributes itself.

FlatEvent = Tuple[str, List[Tuple[str, str, bool]]]


def flatten_events(events) -> List[FlatEvent]:
    """The one attr_kvi pass per event list."""
    return [
        (e.type_, [abci.attr_kvi(a) for a in e.attributes])
        for e in (events or [])
    ]


def encode_event_flat(fe: FlatEvent) -> bytes:
    """Portable ``_enc_abci_event`` over the flattened form —
    byte-identical to encoding the Event itself."""
    type_, kvis = fe
    out = proto.field_string(1, type_)
    for k, v, idx in kvis:
        out += proto.field_bytes(
            2,
            proto.field_string(1, k)
            + proto.field_string(2, v)
            + proto.field_varint(3, 1 if idx else 0),
        )
    return out


def encode_events_flat(flat: Sequence[FlatEvent]) -> List[bytes]:
    """Encoded-event bytes per flattened event; native when built."""
    nat = module()
    if nat is not None and flat:
        try:
            return nat.encode_events(
                [
                    (
                        t.encode(),
                        [(k.encode(), v.encode(), 1 if i else 0)
                         for k, v, i in kvis],
                    )
                    for t, kvis in flat
                ]
            )
        except Exception:  # pragma: no cover - defensive parity net
            pass
    return [encode_event_flat(fe) for fe in flat]


class FinalizeArtifacts:
    """Everything the finalize path derives from (txs, tx_results),
    computed once per block and threaded through the stored response,
    state update, event bus, indexer and fan-out:

    - ``tx_hashes[i]``       sha256(txs[i]) — EVENT_TX hash attr +
                             the indexer's ``tx:h:`` row key
    - ``results_enc[i]``     ``tx_results[i].encode()`` bytes, reused
                             by BOTH LastResultsHash and the stored
                             finalize response (encoded exactly once)
    - ``results_hash``       RFC 6962 root over ``results_enc``
    - ``tx_events_flat[i]``  flattened events of tx i (FlatEvent)
    - ``tx_events_enc[i]``   ``_enc_abci_event`` bytes per event of
                             tx i, shared by the stored response and
                             the indexer record rows
    - ``block_events_flat``/``block_events_enc`` — same pair for the
      block-level events
    """

    __slots__ = (
        "tx_hashes",
        "results_enc",
        "results_hash",
        "tx_events_flat",
        "tx_events_enc",
        "block_events_flat",
        "block_events_enc",
        "native",
    )

    def __init__(
        self,
        tx_hashes,
        results_enc,
        results_hash,
        tx_events_flat,
        tx_events_enc,
        block_events_flat,
        block_events_enc,
        native: bool,
    ):
        self.tx_hashes = tx_hashes
        self.results_enc = results_enc
        self.results_hash = results_hash
        self.tx_events_flat = tx_events_flat
        self.tx_events_enc = tx_events_enc
        self.block_events_flat = block_events_flat
        self.block_events_enc = block_events_enc
        self.native = native


def _portable_pass(txs, flat_results):
    """Byte-for-byte twin of the native finalize_pass (the semantic
    source of truth): sha256 per tx, ExecTxResult encode per result,
    binary-carry RFC 6962 fold, event encodes."""
    sha = hashlib.sha256
    tx_hashes = [sha(tx).digest() for tx in txs]
    results_enc = []
    tx_events_enc = []
    for code, data, gw, gu, codespace, flat in flat_results:
        results_enc.append(
            proto.field_varint(1, code)
            + proto.field_bytes(2, data)
            + proto.field_varint(5, gw)
            + proto.field_varint(6, gu)
            + proto.field_string(8, codespace)
        )
        tx_events_enc.append([encode_event_flat(fe) for fe in flat])
    from ..crypto import merkle

    res_hash = merkle.hash_from_byte_slices(results_enc)
    return tx_hashes, results_enc, res_hash, tx_events_enc


def finalize_pass(
    txs: Sequence[bytes], resp, portable: Optional[bool] = None
) -> FinalizeArtifacts:
    """The one pass per block. ``resp`` is the app's
    ResponseFinalizeBlock; ``portable=True`` forces the Python twin
    (differential tests and the parity leg of ``bench.py finalize``).

    The flatten itself (attr_kvi over every event) happens exactly
    once, HERE, regardless of backend — the artifacts carry the
    flattened form so no downstream consumer walks attributes again.
    """
    tx_events_flat = [flatten_events(r.events) for r in resp.tx_results]
    block_events_flat = flatten_events(resp.events)
    flat_results = [
        (r.code, r.data, r.gas_wanted, r.gas_used, r.codespace, flat)
        for r, flat in zip(resp.tx_results, tx_events_flat)
    ]
    nat = None if portable else module()
    native = False
    if nat is not None:
        try:
            tx_hashes, results_enc, res_hash, tx_events_enc = (
                nat.finalize_pass(
                    list(txs),
                    [
                        (
                            code,
                            data,
                            gw,
                            gu,
                            codespace.encode(),
                            [
                                (
                                    t.encode(),
                                    [
                                        (k.encode(), v.encode(),
                                         1 if i else 0)
                                        for k, v, i in kvis
                                    ],
                                )
                                for t, kvis in flat
                            ],
                        )
                        for code, data, gw, gu, codespace, flat
                        in flat_results
                    ],
                )
            )
            native = True
        except Exception:  # pragma: no cover - defensive parity net
            tx_hashes, results_enc, res_hash, tx_events_enc = (
                _portable_pass(txs, flat_results)
            )
    else:
        tx_hashes, results_enc, res_hash, tx_events_enc = _portable_pass(
            txs, flat_results
        )
    return FinalizeArtifacts(
        tx_hashes=tx_hashes,
        results_enc=results_enc,
        results_hash=res_hash,
        tx_events_flat=tx_events_flat,
        tx_events_enc=tx_events_enc,
        block_events_flat=block_events_flat,
        block_events_enc=encode_events_flat(block_events_flat)
        if not portable
        else [encode_event_flat(fe) for fe in block_events_flat],
        native=native,
    )


def part_leaf_hashes(chunks: Sequence[bytes]) -> Optional[List[bytes]]:
    """Native RFC 6962 leaf hashes for the proposal path's block-part
    chunks (sha256(0x00 || chunk) per part, GIL released), or None
    when the extension is unavailable — PartSet.from_data then hashes
    the leaves in Python via merkle.proofs_from_byte_slices."""
    nat = module()
    if nat is None:
        return None
    try:
        return nat.leaf_hashes(list(chunks))
    except Exception:  # pragma: no cover - defensive parity net
        return None
